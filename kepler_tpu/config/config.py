"""Application configuration: defaults < YAML file < CLI flags.

Reference parity: ``config/config.go`` — three-layer precedence where only
*explicitly passed* flags override the YAML file (``config.go:285-395``),
YAML loading with unknown-key detection, sanitization, validation with
skippable host/kube checks (``config.go:418-509``), and a mergo-style
fragment-merge builder for tests (``config/builder.go:34-57``).

Dev-only settings (fake meter) are YAML-only, never flags
(``config.go:104,189``).
"""

from __future__ import annotations

import argparse
import dataclasses
import io
import os
from dataclasses import dataclass, field
from typing import Any, Callable, IO, Mapping, Sequence

import yaml

from kepler_tpu.config.level import Level, parse_level


def _parse_duration(v: Any) -> float:
    """Parse a duration into seconds.

    Accepts numbers (seconds) or Go-style strings like "5s", "500ms", "1m30s"
    (the reference YAML uses Go duration syntax, e.g. ``monitor.interval: 5s``).
    """
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    if not isinstance(v, str):
        raise ValueError(f"invalid duration: {v!r}")
    s = v.strip()
    if not s:
        raise ValueError("empty duration")
    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}
    total = 0.0
    num = ""
    i = 0
    matched = False
    while i < len(s):
        c = s[i]
        if c.isdigit() or c in ".+-":
            num += c
            i += 1
            continue
        unit = ""
        while i < len(s) and s[i].isalpha():
            unit += s[i]
            i += 1
        if unit not in units or not num:
            raise ValueError(f"invalid duration: {v!r}")
        total += float(num) * units[unit]
        num = ""
        matched = True
    if num:  # trailing bare number, e.g. "5" → seconds
        total += float(num)
        matched = True
    if not matched:
        raise ValueError(f"invalid duration: {v!r}")
    return total


def format_duration(seconds: float) -> str:
    """Render seconds as a compact Go-style duration string."""
    if seconds >= 1:
        return f"{seconds:g}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:g}ms"
    return f"{seconds * 1e6:g}us"


# ---------------------------------------------------------------------------
# Config sections (reference config.go:21-108)
# ---------------------------------------------------------------------------


@dataclass
class LogConfig:
    level: str = "info"
    format: str = "text"  # text | json


@dataclass
class HostConfig:
    sysfs: str = "/sys"
    procfs: str = "/proc"


@dataclass
class RaplConfig:
    zones: list[str] = field(default_factory=list)  # empty = all zones


@dataclass
class MsrConfig:
    """MSR fallback meter (reference proposal EP-002). YAML-only — no CLI
    flags, so the security-sensitive backend can't be enabled by a stray
    argument (proposal §Configuration)."""

    enabled: bool = False  # opt-in: MSR reads are a PLATYPUS side channel
    force: bool = False  # use MSR even when powercap works (testing only)
    device_path: str = "/dev/cpu"


@dataclass
class MonitorConfig:
    interval: float = 5.0  # seconds (reference default 5s, config.go:207)
    staleness: float = 0.5  # seconds (reference default 500ms)
    # <0 unlimited, 0 disabled, >0 top-N by energy (config.go:51-56)
    max_terminated: int = 500
    # joules; only terminated workloads above this are tracked (config.go:58-63)
    min_terminated_energy_threshold: float = 10.0
    # watchdog: refresh-loop stall threshold; 0 = auto (3 × interval)
    stall_after: float = 0.0
    # counter-state persistence: with a path, the last raw counter
    # readings survive a restart so the first window attributes the
    # energy consumed across it ("" = off); a state file older than
    # state_max_age is ignored (a stale baseline would misattribute;
    # 0 = no freshness bound)
    state_path: str = ""
    state_max_age: float = 60.0


@dataclass
class StdoutExporterConfig:
    enabled: bool = False


@dataclass
class PrometheusExporterConfig:
    enabled: bool = True
    debug_collectors: list[str] = field(default_factory=lambda: ["go"])
    metrics_level: Level = Level.all()


@dataclass
class ExporterConfig:
    stdout: StdoutExporterConfig = field(default_factory=StdoutExporterConfig)
    prometheus: PrometheusExporterConfig = field(
        default_factory=PrometheusExporterConfig
    )


@dataclass
class PprofConfig:
    enabled: bool = False


@dataclass
class DebugConfig:
    pprof: PprofConfig = field(default_factory=PprofConfig)


@dataclass
class WebConfig:
    config_file: str = ""
    listen_addresses: list[str] = field(default_factory=lambda: [":28282"])
    # concurrent-connection cap per listener: an accept over the cap is
    # answered 503 + Connection: close WITHOUT spawning a handler
    # thread, so a connection storm (herd after a replica kill) can't
    # grow threads without bound. 0 = unbounded (pre-cap behavior).
    max_connections: int = 1024


@dataclass
class KubeConfig:
    enabled: bool = False
    config: str = ""  # kubeconfig path; empty = in-cluster
    node_name: str = ""


@dataclass
class FakeCpuMeterConfig:
    enabled: bool = False
    zones: list[str] = field(default_factory=list)


@dataclass
class TPUConfig:
    """TPU-specific settings — new in this framework (no reference analog).

    Controls where attribution math runs and how fleet batches are shaped.
    """

    platform: str = "auto"  # auto | tpu | cpu — jax platform for attribution
    # Pad workload axis to the next multiple of this to bound recompilation
    # (bucketed batch shapes; SURVEY §7 hard part (a)).
    workload_bucket: int = 256
    node_bucket: int = 8  # fleet aggregator node-axis bucket
    mesh_shape: list[int] = field(default_factory=list)  # [] = all devices, 1D
    mesh_axes: list[str] = field(default_factory=lambda: ["node"])
    # persistent XLA compilation cache dir ("" = off): bucket-crossing and
    # restart compiles become disk hits instead of fresh XLA runs
    compilation_cache_dir: str = ""
    # fleet attribution contraction: "einsum" (XLA-fused) | "pallas"
    # (hand-written Mosaic kernel, shard_map over the node axis)
    fleet_backend: str = "einsum"


@dataclass
class ServiceConfig:
    """Supervised service-group restarts (service.lifecycle.RestartPolicy).

    ``restart_max: 0`` (default) keeps the reference semantics: the first
    Runner crash ends the group. > 0 enables bounded restart-with-backoff
    per service.
    """

    restart_max: int = 0
    restart_backoff_initial: float = 0.5
    restart_backoff_max: float = 30.0


@dataclass
class FaultConfig:
    """Fault injection (``kepler_tpu.fault``) — YAML-only, like ``dev.*``:
    a chaos plan must be a deliberate config-file choice, never a stray
    CLI argument. ``specs`` is a list of mappings with a required ``site``
    plus optional probability/count/skip/start/duration/arg (see
    fault.plan.FaultSpec)."""

    enabled: bool = False
    seed: int = 0
    specs: list[Mapping[str, Any]] = field(default_factory=list)


@dataclass
class SpoolConfig:
    """Crash-safe report spool (``fleet.spool``): the agent's durable
    at-least-once delivery queue. Disabled unless ``dir`` is set."""

    dir: str = ""  # spool directory ("" = in-memory ring only)
    max_bytes: int = 64 << 20  # byte cap; oldest segment evicted beyond
    max_records: int = 4096  # record cap (counted, never silent)
    segment_bytes: int = 1 << 20  # rotation size (eviction granularity)
    # fsync policy: "batch" (default; at most one fsync per
    # fsync_interval — nothing per-send), "always", "none"
    fsync: str = "batch"
    fsync_interval: float = 1.0


@dataclass
class DrainConfig:
    """Spool-drain overload behavior (``fleet.agent`` batched replay +
    throttle handling, docs/developer/resilience.md "Overload and
    backpressure")."""

    # spooled records shipped per /v1/reports request during recovery
    # replay (1 = the pre-batch single-record drain)
    batch_max: int = 32
    # token-bucket cap on replay records/second, so a rejoining agent
    # slews its backlog in instead of dumping it (0 = unpaced)
    replay_rps: float = 256.0
    # clamp on any server-sent Retry-After the agent will honor — an
    # adversarial owner must not be able to park an agent forever
    retry_after_max: float = 300.0


@dataclass
class WireConfig:
    """Wire-format behavior of the agent's report stream
    (``fleet.wire`` v2 fast path, docs/user/fleet.md "Wire format
    v2")."""

    # 2 (default) = binary v2 frames with delta encoding; 1 pins the
    # legacy JSON-headered v1 frames (rollout escape hatch)
    version: int = 2
    # a full keyframe every N windows even when deltas would do — bounds
    # how much state a new owner must request after a hand-off
    keyframe_every: int = 16
    # how long a replica that answered 415/400 to v2 bytes stays
    # remembered as v1-only before the agent re-probes v2
    degraded_ttl: float = 60.0


@dataclass
class AgentConfig:
    """Node-agent delivery plane (the sender half of the fleet leg).

    Transport/retry knobs historically live under ``aggregator.*``; the
    durability plane added by the spool starts the agent's own section.
    """

    spool: SpoolConfig = field(default_factory=SpoolConfig)
    drain: DrainConfig = field(default_factory=DrainConfig)
    wire: WireConfig = field(default_factory=WireConfig)


@dataclass
class JournalConfig:
    """Fleet black box (``kepler_tpu.fleet.journal``): the HLC-stamped
    causal event journal behind ``/debug/journal`` and
    ``/debug/bundle``. Disabled emission costs one global read per
    event, same contract as spans."""

    enabled: bool = False
    # bounded in-memory event ring per process
    ring_size: int = 512
    # durable spool directory ("" = ring only); events are appended as
    # CRC32-framed canonical JSON so a crashed replica's last moments
    # survive for the incident bundle
    dir: str = ""
    # durable file size cap (one rotation to .1 beyond it)
    max_bytes: int = 4_000_000


@dataclass
class TelemetryConfig:
    """Self-telemetry plane (``kepler_tpu.telemetry``): span tracing of
    the monitor/exporter/fleet hot paths, ``kepler_self_*`` metrics, and
    the ``/debug/traces`` endpoint. Disabled spans cost one global read
    per call, so ``enabled: false`` is within measurement noise."""

    enabled: bool = True
    # complete cycle traces kept for /debug/traces, PER cycle name
    # (newest wins; per-name rings keep a high-rate cycle like
    # aggregator ingest from evicting the rare once-per-interval ones)
    ring_size: int = 32
    # kepler_self_stage_duration_seconds bucket bounds (seconds)
    stage_buckets: list[float] = field(default_factory=list)
    # kepler_fleet_delivery_latency_seconds bucket bounds (seconds);
    # the default tail reaches hours because spool replays carry outages
    delivery_buckets: list[float] = field(default_factory=list)
    # fleet black-box event journal (docs/developer/observability.md
    # "Fleet black box")
    journal: JournalConfig = field(default_factory=JournalConfig)


@dataclass
class DevConfig:
    fake_cpu_meter: FakeCpuMeterConfig = field(default_factory=FakeCpuMeterConfig)


@dataclass
class MultihostConfig:
    """Multi-host SPMD fleet window (``docs/user/fleet.md`` "Multi-host"):
    N aggregator processes form ONE ``jax.distributed`` job whose mesh
    spans every host's devices; rung 0 runs the multi-host window engine
    (host-local rings, one SPMD dispatch) and — with ``aggregator.peers``
    set — ingest ownership derives from the mesh shard map, so each
    replica ingests exactly the agents whose packed rows live on its
    local devices."""

    enabled: bool = False
    # coordinator endpoint ("" = take JAX_COORDINATOR_ADDRESS from the
    # env, the TPU pod runtime convention)
    coordinator: str = ""
    # process topology (-1 = take JAX_NUM_PROCESSES / JAX_PROCESS_ID
    # from the env)
    num_processes: int = -1
    process_id: int = -1
    # bound on the coordinator join — an unreachable coordinator is
    # surfaced as a DISTINCT failure reason (coordinator_unreachable) in
    # the log, the return, and the fleet-window probe (0 = jax default)
    init_timeout: float = 0.0
    # on a mesh demotion, run coordinator-lease succession: the elected
    # issuer (incumbent lease holder if alive, else the lowest surviving
    # peer) bumps the ring epoch over the survivor set and broadcasts
    # the membership — works at ANY mesh size. Off = every survivor
    # flags itself "degraded, awaiting membership" until an operator
    # apply_membership lands
    takeover: bool = True


@dataclass
class MembershipConfig:
    """Elastic fleet membership (docs/developer/resilience.md "Elastic
    membership"): runtime host join/leave over the coordinator lease,
    plus the autoscale recommendation policy fed by the fleet's own
    overload signals (admission load, shed deltas, ingest-latency EWMA,
    scoreboard states). Recommendations are always surfaced; they are
    ENACTED only with ``autoApply`` on — the default keeps
    operator-driven behavior byte-for-byte."""

    # enact membership changes (succession already runs under
    # multihost.takeover; this additionally lets the lease holder
    # enact autoscale decisions)
    auto_apply: bool = False
    # run the autoscale policy at all (off = no recommendation gauge,
    # zero per-window overhead)
    autoscale_enabled: bool = False
    # admission load ratio at/above which a window counts toward the
    # scale-up streak, and at/below which toward scale-down; between
    # the two is the dead band (streaks preserved, nothing fires)
    scale_up_load: float = 1.0
    scale_down_load: float = 0.25
    # consecutive overloaded/idle windows before a recommendation
    # fires (up reacts in seconds, down in minutes — asymmetric
    # hysteresis so a flapping load never thrashes membership)
    up_windows: int = 3
    down_windows: int = 12
    # replica-count bounds the policy recommends within (maxReplicas
    # 0 = current membership + available standby peers)
    min_replicas: int = 1
    max_replicas: int = 0
    # endpoints a scale-up may promote into the membership (beyond
    # the live peers list); empty = scale-up recommendations are
    # surfaced but never enacted
    standby_peers: list[str] = field(default_factory=list)
    # bound on membership liveness probes (GET /healthz) and
    # membership-plane POSTs
    probe_timeout: float = 2.0


@dataclass
class AggregatorConfig:
    """Cluster aggregator role — new in this framework.

    The reference has no inter-node plane (SURVEY §2 checklist); this framework
    adds an optional gRPC aggregator that batches many nodes' feature rows into
    one TPU attribution call.
    """

    enabled: bool = False
    listen_address: str = ":28283"
    # node-agent side: where to stream feature rows ("" = standalone mode);
    # https:// scheme + URL userinfo carry TLS and basic-auth credentials
    # (https://user:pw@agg:28283) when the aggregator sets web.config-file
    endpoint: str = ""
    # accept the aggregator's TLS cert without verification (self-signed dev)
    tls_skip_verify: bool = False
    # aggregation cadence and how long a silent node stays in the batch
    interval: float = 5.0
    stale_after: float = 15.0
    # learned estimator for non-RAPL nodes: "" = ratio-only, else
    # "linear"/"mlp"/"moe"/"deep"/"temporal"; params_path = .npz from
    # models.estimator.save_params
    model: str = "mlp"
    params_path: str = ""
    # serve estimators at f32/highest matmul precision — the configuration
    # the 0.5% accuracy budget is validated under (benchmarks/accuracy.py);
    # off = bf16 throughput mode. Estimator shapes are tiny, so the cost
    # is negligible at typical fleet sizes.
    accuracy_mode: bool = False
    # temporal mode: ticks of per-workload feature history the aggregator
    # accretes per node (the model's attention window)
    history_window: int = 16
    # capture RAPL nodes' windows + ratio-watt labels as training files for
    # cmd/train ("" = off); oldest files pruned beyond the cap
    training_dump_dir: str = ""
    training_dump_max_files: int = 1000
    # node-agent side: report as a model-estimated node (no trustworthy
    # RAPL — e.g. a VM guest); the aggregator then uses the estimator
    node_mode: str = "ratio"  # ratio | model
    # -- resilience (docs/developer/resilience.md) --
    # agent send retries: exponential backoff with jitter between attempts
    backoff_initial: float = 0.1
    backoff_max: float = 5.0
    # agent circuit breaker: consecutive failures that open it, and the
    # base cooldown before a half-open probe (doubles per failed probe)
    breaker_threshold: int = 5
    breaker_cooldown: float = 10.0
    # agent shutdown: bound on the best-effort final queue flush
    flush_timeout: float = 2.0
    # aggregator: quarantine reports whose sender clock is skewed beyond
    # this (0 disables the check), and how long a node stays marked
    # degraded after its last quarantined report
    skew_tolerance: float = 120.0
    degraded_ttl: float = 60.0
    # HLC drift clamp (telemetry/hlc.py): an inbound journal clock
    # stamp whose physical component is more than this far ahead of the
    # local wall clock is clamped before merging, so one hostile or
    # broken peer cannot vault the fleet's causal clocks
    hlc_max_drift: float = 60.0
    # aggregator: per-node (run, seq) dedup window — spool replays and
    # retries are absorbed idempotently instead of double-ingesting
    dedup_window: int = 1024
    # -- window pipeline (docs/developer/observability.md) --
    # in-flight fleet windows: 1 = serial assemble→dispatch→fetch; 2
    # (the shipped default) overlaps window N's fetch/scatter behind
    # window N+1's assembly+dispatch — published results are at most
    # pipelineDepth−1 intervals stale, shutdown drains deterministically
    pipeline_depth: int = 2
    # fused window loop (rung 0's top tier): batch this many intervals'
    # delta rows host-side and run them as ONE donated lax.scan dispatch
    # + ONE batched K-window fetch — the host↔device sync cost is paid
    # once per K windows instead of once per window. Published results
    # are at most fusedWindowK−1 intervals stale (the flush publishes
    # all K at once, oldest first). 1 (the default) keeps the unfused
    # per-window dispatch exactly as before
    fused_window_k: int = 1
    # bucket hysteresis: padded batch shapes grow geometrically on
    # demand but only SHRINK after this many consecutive windows at
    # under half occupancy — a fleet hovering at a bucket edge never
    # recompile-thrashes
    bucket_shrink_after: int = 16
    # -- device-plane fault tolerance (resilience.md "Device-plane
    # faults"): any device-leg failure (dispatch error, compile failure,
    # OOM on a bucket-growth recompile, hung fetch) demotes the window
    # one ladder rung — packed pipelined → packed serial → einsum-f32
    # serial → pure-NumPy host — instead of crashing the loop
    fallback_enabled: bool = True
    # consecutive clean windows at a demoted rung before the rung above
    # is retried (hysteresis, mirroring the breaker's half-open probe)
    repromote_after: int = 8
    # stall watchdog on the window fetch: a dispatch that hasn't
    # produced its output within this bound demotes instead of wedging
    # the aggregation loop (0 disables the watchdog)
    dispatch_timeout: float = 30.0
    # device mesh the packed window path runs on: [] = all devices on a
    # 1-D node axis — with > 1 device that is the SHARDED window (per-
    # shard resident rings, per-shard delta H2D, sticky node→shard
    # assignment). A 2-D [n, m] node×model mesh falls back to the
    # unsharded engine (batch still NamedSharding-sharded)
    mesh_shape: list[int] = field(default_factory=list)
    mesh_axes: list[str] = field(default_factory=lambda: ["node"])
    # -- multi-host SPMD tier (docs/user/fleet.md "Multi-host") --
    multihost: MultihostConfig = field(default_factory=MultihostConfig)
    # -- elastic membership + autoscale (docs/developer/resilience.md
    # "Elastic membership") --
    membership: MembershipConfig = field(default_factory=MembershipConfig)
    # -- fleet scoreboard (docs/developer/observability.md "Fleet
    # scoreboard"): per-node health table served at /debug/fleet and as
    # kepler_fleet_node_state — LRU-capped (bounds memory AND metric
    # cardinality), with a rolling z-score anomaly flag on each node's
    # self-reported power (0 disables the anomaly flag)
    scoreboard_cap: int = 1024
    anomaly_z: float = 4.0
    # -- HA ingest ring (docs/developer/resilience.md "Ingest
    # hand-off"): static replica membership for the consistent-hash
    # ingest tier. peers lists every replica's dialable endpoint (the
    # SAME list on every replica and every agent); selfPeer names which
    # entry this replica is (replica role only); ringEpoch versions the
    # membership (bump it when rolling out a changed peers list);
    # ringVnodes is the virtual-node count per peer (ownership
    # granularity). Empty peers = single-replica ingest, ring inert.
    peers: list[str] = field(default_factory=list)
    self_peer: str = ""
    ring_epoch: int = 1
    ring_vnodes: int = 64
    # -- ingest admission control (docs/developer/resilience.md
    # "Overload and backpressure"): shed with 429 + Retry-After BEFORE
    # decode work when the inflight or latency budget is blown —
    # priority-aware (replay backlogs first, live RAPL ground truth
    # last). Shedding is loss-free: records stay spooled and replay.
    admission_enabled: bool = True
    admission_max_inflight: int = 64
    # EWMA ingest-latency budget the shed ladder is scaled against
    admission_latency_budget: float = 0.25
    # base Retry-After answered on a shed (load-multiplied, jittered)
    # and the clamp it can never exceed
    admission_retry_after: float = 1.0
    admission_retry_after_max: float = 30.0
    # -- wire v2 delta bases (docs/user/fleet.md "Wire format v2"):
    # per-node last-keyframe LRU the delta frames merge against; an
    # evicted base costs one 409 needs-keyframe round-trip, never loss
    base_row_cache: int = 1024


@dataclass
class Config:
    log: LogConfig = field(default_factory=LogConfig)
    host: HostConfig = field(default_factory=HostConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    rapl: RaplConfig = field(default_factory=RaplConfig)
    msr: MsrConfig = field(default_factory=MsrConfig)
    exporter: ExporterConfig = field(default_factory=ExporterConfig)
    web: WebConfig = field(default_factory=WebConfig)
    debug: DebugConfig = field(default_factory=DebugConfig)
    kube: KubeConfig = field(default_factory=KubeConfig)
    tpu: TPUConfig = field(default_factory=TPUConfig)
    aggregator: AggregatorConfig = field(default_factory=AggregatorConfig)
    agent: AgentConfig = field(default_factory=AgentConfig)
    service: ServiceConfig = field(default_factory=ServiceConfig)
    fault: FaultConfig = field(default_factory=FaultConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    dev: DevConfig = field(default_factory=DevConfig)

    # ---- validation (reference config.go:418-509) ----

    SKIP_HOST_VALIDATION = "host"
    SKIP_KUBE_VALIDATION = "kube"

    def validate(self, skip: Sequence[str] = ()) -> None:
        errs: list[str] = []
        if self.log.level not in ("debug", "info", "warn", "error"):
            errs.append(f"invalid log level: {self.log.level!r}")
        if self.log.format not in ("text", "json"):
            errs.append(f"invalid log format: {self.log.format!r}")
        if self.SKIP_HOST_VALIDATION not in skip:
            if not os.path.isdir(self.host.sysfs):
                errs.append(f"host.sysfs {self.host.sysfs!r} is not a directory")
            if not os.path.isdir(self.host.procfs):
                errs.append(f"host.procfs {self.host.procfs!r} is not a directory")
        if self.monitor.interval < 0:
            errs.append("monitor.interval must be >= 0")
        if self.monitor.staleness < 0:
            errs.append("monitor.staleness must be >= 0")
        if self.monitor.min_terminated_energy_threshold < 0:
            errs.append("monitor.minTerminatedEnergyThreshold must be >= 0")
        if self.kube.enabled and self.SKIP_KUBE_VALIDATION not in skip:
            if not self.kube.node_name:
                errs.append("kube.nodeName must be set when kube.enabled")
            if self.kube.config and not os.path.isfile(self.kube.config):
                errs.append(f"kube.config {self.kube.config!r} does not exist")
        if self.tpu.workload_bucket <= 0:
            errs.append("tpu.workload_bucket must be > 0")
        if self.tpu.node_bucket <= 0:
            errs.append("tpu.node_bucket must be > 0")
        # fail at startup, not on the first aggregation window (YAML values
        # bypass the CLI flags' choices= checks)
        if self.tpu.platform not in ("auto", "tpu", "cpu"):
            errs.append(f"invalid tpu.platform: {self.tpu.platform!r}")
        if self.tpu.fleet_backend not in ("einsum", "pallas"):
            errs.append(
                f"invalid tpu.fleetBackend: {self.tpu.fleet_backend!r}")
        if self.aggregator.history_window < 1:
            errs.append("aggregator.historyWindow must be >= 1")
        if self.aggregator.training_dump_max_files < 1:
            errs.append("aggregator.trainingDumpMaxFiles must be >= 1")
        if self.aggregator.model not in ("", "linear", "mlp", "moe",
                                         "deep", "temporal"):
            errs.append(f"invalid aggregator.model: {self.aggregator.model!r}")
        if self.aggregator.node_mode not in ("ratio", "model"):
            errs.append(
                f"invalid aggregator.nodeMode: {self.aggregator.node_mode!r}")
        if self.monitor.stall_after < 0:
            errs.append("monitor.stallAfter must be >= 0")
        elif 0 < self.monitor.stall_after <= self.monitor.interval:
            # a threshold at or under one refresh interval would flap the
            # watchdog stalled/recovered on a perfectly healthy node
            errs.append("monitor.stallAfter must exceed monitor.interval "
                        "(or be 0 for auto = 3 × interval)")
        for name, val in (
                ("aggregator.backoffInitial", self.aggregator.backoff_initial),
                ("aggregator.backoffMax", self.aggregator.backoff_max),
                ("aggregator.breakerCooldown",
                 self.aggregator.breaker_cooldown),
                ("aggregator.flushTimeout", self.aggregator.flush_timeout),
                ("aggregator.skewTolerance", self.aggregator.skew_tolerance),
                ("aggregator.degradedTtl", self.aggregator.degraded_ttl),
                ("service.restartBackoffInitial",
                 self.service.restart_backoff_initial),
                ("service.restartBackoffMax",
                 self.service.restart_backoff_max)):
            if val < 0:
                errs.append(f"{name} must be >= 0")
        if self.aggregator.breaker_threshold < 1:
            errs.append("aggregator.breakerThreshold must be >= 1")
        if self.aggregator.dedup_window < 1:
            errs.append("aggregator.dedupWindow must be >= 1")
        if not 1 <= self.aggregator.pipeline_depth <= 8:
            # beyond a few intervals of staleness the "latest" results
            # stop meaning anything; 8 is already generous
            errs.append("aggregator.pipelineDepth must be in [1, 8]")
        if not 1 <= self.aggregator.fused_window_k <= 8:
            # same staleness argument as pipelineDepth: a flush that
            # publishes more than a handful of windows at once makes
            # "latest" meaningless
            errs.append("aggregator.fusedWindowK must be in [1, 8]")
        if self.aggregator.bucket_shrink_after < 1:
            errs.append("aggregator.bucketShrinkAfter must be >= 1")
        if self.aggregator.repromote_after < 1:
            errs.append("aggregator.repromoteAfter must be >= 1")
        if self.aggregator.scoreboard_cap < 1:
            errs.append("aggregator.scoreboardCap must be >= 1")
        if self.aggregator.anomaly_z < 0:
            errs.append("aggregator.anomalyZ must be >= 0 (0 disables "
                        "the anomaly flag)")
        # HA ingest ring: membership must be coherent at startup — a
        # replica that can't place itself in the ring would redirect
        # every report forever
        agg = self.aggregator
        if any(not isinstance(p, str) or not p for p in agg.peers):
            errs.append("aggregator.peers entries must be non-empty "
                        "strings")
        elif len(set(agg.peers)) != len(agg.peers):
            errs.append("aggregator.peers must not contain duplicates")
        elif agg.self_peer and agg.peers \
                and agg.self_peer not in agg.peers:
            errs.append(f"aggregator.selfPeer {agg.self_peer!r} must be "
                        "one of aggregator.peers")
        elif agg.enabled and agg.peers and not agg.self_peer:
            errs.append("aggregator.selfPeer must be set when the "
                        "aggregator role is enabled with aggregator.peers")
        if agg.ring_epoch < 1:
            errs.append("aggregator.ringEpoch must be >= 1")
        if agg.ring_vnodes < 1:
            errs.append("aggregator.ringVnodes must be >= 1")
        # overload control: admission budgets + agent drain pacing
        if agg.admission_max_inflight < 1:
            errs.append("aggregator.admissionMaxInflight must be >= 1")
        for name, val in (
                ("aggregator.admissionLatencyBudget",
                 agg.admission_latency_budget),
                ("aggregator.admissionRetryAfter",
                 agg.admission_retry_after),
                ("aggregator.admissionRetryAfterMax",
                 agg.admission_retry_after_max)):
            if val < 0:
                errs.append(f"{name} must be >= 0")
        if agg.admission_retry_after_max < agg.admission_retry_after:
            errs.append("aggregator.admissionRetryAfterMax must be >= "
                        "aggregator.admissionRetryAfter")
        if agg.base_row_cache < 1:
            errs.append("aggregator.baseRowCache must be >= 1")
        mh = agg.multihost
        if mh.init_timeout < 0:
            errs.append("aggregator.multihost.initTimeout must be >= 0 "
                        "(0 = jax's default join deadline)")
        if mh.num_processes != -1 and mh.num_processes < 1:
            errs.append("aggregator.multihost.numProcesses must be >= 1 "
                        "(or -1 = from JAX_NUM_PROCESSES)")
        if mh.process_id < -1:
            errs.append("aggregator.multihost.processId must be >= 0 "
                        "(or -1 = from JAX_PROCESS_ID)")
        if (mh.enabled and agg.peers
                and mh.num_processes not in (-1, len(agg.peers))):
            errs.append("aggregator.peers must list exactly one replica "
                        "endpoint per multihost process (in process-"
                        "index order) when both are configured")
        mem = agg.membership
        if mem.scale_up_load <= 0:
            errs.append("aggregator.membership.scaleUpLoad must be > 0")
        if mem.scale_down_load < 0:
            errs.append("aggregator.membership.scaleDownLoad must be >= 0")
        if mem.scale_down_load >= mem.scale_up_load:
            errs.append("aggregator.membership.scaleDownLoad must be "
                        "below scaleUpLoad (the gap is the hysteresis "
                        "dead band)")
        if mem.up_windows < 1:
            errs.append("aggregator.membership.upWindows must be >= 1")
        if mem.down_windows < 1:
            errs.append("aggregator.membership.downWindows must be >= 1")
        if mem.min_replicas < 1:
            errs.append("aggregator.membership.minReplicas must be >= 1")
        if mem.max_replicas < 0:
            errs.append("aggregator.membership.maxReplicas must be >= 0 "
                        "(0 = membership + standby size)")
        if mem.max_replicas and mem.max_replicas < mem.min_replicas:
            errs.append("aggregator.membership.maxReplicas must be >= "
                        "minReplicas (or 0)")
        if mem.probe_timeout <= 0:
            errs.append("aggregator.membership.probeTimeout must be > 0")
        if any(not isinstance(p, str) or not p for p in mem.standby_peers):
            errs.append("aggregator.membership.standbyPeers entries must "
                        "be non-empty strings")
        elif any(p in agg.peers for p in mem.standby_peers):
            errs.append("aggregator.membership.standbyPeers must not "
                        "overlap aggregator.peers (a standby is by "
                        "definition outside the initial membership)")
        if (mem.auto_apply or mem.autoscale_enabled) and not agg.peers:
            errs.append("aggregator.membership.autoApply/autoscaleEnabled "
                        "need aggregator.peers (the ingest ring is the "
                        "membership being scaled)")
        wire = self.agent.wire
        if wire.version not in (1, 2):
            errs.append("agent.wire.version must be 1 or 2")
        if wire.keyframe_every < 1:
            errs.append("agent.wire.keyframeEvery must be >= 1")
        if wire.degraded_ttl <= 0:
            errs.append("agent.wire.degradedTtl must be > 0")
        drain = self.agent.drain
        if drain.batch_max < 1:
            errs.append("agent.drain.batchMax must be >= 1")
        if drain.replay_rps < 0:
            errs.append("agent.drain.replayRps must be >= 0 "
                        "(0 disables replay pacing)")
        if drain.retry_after_max <= 0:
            errs.append("agent.drain.retryAfterMax must be > 0 (a zero "
                        "clamp would turn every 429 into an immediate "
                        "resend)")
        if self.web.max_connections < 0:
            errs.append("web.maxConnections must be >= 0 "
                        "(0 disables the connection cap)")
        if self.aggregator.dispatch_timeout < 0:
            errs.append("aggregator.dispatchTimeout must be >= 0 "
                        "(0 disables the stall watchdog)")
        # mesh validity beyond this (device divisibility) is checked by
        # make_mesh at startup, when the device count is known
        if not self.aggregator.mesh_axes:
            errs.append("aggregator.meshAxes must name at least one axis")
        elif self.aggregator.mesh_axes[0] != "node":
            errs.append("aggregator.meshAxes must lead with 'node' "
                        f"(got {self.aggregator.mesh_axes!r}) — the "
                        "fleet batch shards over the node axis")
        if self.aggregator.mesh_shape and (
                len(self.aggregator.mesh_shape)
                != len(self.aggregator.mesh_axes)):
            errs.append("aggregator.meshShape and aggregator.meshAxes "
                        "must have the same rank")
        if self.monitor.state_max_age < 0:
            errs.append("monitor.stateMaxAge must be >= 0")
        spool = self.agent.spool
        if spool.fsync not in ("batch", "always", "none"):
            errs.append(f"invalid agent.spool.fsync: {spool.fsync!r} "
                        "(batch | always | none)")
        if spool.fsync_interval < 0:
            errs.append("agent.spool.fsyncInterval must be >= 0")
        for name, val in (("agent.spool.maxBytes", spool.max_bytes),
                          ("agent.spool.maxRecords", spool.max_records),
                          ("agent.spool.segmentBytes", spool.segment_bytes)):
            if val < 1:
                errs.append(f"{name} must be >= 1")
        if self.service.restart_max < 0:
            errs.append("service.restartMax must be >= 0")
        if self.telemetry.ring_size < 1:
            errs.append("telemetry.ringSize must be >= 1")
        journal = self.telemetry.journal
        if journal.ring_size < 1:
            errs.append("telemetry.journal.ringSize must be >= 1")
        if journal.max_bytes < 4096:
            errs.append("telemetry.journal.maxBytes must be >= 4096 "
                        "(one rotation must fit at least a few frames)")
        if self.aggregator.hlc_max_drift <= 0:
            errs.append("aggregator.hlcMaxDrift must be > 0 (the clamp "
                        "bound on inbound HLC physical clocks)")
        for name, buckets in (
                ("telemetry.stageBuckets", self.telemetry.stage_buckets),
                ("telemetry.deliveryBuckets",
                 self.telemetry.delivery_buckets)):
            # [] = use the built-in defaults; an explicit list must be
            # strictly increasing positive bounds or the histogram's
            # cumulative rendering silently lies
            vals = list(buckets)
            if any(isinstance(b, bool) or not isinstance(b, (int, float))
                   for b in vals):
                errs.append(f"{name} must be numbers")
            elif vals and (vals[0] <= 0
                           or any(b >= a for b, a in zip(vals, vals[1:]))):
                errs.append(f"{name} must be strictly increasing and > 0")
        if self.fault.enabled:
            # a typo'd chaos plan must fail at startup, not inject nothing
            try:
                from kepler_tpu.fault import FaultPlan
                FaultPlan.from_config(self.fault)
            except ValueError as err:
                errs.append(str(err))
        if errs:
            raise ValueError("invalid configuration: " + "; ".join(errs))


# ---------------------------------------------------------------------------
# YAML loading (reference config.go:241-278)
# ---------------------------------------------------------------------------

# YAML key → (section attr, field attr) spelling map for keys whose YAML name
# differs from the Python attribute (mirrors reference yaml tags). Every
# multi-word key accepts BOTH the reference-style camelCase spelling and the
# kebab-case spelling matching its CLI flag, so a flag line can be pasted
# into YAML without a spelling surprise.
_CANONICAL_YAML_KEYS: dict[str, str] = {
    "configFile": "config_file",
    "listenAddresses": "listen_addresses",
    "maxTerminated": "max_terminated",
    "minTerminatedEnergyThreshold": "min_terminated_energy_threshold",
    "debugCollectors": "debug_collectors",
    "metricsLevel": "metrics_level",
    "nodeName": "node_name",
    "listenAddress": "listen_address",
    "staleAfter": "stale_after",
    "paramsPath": "params_path",
    "tlsSkipVerify": "tls_skip_verify",
    "nodeMode": "node_mode",
    "workloadBucket": "workload_bucket",
    "nodeBucket": "node_bucket",
    "meshShape": "mesh_shape",
    "meshAxes": "mesh_axes",
    "fleetBackend": "fleet_backend",
    "historyWindow": "history_window",
    "accuracyMode": "accuracy_mode",
    "trainingDumpDir": "training_dump_dir",
    "trainingDumpMaxFiles": "training_dump_max_files",
    "fakeCpuMeter": "fake_cpu_meter",
    "devicePath": "device_path",
    "compilationCacheDir": "compilation_cache_dir",
    "stallAfter": "stall_after",
    "backoffInitial": "backoff_initial",
    "backoffMax": "backoff_max",
    "breakerThreshold": "breaker_threshold",
    "breakerCooldown": "breaker_cooldown",
    "flushTimeout": "flush_timeout",
    "skewTolerance": "skew_tolerance",
    "degradedTtl": "degraded_ttl",
    "restartMax": "restart_max",
    "restartBackoffInitial": "restart_backoff_initial",
    "restartBackoffMax": "restart_backoff_max",
    "statePath": "state_path",
    "stateMaxAge": "state_max_age",
    "dedupWindow": "dedup_window",
    "pipelineDepth": "pipeline_depth",
    "fusedWindowK": "fused_window_k",
    "bucketShrinkAfter": "bucket_shrink_after",
    "fallbackEnabled": "fallback_enabled",
    "repromoteAfter": "repromote_after",
    "dispatchTimeout": "dispatch_timeout",
    "scoreboardCap": "scoreboard_cap",
    "anomalyZ": "anomaly_z",
    "selfPeer": "self_peer",
    "ringEpoch": "ring_epoch",
    "ringVnodes": "ring_vnodes",
    "admissionEnabled": "admission_enabled",
    "numProcesses": "num_processes",
    "processId": "process_id",
    "initTimeout": "init_timeout",
    "autoApply": "auto_apply",
    "autoscaleEnabled": "autoscale_enabled",
    "scaleUpLoad": "scale_up_load",
    "scaleDownLoad": "scale_down_load",
    "upWindows": "up_windows",
    "downWindows": "down_windows",
    "minReplicas": "min_replicas",
    "maxReplicas": "max_replicas",
    "standbyPeers": "standby_peers",
    "probeTimeout": "probe_timeout",
    "admissionMaxInflight": "admission_max_inflight",
    "admissionLatencyBudget": "admission_latency_budget",
    "admissionRetryAfter": "admission_retry_after",
    "admissionRetryAfterMax": "admission_retry_after_max",
    "batchMax": "batch_max",
    "replayRps": "replay_rps",
    "retryAfterMax": "retry_after_max",
    "keyframeEvery": "keyframe_every",
    "baseRowCache": "base_row_cache",
    "maxConnections": "max_connections",
    "maxBytes": "max_bytes",
    "maxRecords": "max_records",
    "segmentBytes": "segment_bytes",
    "fsyncInterval": "fsync_interval",
    "ringSize": "ring_size",
    "stageBuckets": "stage_buckets",
    "deliveryBuckets": "delivery_buckets",
    "hlcMaxDrift": "hlc_max_drift",
}


def _kebab(camel: str) -> str:
    return "".join("-" + c.lower() if c.isupper() else c for c in camel)


_YAML_KEYS: dict[str, str] = {
    **_CANONICAL_YAML_KEYS,
    **{_kebab(k): v for k, v in _CANONICAL_YAML_KEYS.items()},
}

_DURATION_FIELDS = {"interval", "staleness", "stale_after", "stall_after",
                    "backoff_initial", "backoff_max", "breaker_cooldown",
                    "flush_timeout", "skew_tolerance", "degraded_ttl",
                    "restart_backoff_initial", "restart_backoff_max",
                    "state_max_age", "fsync_interval", "dispatch_timeout",
                    "admission_latency_budget", "admission_retry_after",
                    "admission_retry_after_max", "retry_after_max",
                    "init_timeout", "probe_timeout", "hlc_max_drift"}


def _apply_mapping(obj: Any, data: Mapping[str, Any], path: str = "") -> None:
    for raw_key, value in data.items():
        attr = _YAML_KEYS.get(raw_key, raw_key)
        where = f"{path}.{raw_key}" if path else raw_key
        if not dataclasses.is_dataclass(obj) or not hasattr(obj, attr):
            raise ValueError(f"unknown config key: {where!r}")
        current = getattr(obj, attr)
        if dataclasses.is_dataclass(current):
            if value is None:
                continue
            if not isinstance(value, Mapping):
                raise ValueError(f"config key {where!r} expects a mapping")
            _apply_mapping(current, value, where)
        elif attr == "metrics_level":
            if isinstance(value, str):
                value = [value]
            setattr(obj, attr, parse_level(value))
        elif attr in _DURATION_FIELDS:
            setattr(obj, attr, _parse_duration(value))
        elif isinstance(current, bool):
            if not isinstance(value, bool):
                raise ValueError(f"config key {where!r} expects a bool")
            setattr(obj, attr, value)
        elif isinstance(current, float) and isinstance(value, (int, float)):
            setattr(obj, attr, float(value))
        elif isinstance(current, list):
            if value is None:
                setattr(obj, attr, [])
            elif isinstance(value, list):
                setattr(obj, attr, list(value))
            else:
                raise ValueError(f"config key {where!r} expects a list")
        else:
            setattr(obj, attr, value)


def load(stream: IO[str] | str) -> Config:
    """Load configuration from a YAML stream/string over defaults."""
    cfg = default_config()
    text = stream if isinstance(stream, str) else stream.read()
    data = yaml.safe_load(io.StringIO(text)) or {}
    if not isinstance(data, Mapping):
        raise ValueError("config root must be a mapping")
    _apply_mapping(cfg, data)
    return cfg


def from_file(path: str) -> Config:
    """Load configuration from a YAML file path (reference ``FromFile``)."""
    with open(path, "r", encoding="utf-8") as f:
        cfg = load(f)
    return cfg


def default_config() -> Config:
    return Config()


# ---------------------------------------------------------------------------
# Flag registration + precedence (reference config.go:285-395)
# ---------------------------------------------------------------------------


def register_flags(parser: argparse.ArgumentParser) -> None:
    """Register CLI flags. Defaults are sentinels so we can tell 'explicitly
    passed' from 'defaulted' — only explicit flags override YAML
    (reference flag-set tracking, config.go:330-394)."""
    add = parser.add_argument
    add("--config.file", dest="config_file", default=None, help="YAML config path")
    add("--log.level", dest="log_level", default=None,
        choices=["debug", "info", "warn", "error"])
    add("--log.format", dest="log_format", default=None, choices=["text", "json"])
    add("--host.sysfs", dest="host_sysfs", default=None)
    add("--host.procfs", dest="host_procfs", default=None)
    add("--monitor.interval", dest="monitor_interval", default=None,
        help="refresh interval, e.g. 5s")
    add("--monitor.max-terminated", dest="monitor_max_terminated", default=None,
        type=int)
    add("--monitor.state-path", dest="monitor_state_path", default=None,
        help="counter-state file for restart-surviving attribution")
    add("--debug.pprof", dest="debug_pprof", default=None,
        action=argparse.BooleanOptionalAction)
    add("--web.config-file", dest="web_config_file", default=None)
    add("--web.listen-address", dest="web_listen_address", default=None,
        action="append", help="repeatable listen address")
    add("--exporter.stdout", dest="exporter_stdout", default=None,
        action=argparse.BooleanOptionalAction)
    add("--exporter.prometheus", dest="exporter_prometheus", default=None,
        action=argparse.BooleanOptionalAction)
    add("--metrics", dest="metrics", default=None, action="append",
        help="cumulative metrics level: node|process|container|vm|pod|all")
    add("--kube.enable", dest="kube_enable", default=None,
        action=argparse.BooleanOptionalAction)
    add("--kube.config", dest="kube_config", default=None)
    add("--kube.node-name", dest="kube_node_name", default=None)
    add("--aggregator.enable", dest="aggregator_enable", default=None,
        action=argparse.BooleanOptionalAction)
    add("--aggregator.listen-address", dest="aggregator_listen", default=None)
    add("--aggregator.endpoint", dest="aggregator_endpoint", default=None)
    add("--aggregator.tls-skip-verify", dest="aggregator_tls_skip_verify",
        default=None, action=argparse.BooleanOptionalAction)
    add("--aggregator.model", dest="aggregator_model", default=None,
        choices=["", "linear", "mlp", "moe", "deep", "temporal"])
    add("--aggregator.params-path", dest="aggregator_params_path",
        default=None)
    add("--aggregator.node-mode", dest="aggregator_node_mode", default=None,
        choices=["ratio", "model"])
    add("--aggregator.accuracy-mode", dest="aggregator_accuracy_mode",
        default=None, action=argparse.BooleanOptionalAction)
    add("--aggregator.history-window", dest="aggregator_history_window",
        default=None, type=int)
    add("--aggregator.training-dump-dir", dest="aggregator_dump_dir",
        default=None)
    add("--aggregator.training-dump-max-files",
        dest="aggregator_dump_max_files", default=None, type=int)
    add("--aggregator.dedup-window", dest="aggregator_dedup_window",
        default=None, type=int)
    add("--aggregator.pipeline-depth", dest="aggregator_pipeline_depth",
        default=None, type=int,
        help="in-flight fleet windows (1 = serial, 2 = double-buffered)")
    add("--aggregator.fused-window-k", dest="aggregator_fused_window_k",
        default=None, type=int,
        help="intervals batched into one fused device scan (1 = unfused "
             "per-window dispatch; K>1 syncs the host once per K windows)")
    add("--aggregator.bucket-shrink-after",
        dest="aggregator_bucket_shrink_after", default=None, type=int,
        help="consecutive under-half windows before a batch bucket shrinks")
    add("--aggregator.fallback-enabled", dest="aggregator_fallback_enabled",
        default=None, action=argparse.BooleanOptionalAction,
        help="degrade the window device leg down a fallback ladder on "
             "failure instead of crashing the aggregation loop")
    add("--aggregator.repromote-after", dest="aggregator_repromote_after",
        default=None, type=int,
        help="consecutive clean windows at a demoted rung before the "
             "rung above is retried")
    add("--aggregator.dispatch-timeout", dest="aggregator_dispatch_timeout",
        default=None,
        help="stall watchdog bound on the window fetch, e.g. 30s "
             "(0 disables)")
    add("--aggregator.scoreboard-cap", dest="aggregator_scoreboard_cap",
        default=None, type=int,
        help="fleet scoreboard LRU cap (bounds memory and "
             "kepler_fleet_node_state cardinality)")
    add("--aggregator.anomaly-z", dest="aggregator_anomaly_z",
        default=None, type=float,
        help="rolling z-score threshold flagging a node's reported "
             "power as anomalous (0 disables)")
    add("--aggregator.admission-enabled",
        dest="aggregator_admission_enabled", default=None,
        action=argparse.BooleanOptionalAction,
        help="shed ingest load with 429 + Retry-After before decode "
             "when the inflight/latency budget is blown (loss-free: "
             "shed records stay spooled on the agent and replay)")
    add("--web.max-connections", dest="web_max_connections", default=None,
        type=int,
        help="concurrent-connection cap per listener; overflow is "
             "answered 503 without spawning a thread (0 = unbounded)")
    add("--aggregator.peers", dest="aggregator_peers", default=None,
        action="append",
        help="repeatable: one ingest-ring replica endpoint per flag "
             "(the same list on every replica and agent)")
    add("--aggregator.self-peer", dest="aggregator_self_peer",
        default=None,
        help="which aggregator.peers entry THIS replica is")
    add("--aggregator.ring-epoch", dest="aggregator_ring_epoch",
        default=None, type=int,
        help="ingest-ring membership epoch (bump when rolling out a "
             "changed peers list)")
    add("--aggregator.ring-vnodes", dest="aggregator_ring_vnodes",
        default=None, type=int,
        help="virtual nodes per ring peer (ownership granularity)")
    add("--agent.spool-dir", dest="agent_spool_dir", default=None,
        help="crash-safe report spool directory (empty disables)")
    add("--agent.wire-version", dest="agent_wire_version", default=None,
        type=int, choices=[1, 2],
        help="report wire format: 2 = binary delta-encoded v2 "
             "(default), 1 = legacy JSON-headered frames")
    add("--aggregator.base-row-cache",
        dest="aggregator_base_row_cache", default=None, type=int,
        help="wire-v2 delta-base LRU size (per-node last keyframes; "
             "eviction costs a 409 needs-keyframe round-trip)")
    add("--aggregator.multihost.enabled",
        dest="aggregator_multihost_enabled", default=None,
        action=argparse.BooleanOptionalAction,
        help="multi-host SPMD fleet window: join a jax.distributed "
             "cluster and run rung 0 over every host's devices "
             "(host-local rings, one SPMD dispatch, mesh-derived "
             "ingest ownership)")
    add("--aggregator.multihost.coordinator",
        dest="aggregator_multihost_coordinator", default=None,
        help="jax.distributed coordinator address (empty = "
             "JAX_COORDINATOR_ADDRESS)")
    add("--aggregator.multihost.num-processes",
        dest="aggregator_multihost_num_processes", default=None,
        type=int,
        help="process count of the multi-host job (-1 = "
             "JAX_NUM_PROCESSES)")
    add("--aggregator.multihost.process-id",
        dest="aggregator_multihost_process_id", default=None, type=int,
        help="this process's id in the multi-host job (-1 = "
             "JAX_PROCESS_ID)")
    add("--aggregator.multihost.init-timeout",
        dest="aggregator_multihost_init_timeout", default=None,
        help="bound on the coordinator join, e.g. 60s (0 = jax's "
             "default); an unreachable coordinator surfaces as the "
             "distinct coordinator_unreachable failure reason")
    add("--aggregator.multihost.takeover",
        dest="aggregator_multihost_takeover", default=None,
        action=argparse.BooleanOptionalAction,
        help="on a mesh demotion, run coordinator-lease succession: "
             "the elected issuer bumps the ring epoch over the "
             "survivor set and broadcasts it (any mesh size)")
    add("--aggregator.membership.auto-apply",
        dest="aggregator_membership_auto_apply", default=None,
        action=argparse.BooleanOptionalAction,
        help="let the lease holder ENACT autoscale membership changes "
             "(off = recommendations surfaced only; operator behavior "
             "unchanged)")
    add("--aggregator.membership.autoscale-enabled",
        dest="aggregator_membership_autoscale_enabled", default=None,
        action=argparse.BooleanOptionalAction,
        help="run the autoscale recommendation policy over the fleet's "
             "recorded overload signals")
    add("--aggregator.membership.scale-up-load",
        dest="aggregator_membership_scale_up_load", default=None,
        type=float,
        help="admission load ratio counting a window toward the "
             "scale-up streak")
    add("--aggregator.membership.scale-down-load",
        dest="aggregator_membership_scale_down_load", default=None,
        type=float,
        help="admission load ratio counting a window toward the "
             "scale-down streak")
    add("--aggregator.membership.up-windows",
        dest="aggregator_membership_up_windows", default=None, type=int,
        help="consecutive overloaded windows before a scale-up "
             "recommendation fires")
    add("--aggregator.membership.down-windows",
        dest="aggregator_membership_down_windows", default=None,
        type=int,
        help="consecutive idle windows before a scale-down "
             "recommendation fires")
    add("--aggregator.membership.min-replicas",
        dest="aggregator_membership_min_replicas", default=None,
        type=int,
        help="floor the autoscale policy never recommends below")
    add("--aggregator.membership.max-replicas",
        dest="aggregator_membership_max_replicas", default=None,
        type=int,
        help="ceiling the autoscale policy never recommends above "
             "(0 = membership + standby size)")
    add("--aggregator.membership.standby-peers",
        dest="aggregator_membership_standby_peers", default=None,
        action="append",
        help="repeatable: replica endpoint a scale-up may promote "
             "into the membership")
    add("--aggregator.membership.probe-timeout",
        dest="aggregator_membership_probe_timeout", default=None,
        help="bound on membership liveness probes and membership-plane "
             "POSTs, e.g. 2s")
    add("--tpu.platform", dest="tpu_platform", default=None,
        choices=["auto", "tpu", "cpu"])
    add("--tpu.fleet-backend", dest="tpu_fleet_backend", default=None,
        choices=["einsum", "pallas"])
    add("--telemetry.enable", dest="telemetry_enable", default=None,
        action=argparse.BooleanOptionalAction,
        help="self-telemetry span tracing + kepler_self_* metrics")
    add("--telemetry.journal.enable", dest="telemetry_journal_enable",
        default=None, action=argparse.BooleanOptionalAction,
        help="fleet black-box event journal "
             "(/debug/journal + /debug/bundle)")


def apply_flags(cfg: Config, args: argparse.Namespace) -> Config:
    """Overlay explicitly-passed flags onto cfg (highest precedence)."""
    def set_if(attr_path: tuple[str, str], value: Any,
               transform: Callable[[Any], Any] | None = None) -> None:
        if value is None:
            return
        section, attr = attr_path
        setattr(getattr(cfg, section), attr,
                transform(value) if transform else value)

    set_if(("log", "level"), args.log_level)
    set_if(("log", "format"), args.log_format)
    set_if(("host", "sysfs"), args.host_sysfs)
    set_if(("host", "procfs"), args.host_procfs)
    set_if(("monitor", "interval"), args.monitor_interval, _parse_duration)
    set_if(("monitor", "max_terminated"), args.monitor_max_terminated)
    set_if(("monitor", "state_path"), args.monitor_state_path)
    if args.debug_pprof is not None:
        cfg.debug.pprof.enabled = args.debug_pprof
    set_if(("web", "config_file"), args.web_config_file)
    if args.web_listen_address:
        cfg.web.listen_addresses = list(args.web_listen_address)
    if args.exporter_stdout is not None:
        cfg.exporter.stdout.enabled = args.exporter_stdout
    if args.exporter_prometheus is not None:
        cfg.exporter.prometheus.enabled = args.exporter_prometheus
    if args.metrics:
        cfg.exporter.prometheus.metrics_level = parse_level(args.metrics)
    set_if(("kube", "enabled"), args.kube_enable)
    set_if(("kube", "config"), args.kube_config)
    set_if(("kube", "node_name"), args.kube_node_name)
    set_if(("aggregator", "enabled"), args.aggregator_enable)
    set_if(("aggregator", "listen_address"), args.aggregator_listen)
    set_if(("aggregator", "endpoint"), args.aggregator_endpoint)
    set_if(("aggregator", "tls_skip_verify"), args.aggregator_tls_skip_verify)
    set_if(("aggregator", "model"), args.aggregator_model)
    set_if(("aggregator", "params_path"), args.aggregator_params_path)
    set_if(("aggregator", "node_mode"), args.aggregator_node_mode)
    set_if(("aggregator", "accuracy_mode"), args.aggregator_accuracy_mode)
    set_if(("aggregator", "history_window"), args.aggregator_history_window)
    set_if(("aggregator", "training_dump_dir"), args.aggregator_dump_dir)
    set_if(("aggregator", "training_dump_max_files"),
           args.aggregator_dump_max_files)
    set_if(("aggregator", "dedup_window"), args.aggregator_dedup_window)
    set_if(("aggregator", "pipeline_depth"), args.aggregator_pipeline_depth)
    set_if(("aggregator", "fused_window_k"),
           args.aggregator_fused_window_k)
    set_if(("aggregator", "bucket_shrink_after"),
           args.aggregator_bucket_shrink_after)
    set_if(("aggregator", "fallback_enabled"),
           args.aggregator_fallback_enabled)
    set_if(("aggregator", "repromote_after"), args.aggregator_repromote_after)
    set_if(("aggregator", "dispatch_timeout"),
           args.aggregator_dispatch_timeout, _parse_duration)
    set_if(("aggregator", "scoreboard_cap"), args.aggregator_scoreboard_cap)
    set_if(("aggregator", "anomaly_z"), args.aggregator_anomaly_z)
    set_if(("aggregator", "admission_enabled"),
           args.aggregator_admission_enabled)
    if args.web_max_connections is not None:
        cfg.web.max_connections = args.web_max_connections
    if args.aggregator_peers:
        cfg.aggregator.peers = list(args.aggregator_peers)
    set_if(("aggregator", "self_peer"), args.aggregator_self_peer)
    set_if(("aggregator", "ring_epoch"), args.aggregator_ring_epoch)
    set_if(("aggregator", "ring_vnodes"), args.aggregator_ring_vnodes)
    if args.agent_spool_dir is not None:
        cfg.agent.spool.dir = args.agent_spool_dir
    if args.agent_wire_version is not None:
        cfg.agent.wire.version = args.agent_wire_version
    set_if(("aggregator", "base_row_cache"),
           args.aggregator_base_row_cache)
    mh = cfg.aggregator.multihost
    if args.aggregator_multihost_enabled is not None:
        mh.enabled = args.aggregator_multihost_enabled
    if args.aggregator_multihost_coordinator is not None:
        mh.coordinator = args.aggregator_multihost_coordinator
    if args.aggregator_multihost_num_processes is not None:
        mh.num_processes = args.aggregator_multihost_num_processes
    if args.aggregator_multihost_process_id is not None:
        mh.process_id = args.aggregator_multihost_process_id
    if args.aggregator_multihost_init_timeout is not None:
        mh.init_timeout = _parse_duration(
            args.aggregator_multihost_init_timeout)
    if args.aggregator_multihost_takeover is not None:
        mh.takeover = args.aggregator_multihost_takeover
    mem = cfg.aggregator.membership
    if args.aggregator_membership_auto_apply is not None:
        mem.auto_apply = args.aggregator_membership_auto_apply
    if args.aggregator_membership_autoscale_enabled is not None:
        mem.autoscale_enabled = args.aggregator_membership_autoscale_enabled
    if args.aggregator_membership_scale_up_load is not None:
        mem.scale_up_load = args.aggregator_membership_scale_up_load
    if args.aggregator_membership_scale_down_load is not None:
        mem.scale_down_load = args.aggregator_membership_scale_down_load
    if args.aggregator_membership_up_windows is not None:
        mem.up_windows = args.aggregator_membership_up_windows
    if args.aggregator_membership_down_windows is not None:
        mem.down_windows = args.aggregator_membership_down_windows
    if args.aggregator_membership_min_replicas is not None:
        mem.min_replicas = args.aggregator_membership_min_replicas
    if args.aggregator_membership_max_replicas is not None:
        mem.max_replicas = args.aggregator_membership_max_replicas
    if args.aggregator_membership_standby_peers:
        mem.standby_peers = list(args.aggregator_membership_standby_peers)
    if args.aggregator_membership_probe_timeout is not None:
        mem.probe_timeout = _parse_duration(
            args.aggregator_membership_probe_timeout)
    set_if(("tpu", "platform"), args.tpu_platform)
    set_if(("tpu", "fleet_backend"), args.tpu_fleet_backend)
    set_if(("telemetry", "enabled"), args.telemetry_enable)
    if args.telemetry_journal_enable is not None:
        cfg.telemetry.journal.enabled = args.telemetry_journal_enable
    return cfg


def parse_args_and_config(
    argv: Sequence[str] | None = None,
    skip_validation: Sequence[str] = (),
) -> Config:
    """Full precedence chain: defaults < --config.file YAML < explicit flags.

    Reference ``cmd/kepler/main.go:80-122`` parseArgsAndConfig.
    """
    parser = argparse.ArgumentParser(prog="kepler-tpu")
    register_flags(parser)
    args = parser.parse_args(argv)
    cfg = from_file(args.config_file) if args.config_file else default_config()
    cfg = apply_flags(cfg, args)
    cfg.validate(skip=skip_validation)
    return cfg


# ---------------------------------------------------------------------------
# Builder: merge YAML fragments (reference config/builder.go:34-57)
# ---------------------------------------------------------------------------


class Builder:
    """Accumulates YAML fragments and merges them over defaults, last wins.

    Used by tests to compose configs piecemeal, like the reference's
    mergo-based builder.
    """

    def __init__(self) -> None:
        self._fragments: list[str] = []

    def use(self, yaml_fragment: str) -> "Builder":
        self._fragments.append(yaml_fragment)
        return self

    def build(self) -> Config:
        cfg = default_config()
        for frag in self._fragments:
            data = yaml.safe_load(io.StringIO(frag)) or {}
            if not isinstance(data, Mapping):
                raise ValueError("config fragment root must be a mapping")
            _apply_mapping(cfg, data)
        return cfg
