"""Metrics-level bitmask.

Reference parity: ``config/level.go:12-24`` — a bitmask selecting which metric
families are exported (node / process / container / vm / pod), with parsing of
cumulative ``--metrics`` flag values and "all" shorthand.
"""

from __future__ import annotations

import enum
from typing import Iterable


class Level(enum.IntFlag):
    """Which workload granularities to export metrics for."""

    NODE = 1 << 0
    PROCESS = 1 << 1
    CONTAINER = 1 << 2
    VM = 1 << 3
    POD = 1 << 4

    @classmethod
    def all(cls) -> "Level":
        return cls.NODE | cls.PROCESS | cls.CONTAINER | cls.VM | cls.POD

    def __str__(self) -> str:
        if self == Level.all():
            return "all"
        names = [m.name.lower() for m in Level if m in self and m.name]
        return "|".join(names) if names else "none"


_NAME_TO_LEVEL = {
    "node": Level.NODE,
    "process": Level.PROCESS,
    "container": Level.CONTAINER,
    "vm": Level.VM,
    "pod": Level.POD,
    "all": Level.all(),
}


def parse_level(values: Iterable[str]) -> Level:
    """Parse a list of level names into a combined bitmask.

    Accepts case-insensitive names; raises ``ValueError`` on unknown names
    (reference ``config/level.go`` ParseLevel).
    """
    combined = Level(0)
    for v in values:
        key = v.strip().lower()
        if key not in _NAME_TO_LEVEL:
            raise ValueError(
                f"invalid metrics level {v!r}; valid: "
                f"{', '.join(_NAME_TO_LEVEL)}"
            )
        combined |= _NAME_TO_LEVEL[key]
    return combined
