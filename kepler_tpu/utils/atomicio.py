"""Atomic small-file persistence shared by the durability plane.

The monitor's counter-state file and the spool's ack cursor both need
the same property: a reader (usually the next process incarnation) must
see either the previous complete document or the new complete document,
never a torn write. One implementation — write a sibling tmp file, then
``os.replace`` (atomic on POSIX within a filesystem) — keeps the
crash-safety semantics in a single place.
"""

from __future__ import annotations

import json
import os
from typing import Any


def atomic_write_json(path: str, obj: Any) -> None:
    """Write ``obj`` as JSON to ``path`` via tmp-file + atomic rename.

    Raises ``OSError`` on failure (callers decide whether a failed
    persist is fatal — for both current users it only weakens a
    redelivery/freshness guarantee, so they log and continue)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)
