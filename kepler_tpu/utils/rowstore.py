"""Dense id-keyed row store for cumulative accumulators.

Both per-workload energy on the node monitor and per-node energy on the
fleet aggregator need the same thing: ``store[id] += delta`` for tens of
thousands of ids per tick WITHOUT per-row Python. Values live in one f64
``[cap, Z]`` matrix; ids map to rows that persist for the entity's
lifetime (freed on termination); the steady-state update is one cached
gather, one vectorized add, one scatter.
"""

from __future__ import annotations

import numpy as np


class RowStore:
    """Cumulative ``[*, Z]`` accumulators keyed by string ids."""

    def __init__(self, n_zones: int, initial_rows: int = 64) -> None:
        self._z = n_zones
        self.arr = np.zeros((initial_rows, n_zones))
        self.rows: dict[str, int] = {}
        self._free: list[int] = list(range(initial_rows - 1, -1, -1))
        self._cached: tuple[tuple[str, ...], np.ndarray] | None = None

    @property
    def n_zones(self) -> int:
        return self._z

    def __contains__(self, wid: str) -> bool:
        return wid in self.rows

    def row_indices(self, ids: tuple[str, ...]) -> np.ndarray:
        """Row index per id, allocating fresh (zeroed) rows for new ids.
        The index array is cached while the id tuple is unchanged."""
        cached = self._cached
        if cached is not None and cached[0] == ids:
            return cached[1]
        if len(set(ids)) != len(ids):
            # a duplicate id would collapse onto one row and the scatter
            # in accumulate() would drop a delta — fail loudly (not
            # assert: -O must not change energy accounting)
            raise ValueError(
                "duplicate ids in accumulator batch; cumulative energy "
                "accounting requires unique ids")
        idx = np.empty(len(ids), np.intp)
        get = self.rows.get
        for j, wid in enumerate(ids):
            r = get(wid)
            if r is None:
                if not self._free:
                    old_len = len(self.arr)
                    grow = max(old_len, 64)
                    self.arr = np.vstack(
                        [self.arr, np.zeros((grow, self._z))])
                    self._free = list(
                        range(old_len + grow - 1, old_len - 1, -1))
                r = self._free.pop()
                self.arr[r] = 0.0
                self.rows[wid] = r
            idx[j] = r
        self._cached = (ids, idx)
        return idx

    def accumulate(self, ids: tuple[str, ...],
                   deltas: np.ndarray) -> np.ndarray:
        """arr[ids] += deltas; → the new cumulative values [n, Z]."""
        idx = self.row_indices(ids)
        vals = self.arr[idx] + deltas
        self.arr[idx] = vals
        return vals

    def value(self, wid: str) -> np.ndarray:
        return self.arr[self.rows[wid]]

    def pop(self, wid: str) -> None:
        r = self.rows.pop(wid, None)
        if r is not None:
            self._free.append(r)
            self._cached = None

    def remap_columns(self, old_names: list[str],
                      new_names: list[str]) -> None:
        """Re-key the value columns by NAME onto a new axis (zones newly
        appearing start at zero, vanished ones are dropped). Used by the
        fleet aggregator when the canonical zone union changes."""
        old_arr = self.arr
        nz = len(new_names)
        arr = np.zeros((max(len(old_arr), 64), nz))
        old_idx = {zn: j for j, zn in enumerate(old_names)}
        for j, zn in enumerate(new_names):
            oj = old_idx.get(zn)
            if oj is not None and len(old_arr):
                arr[:len(old_arr), j] = old_arr[:, oj]
        self._z = nz
        self.arr = arr
        used = set(self.rows.values())
        self._free = [r for r in range(len(arr) - 1, -1, -1)
                      if r not in used]
        self._cached = None
