"""Structured logging setup.

Reference parity: ``internal/logger/logger.go:16-76`` — slog text/json
handlers with source-path trimming and a package-level log level. Python
idiom: stdlib ``logging`` with a compact text formatter or a JSON formatter.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import IO

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
}


class JSONFormatter(logging.Formatter):
    """RFC3339 UTC timestamps with millisecond precision, plus the
    thread name — a JSON log line must be correlatable with the
    telemetry plane's traces (/debug/traces anchors are wall-clock) and
    with logs from other nodes, which second-granularity localtime with
    no offset made impossible."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "time": "%s.%03dZ" % (
                time.strftime("%Y-%m-%dT%H:%M:%S",
                              time.gmtime(record.created)),
                int(record.msecs),
            ),
            "level": record.levelname,
            "logger": record.name,
            "thread": record.threadName,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


class TextFormatter(logging.Formatter):
    def __init__(self) -> None:
        super().__init__(
            fmt="%(asctime)s %(levelname)-5s %(name)s %(message)s",
            datefmt="%H:%M:%S",
        )


def new_logger(
    level: str = "info",
    fmt: str = "text",
    stream: IO[str] | None = None,
) -> logging.Logger:
    """Configure and return the root ``kepler`` logger.

    ``stream`` defaults to stdout; the stdout exporter reroutes logs to stderr
    (reference ``cmd/kepler/main.go:34-38``).
    """
    if level not in _LEVELS:
        raise ValueError(f"invalid log level {level!r}")
    if fmt not in ("text", "json"):
        raise ValueError(f"invalid log format {fmt!r}")
    logger = logging.getLogger("kepler")
    logger.setLevel(_LEVELS[level])
    logger.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stdout)
    handler.setFormatter(JSONFormatter() if fmt == "json" else TextFormatter())
    logger.addHandler(handler)
    logger.propagate = False
    return logger
