"""Shared utilities: logging."""

from kepler_tpu.utils.logger import new_logger

__all__ = ["new_logger"]
