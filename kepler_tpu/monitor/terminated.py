"""Terminated-workload tracker: top-N by primary-zone energy.

Reference parity: ``internal/monitor/terminated_resource_tracker.go`` —
generic tracker keyed on primary-zone energy with a min-energy threshold;
``max_size`` semantics: 0 = tracking off, <0 = unbounded, >0 = keep top-N;
``clear()`` after the exporter has consumed the data.

Instead of a per-item min-heap, candidates accumulate in dense columns and
one masked top-k (``ops.topk``) selects survivors per refresh.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from kepler_tpu.monitor.snapshot import WorkloadTable
from kepler_tpu.ops.topk import top_k_by_energy


class TerminatedTracker:
    def __init__(
        self,
        n_zones: int,
        primary_zone_index: int,
        max_size: int = 500,
        min_energy_uj: float = 10e6,  # 10 J default (config.go:210-211)
    ) -> None:
        self._n_zones = n_zones
        self._primary = primary_zone_index
        self._max_size = max_size
        self._min_energy = min_energy_uj
        self._ids: list[str] = []
        self._meta: list[Mapping[str, str]] = []
        self._energy: list[np.ndarray] = []
        self._power: list[np.ndarray] = []
        self._seconds: list[float] = []  # process kind; 0.0 elsewhere
        self._has_seconds = False
        self._known: set[str] = set()

    def __len__(self) -> int:
        return len(self._ids)

    def add_batch(self, table: WorkloadTable) -> None:
        """Add terminated workloads (with their final cumulative usage)."""
        if self._max_size == 0:
            return
        if table.seconds is not None:
            self._has_seconds = True
        for i, wid in enumerate(table.ids):
            if wid in self._known:
                continue
            energy = table.energy_uj[i]
            if energy[self._primary] < self._min_energy:
                continue
            self._known.add(wid)
            self._ids.append(wid)
            self._meta.append(table.meta[i])
            self._energy.append(np.asarray(energy, dtype=np.float64))
            self._power.append(np.asarray(table.power_uw[i], np.float64))
            self._seconds.append(float(table.seconds[i])
                                 if table.seconds is not None else 0.0)
        self._compact()

    def _compact(self) -> None:
        if self._max_size < 0 or len(self._ids) <= self._max_size:
            return
        primary = np.array([e[self._primary] for e in self._energy])
        keep = top_k_by_energy(primary, self._max_size, self._min_energy)
        keep_set = sorted(keep.tolist())
        self._ids = [self._ids[i] for i in keep_set]
        self._meta = [self._meta[i] for i in keep_set]
        self._energy = [self._energy[i] for i in keep_set]
        self._power = [self._power[i] for i in keep_set]
        self._seconds = [self._seconds[i] for i in keep_set]
        self._known = set(self._ids)

    def items(self) -> WorkloadTable:
        if not self._ids:
            return WorkloadTable.empty(self._n_zones)
        return WorkloadTable(
            ids=tuple(self._ids),
            meta=tuple(self._meta),
            energy_uj=np.stack(self._energy),
            power_uw=np.stack(self._power),
            seconds=(np.asarray(self._seconds)
                     if self._has_seconds else None),
        )

    def clear(self) -> None:
        self._ids.clear()
        self._meta.clear()
        self._energy.clear()
        self._power.clear()
        self._seconds.clear()
        self._known.clear()
