"""Monitor layer: attribution service + snapshots (reference
``internal/monitor/``)."""

from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.monitor.snapshot import (
    NodeUsage,
    Snapshot,
    WorkloadRow,
    WorkloadTable,
)
from kepler_tpu.monitor.terminated import TerminatedTracker
from kepler_tpu.monitor.watchdog import MonitorWatchdog

__all__ = [
    "MonitorWatchdog",
    "NodeUsage",
    "PowerMonitor",
    "Snapshot",
    "TerminatedTracker",
    "WorkloadRow",
    "WorkloadTable",
]
