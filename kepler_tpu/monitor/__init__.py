"""Monitor layer: attribution service + snapshots (reference
``internal/monitor/``)."""

from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.monitor.snapshot import (
    NodeUsage,
    Snapshot,
    WorkloadRow,
    WorkloadTable,
)
from kepler_tpu.monitor.terminated import TerminatedTracker

__all__ = [
    "NodeUsage",
    "PowerMonitor",
    "Snapshot",
    "TerminatedTracker",
    "WorkloadRow",
    "WorkloadTable",
]
