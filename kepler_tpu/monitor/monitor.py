"""PowerMonitor: the attribution service.

Reference parity: ``internal/monitor/monitor.go`` — owns the refresh loop;
``snapshot()`` API with staleness check + singleflight dedup (:265-302
double-check pattern); atomic snapshot publication; ``data_channel`` signal
for exporter readiness; ``exported`` flag gating terminated-workload
clearing; self-rescheduling timer (:229-251).

Per refresh (reference refreshSnapshot :317-356 → calculate*Power):
1. host: read each zone's counter, exact wraparound delta (``ops.deltas``);
   failed zones are masked out this window (node.go:39-44 analog);
2. host: ``resources.refresh()`` → dense ``FeatureBatch``;
3. device: ONE jitted ``ops.attribute`` call computes the node active/idle
   split and every workload's energy/power share — the reference's four
   per-kind loops fused into a single [W,Z] outer product, padded to a
   bucketed shape so ragged workload counts don't recompile;
4. host: scatter window deltas into cumulative f64 accumulators, build the
   immutable ``Snapshot``; move terminated workloads into top-k trackers.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from dataclasses import dataclass
from typing import Callable, Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from kepler_tpu import fault, telemetry
from kepler_tpu.device.meter import CPUPowerMeter, EnergyZone
from kepler_tpu.monitor.snapshot import NodeUsage, Snapshot, WorkloadTable
from kepler_tpu.monitor.terminated import TerminatedTracker
from kepler_tpu.ops.attribution import attribute, pad_to_bucket
from kepler_tpu.ops.deltas import energy_delta
from kepler_tpu.resource.informer import FeatureBatch, ResourceInformer
from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.monitor")


class SnapshotUnavailableError(RuntimeError):
    """No snapshot exists and the refresh that would create one failed.

    Raised from ``PowerMonitor.snapshot()`` only when there is no stale
    snapshot to degrade to (the reference serves stale data on refresh
    failure when it can — :185-200); collectors catch this to render a
    scrape error rather than propagate a raw traceback."""

_UNSET = object()  # "batch plan not yet computed" (None = computed, absent)

_KINDS = ("processes", "containers", "virtual_machines", "pods")
_KIND_CODES = (
    FeatureBatch.KIND_PROCESS,
    FeatureBatch.KIND_CONTAINER,
    FeatureBatch.KIND_VM,
    FeatureBatch.KIND_POD,
)


# cumulative per-workload accumulators (shared with the fleet
# aggregator's per-node totals — one row-store implementation)
from kepler_tpu.utils.rowstore import RowStore as _CumStore  # noqa: E402


@dataclass(frozen=True)
class WindowSample:
    """Raw per-refresh inputs, before attribution — the feature rows a fleet
    agent streams to the cluster aggregator (SURVEY §5 "distributed
    communication backend": per-node agents producing `[pods × features]`
    rows; the aggregator batches them into `[nodes × pods × features]`)."""

    timestamp: float
    dt_s: float
    zone_names: tuple[str, ...]
    zone_deltas_uj: np.ndarray  # f64 [Z] this window
    zone_valid: np.ndarray  # bool [Z]
    usage_ratio: float
    batch: FeatureBatch


class PowerMonitor:
    def __init__(
        self,
        meter: CPUPowerMeter,
        resources: ResourceInformer,
        interval: float = 5.0,
        staleness: float = 0.5,
        max_terminated: int = 500,
        min_terminated_energy_uj: float = 10e6,
        workload_bucket: int = 256,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
        state_path: str = "",
        state_max_age: float = 60.0,
    ) -> None:
        self._meter = meter
        self._resources = resources
        self._interval = interval
        self._staleness = staleness
        self._max_terminated = max_terminated
        self._min_terminated_energy_uj = min_terminated_energy_uj
        self._bucket = workload_bucket
        self._clock = clock or _time.time  # wall: timestamps/staleness
        # dt for power uses a monotonic source so NTP steps can't inflate
        # watts; tests inject the same fake for both
        self._monotonic = monotonic or (clock if clock else _time.monotonic)

        # counter-state persistence: with a state_path, the last raw
        # counter readings + a wall-clock anchor survive restarts, so the
        # first post-restart window attributes the energy consumed ACROSS
        # the restart instead of discarding it as a fresh seeding read
        self._state_path = state_path
        self._state_max_age = max(0.0, state_max_age)

        self._zones: list[EnergyZone] = []
        self._zone_names: tuple[str, ...] = ()
        self._prev_counters: list[int | None] = []  # keplint: guarded-by=_snapshot_lock
        self._batch_plan = _UNSET  # lazily-resolved native zone-read plan
        self._last_read_ts: float | None = None

        # cumulative f64 accumulators: kind → dense row store (id-keyed)
        self._cumulative: dict[str, _CumStore] = {}
        # per-kind meta tuple cache: (meta_gen, view-dict ref, rows);
        # validated by gen equality + dict IDENTITY (see _meta_rows)
        self._meta_rows_cache: dict[str, tuple] = {}
        self._node_energy = np.zeros(0)
        self._node_active = np.zeros(0)
        self._node_idle = np.zeros(0)

        self._trackers: dict[str, TerminatedTracker] = {}
        # workload-bucket shapes whose attribution program is (being)
        # compiled — used to pre-warm the NEXT bucket in the background
        # so a churn burst crossing a bucket boundary doesn't pay an XLA
        # compile inside its refresh
        self._warmed_buckets: set[int] = set()
        # padded attribution staging, reused across refreshes (the
        # node-side analog of the aggregator's delta-H2D slice: a steady
        # window rewrites only the live prefix + the stale tail slice; a
        # churn burst that crosses a bucket boundary reallocates once)
        self._cpu_stage = np.zeros(0, np.float32)
        self._valid_stage = np.zeros(0, bool)
        self._stage_live = 0  # rows of the staging prefix in use
        self._window_listeners: list[Callable[[WindowSample], None]] = []
        self._snapshot: Snapshot | None = None  # keplint: guarded-by=_snapshot_lock
        self._snapshot_lock = threading.Lock()  # singleflight for refresh
        self._exported = False
        self._data_event = threading.Event()  # reference dataCh signal
        # watchdog plane: when the refresh loop stalls (wedged meter,
        # deadlocked informer), MonitorWatchdog flips _stalled so /healthz
        # reports the published snapshot as stale; a completed refresh
        # clears it
        self._last_refresh_done: float | None = None  # monotonic
        self._stalled = False

    # -- service lifecycle -------------------------------------------------

    def name(self) -> str:
        return "power-monitor"

    def init(self) -> None:
        """Probe zones, seed counters, create trackers (reference Init
        :118-150)."""
        if hasattr(self._meter, "init"):
            self._meter.init()
        self._zones = list(self._meter.zones())
        self._zone_names = tuple(z.name() for z in self._zones)
        self._batch_plan = _UNSET  # re-resolve against the new zone list
        z = len(self._zones)
        self._prev_counters = [None] * z
        self._node_energy = np.zeros(z)
        self._node_active = np.zeros(z)
        self._node_idle = np.zeros(z)
        primary = self._meter.primary_energy_zone().name()
        primary_idx = self._zone_names.index(primary)
        for kind in _KINDS:
            store = self._cumulative.get(kind)
            if store is None or store.arr.shape[1] != z:
                self._cumulative[kind] = _CumStore(z)
            self._trackers[kind] = TerminatedTracker(
                n_zones=z,
                primary_zone_index=primary_idx,
                max_size=self._max_terminated,
                min_energy_uj=self._min_terminated_energy_uj,
            )
        self._restore_state()
        log.info("monitor initialized: zones=%s primary=%s",
                 self._zone_names, primary)

    def run(self, ctx: CancelContext) -> None:
        """Self-rearming collection loop (reference collectionLoop :218)."""
        if self._interval <= 0:
            ctx.wait(None)
            return
        while not ctx.cancelled():
            try:
                self.refresh()
            except Exception:
                log.exception("refresh failed")
            if ctx.wait(self._interval):
                return

    def shutdown(self) -> None:
        self.join_prewarm()

    # -- read API (reference PowerDataProvider) ----------------------------

    def zone_names(self) -> Sequence[str]:
        return self._zone_names

    def data_channel(self) -> threading.Event:
        """Set once the first snapshot exists (collector readiness gate)."""
        return self._data_event

    def add_window_listener(
            self, listener: Callable[[WindowSample], None]) -> None:
        """Subscribe to raw per-window samples (fleet agent feed). Listeners
        run inside the refresh lock — they must be fast and non-blocking
        (the agent just enqueues)."""
        self._window_listeners.append(listener)

    def last_refresh_age(self) -> float | None:
        """Monotonic seconds since the last COMPLETED refresh (None before
        the first). The watchdog's stall signal."""
        done = self._last_refresh_done
        if done is None:
            return None
        return self._monotonic() - done

    def mark_stalled(self, stalled: bool) -> None:
        """Watchdog hook: flag the published snapshot as stale because the
        refresh loop stopped making progress."""
        self._stalled = stalled

    @property
    def stalled(self) -> bool:
        return self._stalled

    def health(self) -> dict:
        """Probe for /healthz: not-ok while the watchdog flags a stall."""
        out: dict = {"ok": not self._stalled, "stalled": self._stalled,
                     "snapshot": self._snapshot is not None}
        age = self.last_refresh_age()
        if age is not None:
            out["last_refresh_age_s"] = round(age, 3)
        return out

    def snapshot(self, clone: bool = True) -> Snapshot:
        """Return a deep-cloned, fresh snapshot.

        ``clone=False`` returns the published object itself — safe for
        read-only consumers because a published snapshot is never mutated
        (every refresh builds new arrays/dicts and swaps the reference);
        the exporter's direct text render uses it to skip a 10k-row deep
        copy per scrape. External callers should keep the default.

        Freshness contract (reference :185-200, :254-302): if the current
        snapshot is older than ``staleness``, refresh first; concurrent
        callers dedupe on a lock with a double-check so at most one refresh
        runs (singleflight). Degradation contract: if the refresh fails
        (meter died between init and scrape) a stale snapshot, when one
        exists, is served with a warning — matching the reference's
        serve-stale-on-error stance; with no snapshot at all the failure
        surfaces as ``SnapshotUnavailableError`` so the collector can
        render a scrape error instead of a raw traceback.
        """
        snap = self._snapshot
        if snap is None or not self._is_fresh():
            with self._snapshot_lock:
                if not self._is_fresh():  # double-check under the lock
                    try:
                        self._refresh_locked()
                    except Exception as err:
                        if self._snapshot is None:
                            raise SnapshotUnavailableError(
                                f"first refresh failed: {err}") from err
                        log.warning("refresh failed (%s); serving stale "
                                    "snapshot", err)
            snap = self._snapshot
        assert snap is not None
        self._exported = True  # terminated data now consumable→clearable
        return snap.clone() if clone else snap

    def _is_fresh(self) -> bool:
        snap = self._snapshot
        if snap is None:
            return False
        return (self._clock() - snap.timestamp) <= self._staleness

    # -- refresh -----------------------------------------------------------

    def refresh(self) -> None:
        with self._snapshot_lock:
            self._refresh_locked()

    # keplint: hot-loop
    # keplint: requires-lock=_snapshot_lock
    def _refresh_locked(self) -> None:
        # the whole refresh is one telemetry CYCLE; the stage spans below
        # feed kepler_self_stage_duration_seconds and the /debug/traces
        # ring, and exceeding one interval counts a cycle overrun. Cycle
        # timing has ONE source of truth now — the span sink (which also
        # emits the "monitor.refresh done in …" debug log the old inline
        # perf_counter line used to).
        budget = self._interval if self._interval > 0 else None
        with telemetry.span("monitor.refresh", budget_s=budget):
            self._refresh_staged()

    # keplint: hot-loop
    # keplint: requires-lock=_snapshot_lock
    def _refresh_staged(self) -> None:
        now = self._clock()
        mono = self._monotonic()
        dt = (mono - self._last_read_ts
              if self._last_read_ts is not None else 0.0)
        self._last_read_ts = mono

        with telemetry.span("monitor.device_read"):
            zone_deltas, zone_valid = self._read_zone_deltas()
        with telemetry.span("monitor.resource_scan"):
            self._resources.refresh()
            batch = self._resources.feature_batch()

        with telemetry.span("monitor.attribute"):
            w = batch.cpu_deltas.shape[0]
            padded_w = pad_to_bucket(w, self._bucket)
            if self._cpu_stage.shape[0] != padded_w:
                self._cpu_stage = np.zeros(padded_w, np.float32)
                self._valid_stage = np.zeros(padded_w, bool)
                self._stage_live = 0
            cpu, valid = self._cpu_stage, self._valid_stage
            cpu[:w] = batch.cpu_deltas
            valid[:w] = True
            if self._stage_live > w:  # clear the shrunk tail only
                cpu[w:self._stage_live] = 0.0
                valid[w:self._stage_live] = False
            self._stage_live = w

            result = attribute(
                jnp.asarray(zone_deltas, jnp.float32),
                jnp.asarray(zone_valid),
                jnp.float32(batch.usage_ratio),
                jnp.asarray(cpu),
                jnp.asarray(valid),
                jnp.float32(batch.node_cpu_delta),
                jnp.float32(max(dt, 0.0)),
            )

            node = self._accumulate_node(result, batch.usage_ratio)
            tables = self._accumulate_workloads(batch, result, w)
            self._handle_terminated(tables)

        with telemetry.span("monitor.publish"):
            self._snapshot = Snapshot(
                timestamp=now,
                node=node,
                terminated_processes=self._trackers["processes"].items(),
                terminated_containers=self._trackers["containers"].items(),
                terminated_virtual_machines=self._trackers[
                    "virtual_machines"].items(),
                terminated_pods=self._trackers["pods"].items(),
                **tables,
            )
            self._data_event.set()
            if self._window_listeners:
                sample = WindowSample(
                    timestamp=now, dt_s=max(dt, 0.0),
                    zone_names=self._zone_names,
                    zone_deltas_uj=zone_deltas, zone_valid=zone_valid,
                    usage_ratio=batch.usage_ratio, batch=batch,
                )
                for listener in self._window_listeners:
                    try:
                        listener(sample)
                    except Exception:
                        log.exception("window listener failed")
        self._maybe_prewarm_next_bucket(w, padded_w)
        if self._state_path:
            with telemetry.span("monitor.persist"):
                self._persist_state(now)
        self._last_refresh_done = self._monotonic()
        if self._stalled:
            log.info("refresh loop recovered; clearing stall flag")
            self._stalled = False

    def _maybe_prewarm_next_bucket(self, w: int, padded_w: int) -> None:
        """When the workload count nears its bucket, compile the next
        bucket's attribution program on a background thread — GRADUAL
        growth that crosses one boundary then finds the program ready
        instead of paying the XLA compile in-line (measured ~165 ms on
        CPU at the 10k shape). A burst jumping several buckets at once
        still pays one in-line compile for its new shape unless the
        persistent cache has seen it (tpu.compilationCacheDir)."""
        self._warmed_buckets.add(padded_w)
        if w < 0.75 * padded_w:
            return
        nxt = padded_w + self._bucket
        if nxt in self._warmed_buckets:
            return
        self._warmed_buckets.add(nxt)
        z = len(self._zones)

        def warm() -> None:
            try:
                attribute(
                    jnp.zeros(z, jnp.float32), jnp.ones(z, bool),
                    jnp.float32(0.5), jnp.zeros(nxt, jnp.float32),
                    jnp.zeros(nxt, bool), jnp.float32(1.0),
                    jnp.float32(1.0),
                ).node.energy_uj.block_until_ready()
            except Exception as err:  # never break serving on a warmup
                log.debug("bucket prewarm failed: %s", err)

        # non-daemon: a daemon thread killed mid-XLA-compile at
        # interpreter exit aborts the process ("exception not rethrown");
        # shutdown() joins it instead
        t = threading.Thread(target=warm, name="kepler-bucket-prewarm",
                             daemon=False)
        # track EVERY live prewarm, not just the latest: two quick bucket
        # crossings can overlap compiles, and join_prewarm/shutdown must
        # wait for all of them or an orphan non-daemon thread outlives
        # shutdown() and delays interpreter exit
        self._prewarm_threads = [
            p for p in getattr(self, "_prewarm_threads", [])
            if p.is_alive()]
        self._prewarm_threads.append(t)
        t.start()

    def join_prewarm(self, timeout: float | None = None) -> None:
        """Wait for ALL in-flight bucket prewarms (benchmarks/tests: keep
        the background compiles out of timed windows)."""
        deadline = (None if timeout is None
                    else _time.perf_counter() + timeout)
        for t in getattr(self, "_prewarm_threads", []):
            if deadline is None:
                t.join()
            else:
                t.join(max(0.0, deadline - _time.perf_counter()))

    def _zone_batch_plan(self):
        """(paths, per-zone slices) when EVERY zone supports batched raw
        reads AND the native library is present — else None. Computed once;
        one C call then replaces Z×(open+read+close) Python file reads per
        tick."""
        if self._batch_plan is not _UNSET:
            return self._batch_plan
        plan = None
        if not all(hasattr(z, "energy_paths") for z in self._zones):
            self._batch_plan = None  # fake/mock zones: no fast path
            return None
        try:
            from kepler_tpu.native import scanner

            native = scanner()
            if native is not None:
                paths: list[str] = []
                slices: list[slice] = []
                for zone in self._zones:
                    zp = zone.energy_paths()
                    slices.append(slice(len(paths), len(paths) + len(zp)))
                    paths.extend(zp)
                if paths:
                    plan = (native, paths, slices)
        except Exception as err:  # native build failure etc.
            log.debug("no batched zone reads: %s", err)
            plan = None
        self._batch_plan = plan
        return plan

    # keplint: hot-loop
    def _read_zone_energies(self) -> list[int | None]:
        """Current raw counter per zone (None = failed read this tick)."""
        out: list[int | None] = []
        plan = self._zone_batch_plan()
        if plan is not None:
            native, paths, slices = plan
            raw = native.read_counters(paths)
            for zone, sl in zip(self._zones, slices):
                try:
                    out.append(int(zone.energy_from_raw(raw[sl].tolist())))
                except (OSError, ValueError) as err:
                    log.warning("zone %s read failed: %s", zone.name(), err)
                    out.append(None)
            return out
        for zone in self._zones:
            try:
                out.append(int(zone.energy()))
            except (OSError, ValueError) as err:
                log.warning("zone %s read failed: %s", zone.name(), err)
                out.append(None)
        return out

    # keplint: hot-loop
    # keplint: requires-lock=_snapshot_lock
    def _read_zone_deltas(self) -> tuple[np.ndarray, np.ndarray]:
        z = len(self._zones)
        deltas = np.zeros(z, np.float64)
        valid = np.zeros(z, bool)
        for i, (zone, current) in enumerate(
                zip(self._zones, self._read_zone_energies())):
            if current is not None:
                # chaos-harness injection points: a read error masks the
                # zone this window (exactly like a real failed read); a
                # counter wrap forces the wraparound-delta path
                if fault.fire("device.read_error") is not None:
                    log.warning("fault: injected read error on zone %s",
                                zone.name())
                    current = None
                else:
                    spec = fault.fire("device.counter_wrap")
                    if spec is not None:
                        current = int(spec.arg or 0) % max(
                            1, int(zone.max_energy()))
            if current is None:
                continue  # stays masked this window
            prev = self._prev_counters[i]
            self._prev_counters[i] = current
            if prev is None:
                continue  # first reading seeds only (reference firstNodeRead)
            deltas[i] = energy_delta(current, prev, int(zone.max_energy()))
            valid[i] = True
        return deltas, valid

    # -- counter-state persistence (restart without losing a window) -------

    @staticmethod
    def _boot_id() -> str:
        """Kernel boot identity: RAPL counters reset on reboot, so a
        baseline from a previous boot must never be adopted — the wrap
        math would read the reset as a wrap and fabricate up to a full
        counter range of energy. Empty when unreadable (non-Linux): the
        check then degrades to the staleness bound alone."""
        try:
            with open("/proc/sys/kernel/random/boot_id",
                      encoding="ascii") as fh:
                return fh.read().strip()
        except OSError:
            return ""

    # keplint: role-boundary — the per-refresh atomic write of the tiny
    # counter-state file IS the durability contract (PR 3); local disk,
    # bounded size, failures never break refresh
    def _persist_state(self, now: float) -> None:
        """Write the raw counter baseline + wall anchor, atomically.

        No fsync: losing the newest state file on a power cut only means
        the next start seeds counters like a cold boot — correct, just
        one window poorer. Failures are logged and never break refresh."""
        from kepler_tpu.utils.atomicio import atomic_write_json

        state = {"v": 1, "saved_at": now,
                 "boot_id": self._boot_id(),
                 "zone_names": list(self._zone_names),
                 "counters": list(self._prev_counters)}
        try:
            atomic_write_json(self._state_path, state)
        except OSError as err:
            log.warning("monitor state persist failed: %s", err)

    # called from init() before any other thread exists; the annotation
    # records that it writes the lock-guarded counter baseline
    # keplint: requires-lock=_snapshot_lock
    def _restore_state(self) -> None:
        """Adopt a fresh state file's counter baseline at startup.

        The restored counters make the FIRST refresh a real window (delta
        since the previous process's last reading — wrap-aware, because
        ``_read_zone_deltas`` already routes through ``energy_delta``),
        and the wall anchor back-dates the monotonic read timestamp so
        dt covers the restart gap. Anything suspicious — missing file,
        unparseable JSON, zone-set change, stale or future ``saved_at`` —
        is IGNORED with a warning: a state file must never be able to
        prevent startup, and a stale baseline would attribute energy from
        a long-dead window to the first post-restart one."""
        import json

        if not self._state_path:
            return
        try:
            with open(self._state_path, encoding="utf-8") as fh:
                state = json.load(fh)
        except FileNotFoundError:
            return
        except (OSError, ValueError) as err:
            log.warning("monitor state file unreadable (%s); seeding "
                        "counters from scratch", err)
            return
        try:
            if not isinstance(state, dict) or state.get("v") != 1:
                raise ValueError(f"unsupported version {state.get('v')!r}")
            saved_at = state["saved_at"]
            if isinstance(saved_at, bool) or not isinstance(
                    saved_at, (int, float)):
                raise ValueError("saved_at must be a number")
            zone_names = state["zone_names"]
            counters = state["counters"]
            if not (isinstance(zone_names, list)
                    and isinstance(counters, list)
                    and len(zone_names) == len(counters)):
                raise ValueError("zone_names/counters malformed")
            for c in counters:
                if c is not None and (isinstance(c, bool)
                                      or not isinstance(c, int) or c < 0):
                    raise ValueError(f"bad counter value {c!r}")
        except (ValueError, KeyError, TypeError) as err:
            log.warning("monitor state file invalid (%s); seeding "
                        "counters from scratch", err)
            return
        now = self._clock()
        age = now - float(saved_at)
        # state_max_age == 0 means unbounded (this codebase's 0-disables
        # convention, like aggregator.skewTolerance); negative age means
        # the wall clock stepped backwards — never trust that baseline
        if age < 0 or (self._state_max_age > 0
                       and age > self._state_max_age):
            log.warning("monitor state is %.1fs old (bound %.1fs); "
                        "seeding counters from scratch", age,
                        self._state_max_age)
            return
        if tuple(zone_names) != self._zone_names:
            log.warning("monitor state zone set %s != current %s; "
                        "seeding counters from scratch",
                        zone_names, list(self._zone_names))
            return
        saved_boot = state.get("boot_id", "")
        if saved_boot != self._boot_id():
            # a reboot inside stateMaxAge: the counters RESET, they did
            # not wrap — adopting the old baseline would fabricate up to
            # a full counter range of energy in the first window
            log.warning("monitor state is from a previous boot; "
                        "seeding counters from scratch")
            return
        self._prev_counters = [None if c is None else int(c)
                               for c in counters]
        # back-date the monotonic read anchor so the first window's dt
        # spans the restart (power = energy / dt must use the real gap)
        self._last_read_ts = self._monotonic() - age
        log.info("monitor state restored (age %.1fs): first window "
                 "attributes across the restart", age)

    def _accumulate_node(self, result, usage_ratio: float) -> NodeUsage:
        n = result.node
        energy = np.asarray(n.energy_uj, np.float64)
        active = np.asarray(n.active_uj, np.float64)
        idle = np.asarray(n.idle_uj, np.float64)
        self._node_energy += energy
        self._node_active += active
        self._node_idle += idle
        return NodeUsage(
            zone_names=self._zone_names,
            energy_uj=self._node_energy.copy(),
            active_uj=self._node_active.copy(),
            idle_uj=self._node_idle.copy(),
            power_uw=np.asarray(n.power_uw, np.float64),
            active_power_uw=np.asarray(n.active_power_uw, np.float64),
            idle_power_uw=np.asarray(n.idle_power_uw, np.float64),
            window_active_uj=active,
            usage_ratio=float(usage_ratio),
        )

    @staticmethod
    def _process_meta(p) -> Mapping[str, str]:
        m = p.meta_cache
        if m is None:
            m = {"comm": p.comm, "exe": p.exe,
                 "type": ("container" if p.container else
                          "vm" if p.virtual_machine else "regular"),
                 "container_id": p.container.id if p.container else "",
                 "vm_id": (p.virtual_machine.id
                           if p.virtual_machine else "")}
            p.meta_cache = m
        return m

    @staticmethod
    def _container_meta(c) -> Mapping[str, str]:
        m = c.meta_cache
        if m is None:
            m = {"container_name": c.name, "runtime": c.runtime.value,
                 "pod_id": c.pod_id or ""}
            c.meta_cache = m
        return m

    @staticmethod
    def _vm_meta(v) -> Mapping[str, str]:
        m = v.meta_cache
        if m is None:
            m = {"vm_name": v.name, "hypervisor": v.hypervisor.value}
            v.meta_cache = m
        return m

    @staticmethod
    def _pod_meta(p) -> Mapping[str, str]:
        m = p.meta_cache
        if m is None:
            m = {"pod_name": p.name, "namespace": p.namespace}
            p.meta_cache = m
        return m

    def _meta_rows(self, kind: str) -> tuple[Mapping[str, str], ...]:
        """Label dicts for the running workloads of ``kind``, in informer
        view order (== feature-batch row order: both walk the same dicts).
        Dicts are cached on the objects and invalidated by the informer on
        identity changes; the whole tuple is reused between ticks while the
        informer's ``meta_gen`` and the view dict are unchanged."""
        res = self._resources
        if kind == "processes":
            running, f = res.processes().running, self._process_meta
        elif kind == "containers":
            running, f = res.containers().running, self._container_meta
        elif kind == "virtual_machines":
            running, f = res.virtual_machines().running, self._vm_meta
        else:
            # pods' running dict is rebuilt every tick — not cacheable by
            # identity, and small; always materialize
            return tuple(self._pod_meta(p)
                         for p in res.pods().running.values())
        gen = getattr(res, "meta_gen", None)
        if gen is None:
            return tuple(f(o) for o in running.values())
        # The cache entry holds a STRONG reference to the view dict and
        # validates it with ``is``: identity then guarantees membership
        # AND iteration order are unchanged (a dict is append-ordered and
        # the informer never reorders in place), while ``meta_gen``
        # covers in-place label mutations. An id()-based key would be
        # unsound on the legacy informer path, which builds a fresh dict
        # every tick — a recycled address plus an unchanged gen could
        # serve another membership's meta rows.
        cached = self._meta_rows_cache.get(kind)
        if (cached is not None and cached[0] == gen
                and cached[1] is running):
            return cached[2]
        rows = tuple(f(o) for o in running.values())
        self._meta_rows_cache[kind] = (gen, running, rows)
        return rows

    # keplint: hot-loop
    def _accumulate_workloads(self, batch: FeatureBatch, result, w: int
                              ) -> dict[str, WorkloadTable]:
        energy_delta_wz = np.asarray(result.workloads.energy_uj,
                                     np.float64)[:w]
        power_wz = np.asarray(result.workloads.power_uw, np.float64)[:w]
        tables: dict[str, WorkloadTable] = {}
        kinds = batch.kinds
        offsets = batch.kind_offsets
        nz = len(self._zone_names)
        for k, (kind_name, kind_code) in enumerate(zip(_KINDS, _KIND_CODES)):
            if offsets is not None:
                sl = slice(offsets[k], offsets[k + 1])
                ids = tuple(batch.ids[sl])
                idx: slice | np.ndarray = sl
            else:
                nz_idx = np.nonzero(kinds == kind_code)[0]
                ids = tuple(batch.ids[i] for i in nz_idx)
                idx = nz_idx
            store = self._cumulative[kind_name]
            n = len(ids)
            power_rows = power_wz[idx] if n else np.zeros((0, nz))
            # PRECONDITION: ids within a kind are unique (they come from
            # dict-keyed informer views) — a duplicate would silently drop
            # one delta in the last-writer-wins scatter inside the store,
            # so fail loudly (not assert: -O must not change accounting).
            # The check is O(1) when the id tuple is unchanged (cached).
            if n:
                energy_rows = store.accumulate(ids, energy_delta_wz[idx])
            else:
                energy_rows = np.zeros((0, nz))
            meta_rows = self._meta_rows(kind_name)
            if len(meta_rows) != n:
                raise ValueError(
                    f"{kind_name}: feature batch has {n} rows but the "
                    f"informer view has {len(meta_rows)} — views and "
                    "batch must be built from the same refresh")
            seconds = None
            if kind_name == "processes" and batch.cpu_totals is not None:
                seconds = np.asarray(batch.cpu_totals[idx], np.float64)
            # terminated ids stay in the store until _handle_terminated
            # has captured their final cumulative values
            tables[kind_name] = WorkloadTable(
                ids=ids,
                meta=meta_rows,
                energy_uj=energy_rows,
                power_uw=power_rows,
                seconds=seconds,
            )
        return tables

    def _terminated_views(self) -> dict[str, WorkloadTable]:
        """Final cumulative usage of workloads that vanished this refresh.
        Labels come straight from the informer's terminated objects (their
        cached meta survives termination)."""
        res = self._resources
        views: dict[str, WorkloadTable] = {}
        term = {
            "processes": [(str(pid), p, self._process_meta)
                          for pid, p in res.processes().terminated.items()],
            "containers": [(cid, c, self._container_meta)
                           for cid, c in res.containers()
                           .terminated.items()],
            "virtual_machines": [(vid, v, self._vm_meta)
                                 for vid, v in res.virtual_machines()
                                 .terminated.items()],
            "pods": [(pid_, p, self._pod_meta)
                     for pid_, p in res.pods().terminated.items()],
        }
        nz = len(self._zone_names)
        for kind in _KINDS:
            store = self._cumulative[kind]
            rows = [(wid, obj, f) for wid, obj, f in term[kind]
                    if wid in store]
            ids = tuple(wid for wid, _, _ in rows)
            energy = (np.stack([store.value(wid) for wid in ids])
                      if ids else np.zeros((0, nz)))
            seconds = None
            if kind == "processes":
                seconds = np.asarray(
                    [obj.cpu_total_time for _, obj, _ in rows], np.float64)
            views[kind] = WorkloadTable(
                ids=ids,
                meta=tuple(f(obj) for _, obj, f in rows),
                energy_uj=energy,
                power_uw=np.zeros((len(ids), nz)),
                seconds=seconds,
            )
        return views

    # keplint: hot-loop
    def _handle_terminated(self, tables: dict[str, WorkloadTable]) -> None:
        """Clear-after-export then absorb this window's terminated workloads
        (reference refreshSnapshot: exported flag gates clearing)."""
        views = self._terminated_views()
        for kind in _KINDS:
            if self._exported:
                self._trackers[kind].clear()
            self._trackers[kind].add_batch(views[kind])
        if self._exported:
            self._exported = False
        # now that final values are tracked, drop them from the stores
        for kind in _KINDS:
            store = self._cumulative[kind]
            for wid in views[kind].ids:
                store.pop(wid)
