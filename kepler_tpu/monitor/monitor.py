"""PowerMonitor: the attribution service.

Reference parity: ``internal/monitor/monitor.go`` — owns the refresh loop;
``snapshot()`` API with staleness check + singleflight dedup (:265-302
double-check pattern); atomic snapshot publication; ``data_channel`` signal
for exporter readiness; ``exported`` flag gating terminated-workload
clearing; self-rescheduling timer (:229-251).

Per refresh (reference refreshSnapshot :317-356 → calculate*Power):
1. host: read each zone's counter, exact wraparound delta (``ops.deltas``);
   failed zones are masked out this window (node.go:39-44 analog);
2. host: ``resources.refresh()`` → dense ``FeatureBatch``;
3. device: ONE jitted ``ops.attribute`` call computes the node active/idle
   split and every workload's energy/power share — the reference's four
   per-kind loops fused into a single [W,Z] outer product, padded to a
   bucketed shape so ragged workload counts don't recompile;
4. host: scatter window deltas into cumulative f64 accumulators, build the
   immutable ``Snapshot``; move terminated workloads into top-k trackers.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from dataclasses import dataclass
from typing import Callable, Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from kepler_tpu.device.meter import CPUPowerMeter, EnergyZone
from kepler_tpu.monitor.snapshot import NodeUsage, Snapshot, WorkloadTable
from kepler_tpu.monitor.terminated import TerminatedTracker
from kepler_tpu.ops.attribution import attribute, pad_to_bucket
from kepler_tpu.ops.deltas import energy_delta
from kepler_tpu.resource.informer import FeatureBatch, ResourceInformer
from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.monitor")


class SnapshotUnavailableError(RuntimeError):
    """No snapshot exists and the refresh that would create one failed.

    Raised from ``PowerMonitor.snapshot()`` only when there is no stale
    snapshot to degrade to (the reference serves stale data on refresh
    failure when it can — :185-200); collectors catch this to render a
    scrape error rather than propagate a raw traceback."""

_UNSET = object()  # "batch plan not yet computed" (None = computed, absent)

_KINDS = ("processes", "containers", "virtual_machines", "pods")
_KIND_CODES = (
    FeatureBatch.KIND_PROCESS,
    FeatureBatch.KIND_CONTAINER,
    FeatureBatch.KIND_VM,
    FeatureBatch.KIND_POD,
)


@dataclass(frozen=True)
class WindowSample:
    """Raw per-refresh inputs, before attribution — the feature rows a fleet
    agent streams to the cluster aggregator (SURVEY §5 "distributed
    communication backend": per-node agents producing `[pods × features]`
    rows; the aggregator batches them into `[nodes × pods × features]`)."""

    timestamp: float
    dt_s: float
    zone_names: tuple[str, ...]
    zone_deltas_uj: np.ndarray  # f64 [Z] this window
    zone_valid: np.ndarray  # bool [Z]
    usage_ratio: float
    batch: FeatureBatch


class PowerMonitor:
    def __init__(
        self,
        meter: CPUPowerMeter,
        resources: ResourceInformer,
        interval: float = 5.0,
        staleness: float = 0.5,
        max_terminated: int = 500,
        min_terminated_energy_uj: float = 10e6,
        workload_bucket: int = 256,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
    ) -> None:
        self._meter = meter
        self._resources = resources
        self._interval = interval
        self._staleness = staleness
        self._max_terminated = max_terminated
        self._min_terminated_energy_uj = min_terminated_energy_uj
        self._bucket = workload_bucket
        self._clock = clock or _time.time  # wall: timestamps/staleness
        # dt for power uses a monotonic source so NTP steps can't inflate
        # watts; tests inject the same fake for both
        self._monotonic = monotonic or (clock if clock else _time.monotonic)

        self._zones: list[EnergyZone] = []
        self._zone_names: tuple[str, ...] = ()
        self._prev_counters: list[int | None] = []
        self._batch_plan = _UNSET  # lazily-resolved native zone-read plan
        self._last_read_ts: float | None = None

        # cumulative f64 accumulators: kind → id → [Z] µJ
        self._cumulative: dict[str, dict[str, np.ndarray]] = {
            k: {} for k in _KINDS
        }
        # last-known labels so terminated rows keep their metadata
        # (reference pulls terminated entries from the previous snapshot)
        self._meta_cache: dict[str, dict[str, Mapping[str, str]]] = {
            k: {} for k in _KINDS
        }
        self._node_energy = np.zeros(0)
        self._node_active = np.zeros(0)
        self._node_idle = np.zeros(0)

        self._trackers: dict[str, TerminatedTracker] = {}
        self._window_listeners: list[Callable[[WindowSample], None]] = []
        self._snapshot: Snapshot | None = None
        self._snapshot_lock = threading.Lock()  # singleflight for refresh
        self._exported = False
        self._data_event = threading.Event()  # reference dataCh signal

    # -- service lifecycle -------------------------------------------------

    def name(self) -> str:
        return "power-monitor"

    def init(self) -> None:
        """Probe zones, seed counters, create trackers (reference Init
        :118-150)."""
        if hasattr(self._meter, "init"):
            self._meter.init()
        self._zones = list(self._meter.zones())
        self._zone_names = tuple(z.name() for z in self._zones)
        self._batch_plan = _UNSET  # re-resolve against the new zone list
        z = len(self._zones)
        self._prev_counters = [None] * z
        self._node_energy = np.zeros(z)
        self._node_active = np.zeros(z)
        self._node_idle = np.zeros(z)
        primary = self._meter.primary_energy_zone().name()
        primary_idx = self._zone_names.index(primary)
        for kind in _KINDS:
            self._trackers[kind] = TerminatedTracker(
                n_zones=z,
                primary_zone_index=primary_idx,
                max_size=self._max_terminated,
                min_energy_uj=self._min_terminated_energy_uj,
            )
        log.info("monitor initialized: zones=%s primary=%s",
                 self._zone_names, primary)

    def run(self, ctx: CancelContext) -> None:
        """Self-rearming collection loop (reference collectionLoop :218)."""
        if self._interval <= 0:
            ctx.wait(None)
            return
        while not ctx.cancelled():
            try:
                self.refresh()
            except Exception:
                log.exception("refresh failed")
            if ctx.wait(self._interval):
                return

    def shutdown(self) -> None:
        pass

    # -- read API (reference PowerDataProvider) ----------------------------

    def zone_names(self) -> Sequence[str]:
        return self._zone_names

    def data_channel(self) -> threading.Event:
        """Set once the first snapshot exists (collector readiness gate)."""
        return self._data_event

    def add_window_listener(
            self, listener: Callable[[WindowSample], None]) -> None:
        """Subscribe to raw per-window samples (fleet agent feed). Listeners
        run inside the refresh lock — they must be fast and non-blocking
        (the agent just enqueues)."""
        self._window_listeners.append(listener)

    def snapshot(self, clone: bool = True) -> Snapshot:
        """Return a deep-cloned, fresh snapshot.

        ``clone=False`` returns the published object itself — safe for
        read-only consumers because a published snapshot is never mutated
        (every refresh builds new arrays/dicts and swaps the reference);
        the exporter's direct text render uses it to skip a 10k-row deep
        copy per scrape. External callers should keep the default.

        Freshness contract (reference :185-200, :254-302): if the current
        snapshot is older than ``staleness``, refresh first; concurrent
        callers dedupe on a lock with a double-check so at most one refresh
        runs (singleflight). Degradation contract: if the refresh fails
        (meter died between init and scrape) a stale snapshot, when one
        exists, is served with a warning — matching the reference's
        serve-stale-on-error stance; with no snapshot at all the failure
        surfaces as ``SnapshotUnavailableError`` so the collector can
        render a scrape error instead of a raw traceback.
        """
        snap = self._snapshot
        if snap is None or not self._is_fresh():
            with self._snapshot_lock:
                if not self._is_fresh():  # double-check under the lock
                    try:
                        self._refresh_locked()
                    except Exception as err:
                        if self._snapshot is None:
                            raise SnapshotUnavailableError(
                                f"first refresh failed: {err}") from err
                        log.warning("refresh failed (%s); serving stale "
                                    "snapshot", err)
            snap = self._snapshot
        assert snap is not None
        self._exported = True  # terminated data now consumable→clearable
        return snap.clone() if clone else snap

    def _is_fresh(self) -> bool:
        snap = self._snapshot
        if snap is None:
            return False
        return (self._clock() - snap.timestamp) <= self._staleness

    # -- refresh -----------------------------------------------------------

    def refresh(self) -> None:
        with self._snapshot_lock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        start = _time.perf_counter()
        now = self._clock()
        mono = self._monotonic()
        dt = (mono - self._last_read_ts
              if self._last_read_ts is not None else 0.0)
        self._last_read_ts = mono

        zone_deltas, zone_valid = self._read_zone_deltas()
        self._resources.refresh()
        batch = self._resources.feature_batch()

        w = batch.cpu_deltas.shape[0]
        padded_w = pad_to_bucket(w, self._bucket)
        cpu = np.zeros(padded_w, np.float32)
        cpu[:w] = batch.cpu_deltas
        valid = np.zeros(padded_w, bool)
        valid[:w] = True

        result = attribute(
            jnp.asarray(zone_deltas, jnp.float32),
            jnp.asarray(zone_valid),
            jnp.float32(batch.usage_ratio),
            jnp.asarray(cpu),
            jnp.asarray(valid),
            jnp.float32(batch.node_cpu_delta),
            jnp.float32(max(dt, 0.0)),
        )

        node = self._accumulate_node(result, batch.usage_ratio)
        tables = self._accumulate_workloads(batch, result, w)
        self._handle_terminated(tables)

        self._snapshot = Snapshot(
            timestamp=now,
            node=node,
            terminated_processes=self._trackers["processes"].items(),
            terminated_containers=self._trackers["containers"].items(),
            terminated_virtual_machines=self._trackers[
                "virtual_machines"].items(),
            terminated_pods=self._trackers["pods"].items(),
            **tables,
        )
        self._data_event.set()
        if self._window_listeners:
            sample = WindowSample(
                timestamp=now, dt_s=max(dt, 0.0),
                zone_names=self._zone_names,
                zone_deltas_uj=zone_deltas, zone_valid=zone_valid,
                usage_ratio=batch.usage_ratio, batch=batch,
            )
            for listener in self._window_listeners:
                try:
                    listener(sample)
                except Exception:
                    log.exception("window listener failed")
        log.debug("refresh done in %.2f ms", (_time.perf_counter() - start) * 1e3)

    def _zone_batch_plan(self):
        """(paths, per-zone slices) when EVERY zone supports batched raw
        reads AND the native library is present — else None. Computed once;
        one C call then replaces Z×(open+read+close) Python file reads per
        tick."""
        if self._batch_plan is not _UNSET:
            return self._batch_plan
        plan = None
        if not all(hasattr(z, "energy_paths") for z in self._zones):
            self._batch_plan = None  # fake/mock zones: no fast path
            return None
        try:
            from kepler_tpu.native import scanner

            native = scanner()
            if native is not None:
                paths: list[str] = []
                slices: list[slice] = []
                for zone in self._zones:
                    zp = zone.energy_paths()
                    slices.append(slice(len(paths), len(paths) + len(zp)))
                    paths.extend(zp)
                if paths:
                    plan = (native, paths, slices)
        except Exception as err:  # native build failure etc.
            log.debug("no batched zone reads: %s", err)
            plan = None
        self._batch_plan = plan
        return plan

    def _read_zone_energies(self) -> list[int | None]:
        """Current raw counter per zone (None = failed read this tick)."""
        out: list[int | None] = []
        plan = self._zone_batch_plan()
        if plan is not None:
            native, paths, slices = plan
            raw = native.read_counters(paths)
            for zone, sl in zip(self._zones, slices):
                try:
                    out.append(int(zone.energy_from_raw(raw[sl].tolist())))
                except (OSError, ValueError) as err:
                    log.warning("zone %s read failed: %s", zone.name(), err)
                    out.append(None)
            return out
        for zone in self._zones:
            try:
                out.append(int(zone.energy()))
            except (OSError, ValueError) as err:
                log.warning("zone %s read failed: %s", zone.name(), err)
                out.append(None)
        return out

    def _read_zone_deltas(self) -> tuple[np.ndarray, np.ndarray]:
        z = len(self._zones)
        deltas = np.zeros(z, np.float64)
        valid = np.zeros(z, bool)
        for i, (zone, current) in enumerate(
                zip(self._zones, self._read_zone_energies())):
            if current is None:
                continue  # stays masked this window
            prev = self._prev_counters[i]
            self._prev_counters[i] = current
            if prev is None:
                continue  # first reading seeds only (reference firstNodeRead)
            deltas[i] = energy_delta(current, prev, int(zone.max_energy()))
            valid[i] = True
        return deltas, valid

    def _accumulate_node(self, result, usage_ratio: float) -> NodeUsage:
        n = result.node
        energy = np.asarray(n.energy_uj, np.float64)
        active = np.asarray(n.active_uj, np.float64)
        idle = np.asarray(n.idle_uj, np.float64)
        self._node_energy += energy
        self._node_active += active
        self._node_idle += idle
        return NodeUsage(
            zone_names=self._zone_names,
            energy_uj=self._node_energy.copy(),
            active_uj=self._node_active.copy(),
            idle_uj=self._node_idle.copy(),
            power_uw=np.asarray(n.power_uw, np.float64),
            active_power_uw=np.asarray(n.active_power_uw, np.float64),
            idle_power_uw=np.asarray(n.idle_power_uw, np.float64),
            window_active_uj=active,
            usage_ratio=float(usage_ratio),
        )

    def _workload_meta(self) -> dict[str, dict[str, Mapping[str, str]]]:
        """Exporter label metadata per kind/id, from the informer's views."""
        res = self._resources
        meta: dict[str, dict[str, Mapping[str, str]]] = {
            "processes": {
                str(pid): {"comm": p.comm, "exe": p.exe,
                           "type": ("container" if p.container else
                                    "vm" if p.virtual_machine else "regular"),
                           "container_id": p.container.id if p.container else "",
                           "vm_id": (p.virtual_machine.id
                                     if p.virtual_machine else ""),
                           # numeric pseudo-label consumed (and stripped) by
                           # the collector for kepler_process_cpu_seconds_total
                           "_cpu_total_seconds": f"{p.cpu_total_time:.6f}"}
                for pid, p in res.processes().running.items()
            },
            "containers": {
                c.id: {"container_name": c.name, "runtime": c.runtime.value,
                       "pod_id": c.pod_id or ""}
                for c in res.containers().running.values()
            },
            "virtual_machines": {
                v.id: {"vm_name": v.name, "hypervisor": v.hypervisor.value}
                for v in res.virtual_machines().running.values()
            },
            "pods": {
                p.id: {"pod_name": p.name, "namespace": p.namespace}
                for p in res.pods().running.values()
            },
        }
        return meta

    def _accumulate_workloads(self, batch: FeatureBatch, result, w: int
                              ) -> dict[str, WorkloadTable]:
        energy_delta_wz = np.asarray(result.workloads.energy_uj,
                                     np.float64)[:w]
        power_wz = np.asarray(result.workloads.power_uw, np.float64)[:w]
        meta_by_kind = self._workload_meta()
        tables: dict[str, WorkloadTable] = {}
        kinds = batch.kinds
        for kind_name, kind_code in zip(_KINDS, _KIND_CODES):
            idx = np.nonzero(kinds == kind_code)[0]
            store = self._cumulative[kind_name]
            ids = [batch.ids[i] for i in idx]
            kind_meta = meta_by_kind[kind_name]
            nz = len(self._zone_names)
            n = len(ids)
            energy_rows = np.zeros((n, nz))
            power_rows = power_wz[idx] if n else np.zeros((0, nz))
            # gather prev cumulative, one vectorized add, scatter views
            # back (rows alias energy_rows — safe: snapshot arrays are
            # never mutated after publication, each refresh builds new).
            # PRECONDITION: ids within a kind are unique (they come from
            # dict-keyed informer views) — a duplicate would silently drop
            # one delta in the last-writer-wins scatter below, so fail
            # loudly (not assert: -O must not change energy accounting)
            if len(set(ids)) != len(ids):
                raise ValueError(
                    f"duplicate {kind_name} ids in feature batch; "
                    "cumulative energy accounting requires unique ids")
            get = store.get
            for row, wid in enumerate(ids):
                acc = get(wid)
                if acc is not None:
                    energy_rows[row] = acc
            if n:
                energy_rows += energy_delta_wz[idx]
            for row, wid in enumerate(ids):
                store[wid] = energy_rows[row]
            meta_rows = tuple(kind_meta.get(wid, {}) for wid in ids)
            self._meta_cache[kind_name].update(zip(ids, meta_rows))
            # terminated ids stay in the store until _handle_terminated has
            # captured their final cumulative values
            tables[kind_name] = WorkloadTable(
                ids=tuple(ids),
                meta=meta_rows,
                energy_uj=energy_rows,
                power_uw=power_rows,
            )
        return tables

    def _terminated_views(self) -> dict[str, WorkloadTable]:
        """Final cumulative usage of workloads that vanished this refresh."""
        res = self._resources
        views: dict[str, WorkloadTable] = {}
        terminated_ids = {
            "processes": [str(pid) for pid in res.processes().terminated],
            "containers": list(res.containers().terminated),
            "virtual_machines": list(res.virtual_machines().terminated),
            "pods": list(res.pods().terminated),
        }
        nz = len(self._zone_names)
        for kind in _KINDS:
            store = self._cumulative[kind]
            ids = [wid for wid in terminated_ids[kind] if wid in store]
            energy = (np.stack([store[wid] for wid in ids])
                      if ids else np.zeros((0, nz)))
            meta_cache = self._meta_cache[kind]
            views[kind] = WorkloadTable(
                ids=tuple(ids),
                meta=tuple(meta_cache.get(wid, {}) for wid in ids),
                energy_uj=energy,
                power_uw=np.zeros((len(ids), nz)),
            )
        return views

    def _handle_terminated(self, tables: dict[str, WorkloadTable]) -> None:
        """Clear-after-export then absorb this window's terminated workloads
        (reference refreshSnapshot: exported flag gates clearing)."""
        views = self._terminated_views()
        for kind in _KINDS:
            if self._exported:
                self._trackers[kind].clear()
            self._trackers[kind].add_batch(views[kind])
        if self._exported:
            self._exported = False
        # now that final values are tracked, drop them from the stores
        for kind in _KINDS:
            store = self._cumulative[kind]
            meta_cache = self._meta_cache[kind]
            for wid in views[kind].ids:
                store.pop(wid, None)
                meta_cache.pop(wid, None)
