"""Per-workload feature-history ring buffer (the time axis' host side).

The reference keeps no history — each tick's deltas are consumed and
dropped (`internal/monitor/monitor.go:317-356` replaces the snapshot
wholesale). The temporal estimator (`kepler_tpu.models.temporal`) needs the
last T ticks of the feature vector per workload, so this buffer accretes
one row per workload per `push()` and materialises right-padded
``[W, T, F]`` windows on demand.

Host-side numpy only: rows are tiny (F=7 f32), the buffer is O(W×T)
bytes, and it lives beside the informer on the node agent — the device
only ever sees the dense padded window. Feature rows are computed with the
same formulas as `models.features.build_features` so a window's last
column equals what the single-tick estimators would have seen.

Not thread-safe by design — single-writer, same contract as the informer
(`docs/developer/power-attribution-guide.md:251-257` in the reference).
"""

from __future__ import annotations

import numpy as np

from kepler_tpu.models.features import NUM_FEATURES
from kepler_tpu.resource.informer import FeatureBatch


def feature_rows(batch: FeatureBatch, dt_s: float) -> np.ndarray:
    """One tick's ``[W, F]`` feature matrix (numpy mirror of build_features)."""
    deltas = np.asarray(batch.cpu_deltas, np.float32)
    w = deltas.shape[0]
    denom = batch.node_cpu_delta
    share = deltas / denom if denom > 0 else np.zeros_like(deltas)
    rate = deltas / dt_s if dt_s > 0 else np.zeros_like(deltas)
    rows = np.empty((w, NUM_FEATURES), np.float32)
    rows[:, 0] = deltas
    rows[:, 1] = share
    rows[:, 2] = batch.usage_ratio
    rows[:, 3] = dt_s
    rows[:, 4] = rate
    rows[:, 5] = 1.0
    rows[:, 6] = np.log1p(max(denom, 0.0))
    return rows


class HistoryBuffer:
    """Fixed-window per-id ring buffer of feature rows.

    ``evict_after``: drop ids not seen for that many pushes (terminated
    workloads; mirrors the informer's set-difference terminated detection).
    """

    def __init__(self, window: int = 32,
                 n_features: int = NUM_FEATURES,
                 evict_after: int = 2) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.n_features = n_features
        self._evict_after = evict_after
        self._tick = 0
        # id → (rows [T, F] ring storage, count, write cursor, last-seen tick)
        self._rows: dict[str, np.ndarray] = {}
        self._count: dict[str, int] = {}
        self._cursor: dict[str, int] = {}
        self._seen: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._rows)

    def push(self, batch: FeatureBatch, dt_s: float) -> None:
        """Append this tick's row for every workload in the batch."""
        rows = feature_rows(batch, dt_s)
        self._tick += 1
        for i, wid in enumerate(batch.ids):
            buf = self._rows.get(wid)
            if buf is None:
                buf = np.zeros((self.window, self.n_features), np.float32)
                self._rows[wid] = buf
                self._count[wid] = 0
                self._cursor[wid] = 0
            buf[self._cursor[wid]] = rows[i]
            self._cursor[wid] = (self._cursor[wid] + 1) % self.window
            self._count[wid] = min(self._count[wid] + 1, self.window)
            self._seen[wid] = self._tick
        if self._evict_after > 0:
            dead = [wid for wid, seen in self._seen.items()
                    if self._tick - seen >= self._evict_after]
            for wid in dead:
                for d in (self._rows, self._count, self._cursor, self._seen):
                    del d[wid]

    def window_arrays(
        self, ids: list[str],
    ) -> tuple[np.ndarray, np.ndarray]:
        """→ (features f32 [W, T, F], t_valid bool [W, T]), right-padded.

        Rows are oldest→newest so the last valid position is the current
        tick — the position ``predict_temporal`` pools. Unknown ids yield
        empty (all-invalid) windows.
        """
        w = len(ids)
        feats = np.zeros((w, self.window, self.n_features), np.float32)
        t_valid = np.zeros((w, self.window), bool)
        for i, wid in enumerate(ids):
            n = self._count.get(wid, 0)
            if not n:
                continue
            buf = self._rows[wid]
            cur = self._cursor[wid]
            # unroll the ring: oldest entry sits at the write cursor once full
            ordered = np.roll(buf, -cur, axis=0)[self.window - n:]
            feats[i, :n] = ordered
            t_valid[i, :n] = True
        return feats, t_valid
