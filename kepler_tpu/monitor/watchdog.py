"""Monitor watchdog: detects a stalled refresh loop.

The reference's failure model covers *failing* refreshes (skip-on-error
zone reads, serve-stale-on-error snapshots) but not a refresh loop that
stops running at all — a meter blocked in a driver read, an informer
deadlock, a wedged device call. This Runner closes that gap: it
periodically compares the monitor's last-completed-refresh age against a
stall threshold (default: 3 refresh intervals) and, when exceeded, marks
the published snapshot stale (``PowerMonitor.mark_stalled``) and flips
its own /healthz probe to degraded. A completed refresh clears the flag,
so recovery is automatic and the degraded window is exactly the stall.

The watchdog never restarts anything itself — pairing it with
``run_services(..., restart=RestartPolicy(...))`` is the supervised
variant (docs/developer/resilience.md).
"""

from __future__ import annotations

# keplint: monotonic-only — stall ages must survive NTP clock steps

import logging
import time as _time
from typing import Any, Callable

from kepler_tpu import telemetry
from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.monitor.watchdog")


class MonitorWatchdog:
    def __init__(
        self,
        monitor: PowerMonitor,
        interval: float,
        stall_after: float | None = None,
        check_every: float | None = None,
        monotonic: Callable[[], float] | None = None,
        journal: Any = None,
    ) -> None:
        """``interval`` is the monitor's refresh interval; ``stall_after``
        defaults to 3 intervals (the ISSUE's convergence budget),
        ``check_every`` to one interval. ``journal`` is an optional
        fleet black-box :class:`~kepler_tpu.fleet.journal.EventJournal`
        — passed as an INSTANCE (never imported here) so the monitor
        binary stays jax-free when the journal is off."""
        self._monitor = monitor
        self._journal = journal
        self._interval = max(interval, 1e-3)
        self._stall_after = (stall_after if stall_after is not None
                             else 3.0 * self._interval)
        self._check_every = (check_every if check_every is not None
                             else self._interval)
        self._monotonic = monotonic or _time.monotonic
        self._started_at: float | None = None
        self._stall_count = 0
        # where the wedged refresh is stuck: the innermost open span of
        # the in-flight monitor.refresh cycle, snapshotted from the
        # telemetry plane when the stall is detected ("" when telemetry
        # is disabled or no refresh is in flight)
        self._stuck_stage = ""
        self._stall_spans: list[dict] = []

    def name(self) -> str:
        return "monitor-watchdog"

    def run(self, ctx: CancelContext) -> None:
        self._started_at = self._monotonic()
        while not ctx.cancelled():
            if ctx.wait(self._check_every):
                return
            self.check_once()

    def _age(self) -> float:
        """Seconds since the last completed refresh — or, before any
        refresh EVER completed, since watchdog start (the first refresh
        may be slow — XLA compile — so the same threshold applies)."""
        age = self._monitor.last_refresh_age()
        if age is None:
            started = self._started_at
            if started is None:
                self._started_at = started = self._monotonic()
            age = self._monotonic() - started
        return age

    def check_once(self) -> bool:
        """One stall check (tests call this directly). True = stalled.

        Only ever SETS the stall flag — a completed refresh is what
        clears it (monitor._refresh_locked), so recovery is owned by the
        thing that actually recovered. The age is re-read right before
        flagging so a refresh completing mid-check can't get a
        just-recovered monitor re-marked stale."""
        stalled = self._age() > self._stall_after
        if stalled:
            stalled = self._age() > self._stall_after  # double-check
        if stalled:
            # snapshot the in-flight trace so the report names WHERE the
            # refresh is wedged, not just that it is (re-read every
            # check: the stall may progress into a deeper stage)
            self._stall_spans = self._inflight_refresh_spans()
            self._stuck_stage = (self._stall_spans[-1]["name"]
                                 if self._stall_spans else "")
            if not self._monitor.stalled:
                self._stall_count += 1
                log.error("monitor refresh loop stalled: last refresh "
                          "%.1fs ago (threshold %.1fs); marking snapshot "
                          "stale%s", self._age(), self._stall_after,
                          f" (stuck in {self._stuck_stage})"
                          if self._stuck_stage else "")
                if self._journal is not None:
                    # black box: FIRST detection only — the per-check
                    # repeat while still stalled is not a new event
                    self._journal.emit(
                        "watchdog.stall",
                        age_s=round(self._age(), 3),
                        threshold_s=round(self._stall_after, 3),
                        stuck_stage=self._stuck_stage)
            self._monitor.mark_stalled(True)
        return stalled

    @staticmethod
    def _inflight_refresh_spans() -> list[dict]:
        """Open spans of the in-flight monitor.refresh cycle (outermost
        first), [] when none / telemetry disabled."""
        for entry in telemetry.inflight():
            spans = entry.get("spans", [])
            if spans and spans[0]["name"] == "monitor.refresh":
                return spans
        return []

    def health(self) -> dict:
        """Probe for /healthz (degraded while the loop is stalled)."""
        out: dict = {"ok": not self._monitor.stalled,
                     "stalled": self._monitor.stalled,
                     "stalls_total": self._stall_count}
        if self._monitor.stalled and self._stuck_stage:
            out["stuck_stage"] = self._stuck_stage
            out["inflight_spans"] = [
                {"name": s["name"],
                 "elapsed_s": round(s["elapsed_s"], 3)}
                for s in self._stall_spans]
        age = self._monitor.last_refresh_age()
        if age is not None:
            out["last_refresh_age_s"] = round(age, 3)
        return out
