"""Immutable snapshot types.

Reference parity: ``internal/monitor/types.go`` — ``Usage`` (cumulative
energy + instantaneous power), ``NodeUsage`` (adds active/idle splits),
``Snapshot`` (node + running/terminated maps for each workload kind) with
deep ``Clone`` so collectors read race-free.

TPU-first pivot: per-workload numbers live in dense f64 numpy columns
(``WorkloadTable``) aligned to an id list — the exporter iterates rows only
at scrape-render time; the monitor updates them with vectorized ops, never a
per-workload Python loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Mapping

import numpy as np


@dataclass(frozen=True)
class NodeUsage:
    """Per-zone node energy/power, arrays indexed by zone (``zone_names``).

    Cumulative counters are f64 µJ (sub-µJ exact for centuries of uptime);
    powers are f64 µW.
    """

    zone_names: tuple[str, ...]
    energy_uj: np.ndarray  # [Z] cumulative Δ-sum since start
    active_uj: np.ndarray  # [Z] cumulative active split
    idle_uj: np.ndarray  # [Z] cumulative idle split
    power_uw: np.ndarray  # [Z] last-window total power
    active_power_uw: np.ndarray  # [Z]
    idle_power_uw: np.ndarray  # [Z]
    # last-window active energy — the attribution numerator (private in the
    # reference: NodeUsage.activeEnergy, types.go:27-40)
    window_active_uj: np.ndarray = field(default_factory=lambda: np.zeros(0))
    # node CPU usage ratio over the last window (from /proc/stat deltas)
    usage_ratio: float = 0.0

    def clone(self) -> "NodeUsage":
        return NodeUsage(
            zone_names=self.zone_names,
            energy_uj=self.energy_uj.copy(),
            active_uj=self.active_uj.copy(),
            idle_uj=self.idle_uj.copy(),
            power_uw=self.power_uw.copy(),
            active_power_uw=self.active_power_uw.copy(),
            idle_power_uw=self.idle_power_uw.copy(),
            window_active_uj=self.window_active_uj.copy(),
            usage_ratio=self.usage_ratio,
        )


@dataclass(frozen=True)
class WorkloadRow:
    """One workload's view over a table (returned by iteration, not stored)."""

    id: str
    meta: Mapping[str, str]
    energy_uj: np.ndarray  # [Z] cumulative
    power_uw: np.ndarray  # [Z]


@dataclass(frozen=True)
class WorkloadTable:
    """Dense per-workload columns for one kind (process/container/vm/pod)."""

    ids: tuple[str, ...]
    meta: tuple[Mapping[str, str], ...]  # exporter labels (comm, runtime, …)
    energy_uj: np.ndarray  # [W, Z] cumulative f64
    power_uw: np.ndarray  # [W, Z] f64
    # process kind only: cumulative CPU seconds per row (the
    # kepler_process_cpu_seconds_total column); None for other kinds
    seconds: np.ndarray | None = None

    @staticmethod
    def empty(n_zones: int) -> "WorkloadTable":
        return WorkloadTable(
            ids=(), meta=(),
            energy_uj=np.zeros((0, n_zones)),
            power_uw=np.zeros((0, n_zones)),
        )

    def __len__(self) -> int:
        return len(self.ids)

    def rows(self) -> Iterator[WorkloadRow]:
        for i, wid in enumerate(self.ids):
            yield WorkloadRow(
                id=wid, meta=self.meta[i],
                energy_uj=self.energy_uj[i], power_uw=self.power_uw[i],
            )

    def clone(self) -> "WorkloadTable":
        return WorkloadTable(
            ids=self.ids,
            meta=tuple(dict(m) for m in self.meta),
            energy_uj=self.energy_uj.copy(),
            power_uw=self.power_uw.copy(),
            seconds=(self.seconds.copy()
                     if self.seconds is not None else None),
        )


@dataclass(frozen=True)
class Snapshot:
    """One consistent view of node + workload power (reference Snapshot,
    types.go:224-238)."""

    timestamp: float
    node: NodeUsage
    processes: WorkloadTable
    containers: WorkloadTable
    virtual_machines: WorkloadTable
    pods: WorkloadTable
    terminated_processes: WorkloadTable
    terminated_containers: WorkloadTable
    terminated_virtual_machines: WorkloadTable
    terminated_pods: WorkloadTable

    def clone(self) -> "Snapshot":
        return Snapshot(
            timestamp=self.timestamp,
            node=self.node.clone(),
            processes=self.processes.clone(),
            containers=self.containers.clone(),
            virtual_machines=self.virtual_machines.clone(),
            pods=self.pods.clone(),
            terminated_processes=self.terminated_processes.clone(),
            terminated_containers=self.terminated_containers.clone(),
            terminated_virtual_machines=self.terminated_virtual_machines.clone(),
            terminated_pods=self.terminated_pods.clone(),
        )
