"""TPU compute kernels: attribution, delta math, top-k tracking."""

from kepler_tpu.ops.attribution import (
    AttributionResult,
    NodeAttribution,
    WorkloadAttribution,
    attribute,
    attribute_fleet,
    pad_to_bucket,
)
from kepler_tpu.ops.deltas import energy_delta, energy_deltas

__all__ = [
    "AttributionResult",
    "NodeAttribution",
    "WorkloadAttribution",
    "attribute",
    "attribute_fleet",
    "energy_delta",
    "energy_deltas",
    "pad_to_bucket",
]
