"""Pallas TPU kernel for the temporal estimator's attention hot op.

One fused kernel per ``(batch × head)`` program computes the whole
blockwise-attention partial — scores, causal/validity masking, the
online-softmax statistics, and the value contraction — in a single VMEM
round trip:

    s  = q @ kᵀ · scale          (MXU, bf16 in / f32 out)
    m  = rowmax(s),  p = e^(s−m),  l = rowsum(p)
    pv = p @ v                   (MXU)

XLA's fusion of the jnp path (`ops.attention.block_attn`) materialises the
[T, T] score matrix in HBM between the two matmuls once T grows; here it
never leaves VMEM (history windows are T ≤ a few hundred ticks, so a
[T, T] f32 tile fits comfortably in 16 MB VMEM).

Layout: heads fold into the grid axis — inputs reshape to ``[B·H, T, D]``
so each block is a clean rank-2 ``(T, D)`` tile (Mosaic requires the
trailing block dims to align to (8, 128) or span the array; a
``(1, T, 1, D)`` block on a 4-D array does not). The transposes live
outside the kernel where XLA fuses them with the surrounding projections.

The kernel returns the SAME (pv, m, l) partials contract as
``block_attn``, so it drops into both consumers:

- dense serving: :func:`pallas_attention_fn` → an ``attention_fn`` for
  ``models.temporal.temporal_trunk``'s seam;
- ring attention: ``parallel.ring`` calls :func:`flash_block_pallas` per
  KV rotation (positions arrive as scalar block starts, so the causal
  mask is recomputed from iota inside the kernel — the [T, T] mask is
  never materialised in HBM either).

Masking matches `ops.attention` exactly: fully-masked rows force p = 0
(m stays at −1e30, l = 0) and the caller's l-clamp yields zero output.
CPU tests run ``interpret=True`` (tests/conftest.py forces CPU); on TPU
it compiles with Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kepler_tpu.ops.attention import _NEG_INF, stats_to_out


def _flash_kernel(qs_ref, ks_ref, q_ref, k_ref, v_ref, kvv_ref,
                  o_ref, m_ref, l_ref, *, scale, causal, compute_dtype):
    q = q_ref[0].astype(compute_dtype)  # [Tq, D]
    k = k_ref[0].astype(compute_dtype)  # [Tk, D]
    v = v_ref[0].astype(compute_dtype)  # [Tk, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [Tq, Tk]
    mask = kvv_ref[0, 0][None, :] > 0.5  # [1, Tk] KV validity
    if causal:
        qp = qs_ref[0] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kp = ks_ref[0] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = mask & (qp >= kp)
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=1)  # [Tq]
    p = jnp.exp(s - m[:, None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=1)  # noqa: E741
    pv = jax.lax.dot_general(
        p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = pv
    m_ref[0, 0] = m
    l_ref[0, 0] = l


def flash_block_pallas(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, H, D]
    v: jax.Array,  # [B, Tk, H, D]
    kv_valid: jax.Array,  # bool/float [B, Tk]
    q_start,  # int scalar: global position of q row 0
    kv_start,  # int scalar: global position of k row 0
    *,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    interpret: bool | None = None,
):
    """One fused (q-block × kv-block) partial → (pv [B,Tq,H,D],
    m [B,H,Tq], l [B,H,Tq]) — the ``block_attn`` contract on the MXU."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               compute_dtype=compute_dtype)

    def fold(x, t):  # [B, T, H, D] → [B·H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qs = jnp.asarray(q_start, jnp.int32).reshape(1)
    ks = jnp.asarray(kv_start, jnp.int32).reshape(1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    pv, m, l = pl.pallas_call(  # noqa: E741
        kernel,
        grid=(b * h,),
        in_specs=[
            smem, smem,
            pl.BlockSpec((1, tq, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i: (i, 0, 0)),
            # [B, 1, Tk]: a rank-3 mask keeps the trailing block
            # dims (1, Tk) Mosaic-aligned (rank-2 (1, Tk) on [B, Tk]
            # would put block dim 1 against array dim B)
            pl.BlockSpec((1, 1, tk), lambda i: (i // h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tq, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
        ],
        interpret=interpret,
    )(qs, ks, fold(q, tq), fold(k, tk), fold(v, tk),
      kv_valid.astype(jnp.float32)[:, None, :])
    pv = pv.reshape(b, h, tq, d).transpose(0, 2, 1, 3)  # → [B, Tq, H, D]
    return pv, m.reshape(b, h, tq), l.reshape(b, h, tq)


def full_attention_pallas(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    t_valid: jax.Array | None = None,  # bool [B, T]
    *,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    interpret: bool | None = None,
) -> jax.Array:
    """Dense attention via the fused kernel (drop-in for
    ``ops.attention.full_attention``)."""
    if t_valid is None:
        t_valid = jnp.ones(q.shape[:2], bool)
    pv, _, l = flash_block_pallas(  # noqa: E741
        q, k, v, t_valid, 0, 0, causal=causal,
        compute_dtype=compute_dtype, interpret=interpret)
    l_safe = jnp.maximum(l, 1e-30)
    return (pv / stats_to_out(l_safe)).astype(q.dtype)


def pallas_attention_fn(causal: bool = True,
                        compute_dtype: jnp.dtype = jnp.bfloat16,
                        interpret: bool | None = None):
    """→ an ``attention_fn`` for ``temporal_trunk``'s plug-in seam."""

    def fn(q, k, v, t_valid):
        return full_attention_pallas(q, k, v, t_valid, causal=causal,
                                     compute_dtype=compute_dtype,
                                     interpret=interpret)

    return fn
