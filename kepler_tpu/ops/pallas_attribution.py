"""Pallas TPU kernel for the fleet-attribution hot op.

The core contraction is ``energy[n,w,z] = ratio[n,w] × active[n,z]`` (+ the
same shape for power) — a bandwidth-bound rank-1 outer product over the
fleet batch. XLA fuses the einsum path well; this kernel exists to pin the
best layout and fuse BOTH outputs in one pass over the inputs:

- grid ``(Z, N/TN, W/TW)`` — each program computes a ``[TN, TW]`` tile, a
  clean (8, 128)-aligned 2-D block. Emitting ``[N, W, Z]`` directly would
  put Z(=4) on the lane axis and waste 32× of every VMEM tile; instead the
  kernel writes ``[Z, N, W]`` and the wrapper transposes (one cheap XLA
  relayout) to keep the public ``[N, W, Z]`` contract.
- energy and power tiles read the same ratio block from VMEM once —
  the einsum path reads it twice.

CPU tests run the same kernel with ``interpret=True``
(tests/conftest.py forces the CPU backend); on TPU it compiles with
Mosaic. Sharded use goes through ``shard_map`` over the node axis (see
``kepler_tpu.parallel.aggregator_core.make_fleet_program``) so each device
runs the kernel on its local node shard — no cross-device communication,
matching the einsum path's zero-collective forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kepler_tpu.ops.attribution import (
    AttributionResult,
    WorkloadAttribution,
    _node_split,
    _workload_ratios,
)


def _tile(n: int, preferred: int, align: int) -> int:
    """Largest Mosaic-legal tile for a dim of size ``n``.

    Legal means: a divisor of ``n`` that is a multiple of ``align`` (lane
    dim must be 128-divisible, sublane 8-divisible) — or ``n`` itself, since
    a block spanning the whole array dim is always accepted. Fleet batches
    are bucketed so the aligned-divisor case is the norm; the full-dim
    fallback keeps odd shapes correct at worst a little more VMEM.
    """
    if n <= preferred:
        return n
    t = preferred - preferred % align
    while t > 0:
        if n % t == 0:
            return t
        t -= align
    return n


def _outer_kernel(ratio_ref, a_ref, p_ref, energy_ref, power_ref):
    ratio = ratio_ref[...]  # [TN, TW]
    energy_ref[0] = ratio * a_ref[0]  # a_ref: [1, TN, 1] → [TN, 1] broadcasts
    power_ref[0] = ratio * p_ref[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def outer_product_attribution(
    ratio: jax.Array,  # f32 [N, W]
    active_uj: jax.Array,  # f32 [N, Z]
    active_power_uw: jax.Array,  # f32 [N, Z]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """→ (energy_uj [N,W,Z], power_uw [N,W,Z]) in one fused kernel pass."""
    n, w = ratio.shape
    z = active_uj.shape[1]
    tn = _tile(n, 8, 8)
    tw = _tile(w, 512, 128)  # wide lanes amortize the per-program overhead
    grid = (z, n // tn, w // tw)

    # zone columns as [Z, N, 1] so each program's block is a legal tile
    # (Mosaic wants the last block dim ≡ 128-divisible OR equal to the
    # array's — a trailing singleton qualifies); the relayout is a few KB
    active_zn1 = jnp.transpose(active_uj)[..., None]
    power_zn1 = jnp.transpose(active_power_uw)[..., None]
    zone_spec = pl.BlockSpec((1, tn, 1), lambda zi, i, j: (zi, i, 0))
    out_shape = jax.ShapeDtypeStruct((z, n, w), ratio.dtype)
    out_spec = pl.BlockSpec((1, tn, tw), lambda zi, i, j: (zi, i, j))
    energy_znw, power_znw = pl.pallas_call(
        _outer_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, tw), lambda zi, i, j: (i, j)),
            zone_spec,
            zone_spec,
        ],
        out_specs=[out_spec, out_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(ratio, active_zn1, power_zn1)
    # relayout to the public [N, W, Z] contract
    return (jnp.transpose(energy_znw, (1, 2, 0)),
            jnp.transpose(power_znw, (1, 2, 0)))


@functools.partial(jax.jit, static_argnames=("interpret",))
def attribute_fleet_pallas(
    zone_deltas_uj: jax.Array,  # f32 [N, Z]
    zone_valid: jax.Array,  # bool [N, Z]
    usage_ratio: jax.Array,  # f32 [N]
    cpu_deltas: jax.Array,  # f32 [N, W]
    workload_valid: jax.Array,  # bool [N, W]
    node_cpu_delta: jax.Array,  # f32 [N]
    dt_s: jax.Array,  # f32 [N]
    *,
    interpret: bool = False,
) -> AttributionResult:
    """Drop-in for ``ops.attribution.attribute_fleet`` with the outer
    product running as the Pallas kernel (identical results to f32
    rounding)."""
    node = _node_split(zone_deltas_uj, zone_valid, usage_ratio, dt_s)
    ratios = _workload_ratios(cpu_deltas, workload_valid, node_cpu_delta)
    energy, power = outer_product_attribution(
        ratios, node.active_uj, node.active_power_uw, interpret=interpret)
    return AttributionResult(
        node=node,
        workloads=WorkloadAttribution(
            energy_uj=energy, power_uw=power, cpu_ratio=ratios
        ),
    )
