"""Pallas TPU kernel for the fleet-attribution hot op.

The core contraction is ``energy[n,w,z] = ratio[n,w] × active[n,z]`` (+ the
same shape for power) — a bandwidth-bound rank-1 outer product over the
fleet batch. XLA fuses the einsum path well; this kernel exists to pin the
best layout and fuse BOTH outputs in one pass over the inputs:

- grid ``(Z, N/TN, W/TW)`` — each program computes a ``[TN, TW]`` tile, a
  clean (8, 128)-aligned 2-D block. Emitting ``[N, W, Z]`` directly would
  put Z(=4) on the lane axis and waste 32× of every VMEM tile; instead the
  kernel writes ``[Z, N, W]`` and the wrapper transposes (one cheap XLA
  relayout) to keep the public ``[N, W, Z]`` contract.
- energy and power tiles read the same ratio block from VMEM once —
  the einsum path reads it twice.

CPU tests run the same kernel with ``interpret=True``
(tests/conftest.py forces the CPU backend); on TPU it compiles with
Mosaic. Sharded use goes through ``shard_map`` over the node axis (see
``kepler_tpu.parallel.aggregator_core.make_fleet_program``) so each device
runs the kernel on its local node shard — no cross-device communication,
matching the einsum path's zero-collective forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kepler_tpu.ops.attribution import (
    AttributionResult,
    WorkloadAttribution,
    _node_split,
    _workload_ratios,
)


def _tile(n: int, preferred: int, align: int) -> int:
    """Largest Mosaic-legal tile for a dim of size ``n``.

    Legal means: a divisor of ``n`` that is a multiple of ``align`` (lane
    dim must be 128-divisible, sublane 8-divisible) — or ``n`` itself, since
    a block spanning the whole array dim is always accepted. Fleet batches
    are bucketed so the aligned-divisor case is the norm; the full-dim
    fallback keeps odd shapes correct at worst a little more VMEM.
    """
    if n <= preferred:
        return n
    t = preferred - preferred % align
    while t > 0:
        if n % t == 0:
            return t
        t -= align
    return n


def _outer_kernel(ratio_ref, a_ref, p_ref, energy_ref, power_ref):
    ratio = ratio_ref[...]  # [TN, TW]
    energy_ref[0] = ratio * a_ref[0]  # a_ref: [1, TN, 1] → [TN, 1] broadcasts
    power_ref[0] = ratio * p_ref[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def outer_product_attribution(
    ratio: jax.Array,  # f32 [N, W]
    active_uj: jax.Array,  # f32 [N, Z]
    active_power_uw: jax.Array,  # f32 [N, Z]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """→ (energy_uj [N,W,Z], power_uw [N,W,Z]) in one fused kernel pass."""
    n, w = ratio.shape
    z = active_uj.shape[1]
    tn = _tile(n, 8, 8)
    tw = _tile(w, 512, 128)  # wide lanes amortize the per-program overhead
    grid = (z, n // tn, w // tw)

    # zone columns as [Z, N, 1] so each program's block is a legal tile
    # (Mosaic wants the last block dim ≡ 128-divisible OR equal to the
    # array's — a trailing singleton qualifies); the relayout is a few KB
    active_zn1 = jnp.transpose(active_uj)[..., None]
    power_zn1 = jnp.transpose(active_power_uw)[..., None]
    zone_spec = pl.BlockSpec((1, tn, 1), lambda zi, i, j: (zi, i, 0))
    out_shape = jax.ShapeDtypeStruct((z, n, w), ratio.dtype)
    out_spec = pl.BlockSpec((1, tn, tw), lambda zi, i, j: (zi, i, j))
    energy_znw, power_znw = pl.pallas_call(
        _outer_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, tw), lambda zi, i, j: (i, j)),
            zone_spec,
            zone_spec,
        ],
        out_specs=[out_spec, out_spec],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(ratio, active_zn1, power_zn1)
    # relayout to the public [N, W, Z] contract
    return (jnp.transpose(energy_znw, (1, 2, 0)),
            jnp.transpose(power_znw, (1, 2, 0)))


def _fused_window_kernel(res_ref, rows_ref, idx_ref, newres_ref, out_ref,
                         *, lay, tn):
    """One grid step of the fused window mega-kernel (node tile ``i``).

    Does the WHOLE rung-0 window for its ``[TN, width]`` resident tile in
    one pass: scatter the interval's delta rows into the tile, unpack the
    packed fields, run ratio attribution, and emit the packed f16 watts
    block (workload rows + node ACTIVE + node TOTAL) — the three device
    round-trips of the unfused path collapsed into one kernel body.

    The scatter has no in-kernel gather: a ``[DB, TN]`` hit matrix
    (delta index == global row id) turns row selection into a 0/1 matmul
    — exact, since delta indices are unique per interval, so every output
    row sums at most one product. NaN (the invalid-slot encoding in the
    cpu columns) would poison ``0 × NaN``; the NaN mask rides through a
    second matmul and is re-applied after.
    """
    i = pl.program_id(0)
    res = res_ref[...]  # [TN, width] f32
    drows = rows_ref[...]  # [DB, width] f32
    didx = idx_ref[...]  # [DB, 1] i32 (pad = N: matches no row id)
    row_ids = i * tn + jax.lax.broadcasted_iota(jnp.int32, (1, tn), 1)
    hit = didx == row_ids  # [DB, TN]
    anyhit = jnp.any(hit, axis=0)  # [TN]
    hitf = hit.astype(jnp.float32)
    nan_mask = jnp.isnan(drows)
    sel = jnp.dot(hitf.T, jnp.where(nan_mask, 0.0, drows))  # [TN, width]
    sel_nan = jnp.dot(hitf.T, nan_mask.astype(jnp.float32))
    sel = jnp.where(sel_nan > 0.5, jnp.float32(jnp.nan), sel)
    rows = jnp.where(anyhit[:, None], sel, res)
    newres_ref[...] = rows

    # unpack (PackedLayout-derived slices, passed in statically) + the
    # exact ops.attribution formula chain, tile-local
    cpu_nan = rows[:, lay.cpu]
    workload_valid = ~jnp.isnan(cpu_nan)
    cpu = jnp.where(workload_valid, cpu_nan, 0.0)
    zone = rows[:, lay.zone]
    zone_valid = rows[:, lay.zone_valid] > 0.5
    ratio = rows[:, lay.col_ratio]
    denom = rows[:, lay.col_denom]
    dt = rows[:, lay.col_dt]

    deltas = jnp.where(zone_valid, zone, 0.0)  # [TN, Z]
    active = deltas * jnp.clip(ratio, 0.0, 1.0)[:, None]
    dtc = dt[:, None]
    safe_dt = jnp.where(dtc > 0.0, dtc, 1.0)
    total_uw = jnp.where(dtc > 0.0, deltas / safe_dt, 0.0)
    active_uw = jnp.where(dtc > 0.0, active / safe_dt, 0.0)
    d = denom[:, None]
    ratios = jnp.where(d > 0.0, cpu / jnp.maximum(d, 1e-30), 0.0)  # [TN, W]
    for zi in range(lay.n_zones):  # static unroll (Z is tiny)
        col_a = active_uw[:, zi][:, None]  # [TN, 1]
        col_t = total_uw[:, zi][:, None]
        watts = jnp.concatenate([ratios * col_a, col_a, col_t], axis=1)
        out_ref[zi] = (watts * 1e-6).astype(jnp.float16)


def fused_window_step(
    resident: jax.Array,  # f32 [N, width] packed resident block
    delta_rows: jax.Array,  # f32 [DB, width] interval delta rows
    delta_idx: jax.Array,  # i32 [DB] target rows (pad = N → dropped)
    lay,  # PackedLayout (static: width + field offsets)
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One FUSED window step: scatter + unpack + ratio attribution as a
    single Pallas kernel over the packed resident block.

    → ``(resident' [N, width] f32, packed_watts [N, W+2, Z] f16)`` — the
    same contract as ``scatter_rows`` followed by the packed ratio
    program, with zero intermediate device round-trips. Ratio-only by
    design (the dense-model fused path composes XLA ops instead); used
    as the ``lax.scan`` body of the pallas-backend fused window program.

    The kernel grid is 1-D over node tiles; the watts output lands as
    ``[Z, N, W+2]`` (lane-friendly tiles, same trick as
    ``outer_product_attribution``) and is transposed once on the way out.
    """
    n = resident.shape[0]
    db = delta_rows.shape[0]
    tn = _tile(n, 512, 8)
    grid = (n // tn,)
    kernel = functools.partial(_fused_window_kernel, lay=lay, tn=tn)
    res_spec = pl.BlockSpec((tn, lay.width), lambda i: (i, 0))
    out_znw = jax.ShapeDtypeStruct((lay.n_zones, n, lay.n_workloads + 2),
                                   jnp.float16)
    newres, watts_znw = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            res_spec,
            pl.BlockSpec((db, lay.width), lambda i: (0, 0)),
            pl.BlockSpec((db, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            res_spec,
            pl.BlockSpec((lay.n_zones, tn, lay.n_workloads + 2),
                         lambda i: (0, i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((n, lay.width), jnp.float32),
                   out_znw],
        interpret=interpret,
    )(resident, delta_rows, delta_idx[:, None])
    return newres, jnp.transpose(watts_znw, (1, 2, 0))


@functools.partial(jax.jit, static_argnames=("interpret",))
def attribute_fleet_pallas(
    zone_deltas_uj: jax.Array,  # f32 [N, Z]
    zone_valid: jax.Array,  # bool [N, Z]
    usage_ratio: jax.Array,  # f32 [N]
    cpu_deltas: jax.Array,  # f32 [N, W]
    workload_valid: jax.Array,  # bool [N, W]
    node_cpu_delta: jax.Array,  # f32 [N]
    dt_s: jax.Array,  # f32 [N]
    *,
    interpret: bool = False,
) -> AttributionResult:
    """Drop-in for ``ops.attribution.attribute_fleet`` with the outer
    product running as the Pallas kernel (identical results to f32
    rounding)."""
    node = _node_split(zone_deltas_uj, zone_valid, usage_ratio, dt_s)
    ratios = _workload_ratios(cpu_deltas, workload_valid, node_cpu_delta)
    energy, power = outer_product_attribution(
        ratios, node.active_uj, node.active_power_uw, interpret=interpret)
    return AttributionResult(
        node=node,
        workloads=WorkloadAttribution(
            energy_uj=energy, power_uw=power, cpu_ratio=ratios
        ),
    )
