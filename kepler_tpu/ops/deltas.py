"""Energy-counter delta math with wraparound.

Reference parity: ``internal/monitor/node.go:87-98`` ``calculateEnergyDelta``:
``delta = current - prev``, or ``(max - prev) + current`` when the counter
wrapped (current < prev).

Counters are µJ values up to 2^64; delta math must be exact, so it runs
host-side on numpy uint64/object ints (Z is ~4 — this is scalar work, not the
hot loop). The resulting float32 deltas (< 2^32 µJ per 5 s window) feed the
device kernel.
"""

from __future__ import annotations

import numpy as np


def energy_delta(current: int, prev: int, max_energy: int) -> int:
    """Single-counter delta with wraparound (exact integer math)."""
    if current >= prev:
        return current - prev
    if max_energy <= 0:
        return 0  # cannot disambiguate a wrap without a wrap point
    return (max_energy - prev) + current


def energy_deltas(
    current: np.ndarray, prev: np.ndarray, max_energy: np.ndarray
) -> np.ndarray:
    """Vectorized wraparound delta over aligned uint64 arrays → float64 µJ.

    Used by the fleet aggregator when nodes ship raw counters instead of
    precomputed deltas.
    """
    current = np.asarray(current, dtype=np.uint64)
    prev = np.asarray(prev, dtype=np.uint64)
    max_energy = np.asarray(max_energy, dtype=np.uint64)
    wrapped = current < prev
    normal = (current - prev).astype(np.float64)
    wrap = (max_energy - prev).astype(np.float64) + current.astype(np.float64)
    out = np.where(wrapped, wrap, normal)
    return np.where(wrapped & (max_energy == 0), 0.0, out)
