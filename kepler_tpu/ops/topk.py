"""Top-K selection for terminated-workload tracking.

Reference parity: ``internal/monitor/terminated_resource_tracker.go`` keeps a
min-heap of terminated workloads ranked by primary-zone energy with a minimum
energy threshold. The batched equivalent is a masked ``lax.top_k`` over the
energy column — one call replaces the heap's per-item push/evict.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def _masked_topk(energies: jax.Array, mask: jax.Array, k: int):
    neg_inf = jnp.asarray(-jnp.inf, energies.dtype)
    masked = jnp.where(mask, energies, neg_inf)
    values, indices = jax.lax.top_k(masked, k)
    return values, indices


def top_k_by_energy(
    energies: np.ndarray,
    k: int,
    min_energy: float = 0.0,
) -> np.ndarray:
    """Indices of the top-k energies above ``min_energy``, descending.

    ``k <= 0`` returns an empty selection (tracking disabled); ``k`` larger
    than the candidate count returns all qualifying indices.
    """
    energies = np.asarray(energies)
    n = energies.shape[0]
    if k <= 0 or n == 0:
        return np.empty(0, dtype=np.int64)
    k_eff = min(k, n)
    mask = energies >= min_energy
    values, indices = _masked_topk(
        jnp.asarray(energies, jnp.float32), jnp.asarray(mask), k_eff
    )
    values = np.asarray(values)
    indices = np.asarray(indices, dtype=np.int64)
    return indices[values > -np.inf]
