"""Blockwise attention primitives (flash-style online softmax).

Pure jnp — no mesh/model dependencies, so both the models layer (dense
serving attention in `kepler_tpu.models.temporal`) and the parallel layer
(ring attention in `kepler_tpu.parallel.ring`) build on it without import
cycles. The online-softmax merge is what makes attention computable one
KV block at a time:

    m_new = max(m, rowmax(scores))
    o     = o * e^(m - m_new) + e^(scores - m_new) @ V
    l     = l * e^(m - m_new) + rowsum(e^(scores - m_new))

Matmuls run in the caller's compute dtype (bf16 on TPU → MXU); softmax
statistics stay f32.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large-but-finite: keeps exp() exactly 0 without NaN risk


def block_attn(q, k, v, mask, scale, compute_dtype):
    """Scores for one (q-block, kv-block) pair → (p @ v, rowmax, rowsum).

    q [B, Tq, H, D] × k [B, Tk, H, D] → scores [B, H, Tq, Tk]; f32 softmax
    statistics regardless of the matmul dtype.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(compute_dtype),
        k.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B, H, Tq]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)  # fully-masked rows: force exact 0
    l = jnp.sum(p, axis=-1)  # noqa: E741  [B, H, Tq]
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd",
        p.astype(compute_dtype),
        v.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return pv, m, l


def merge_blocks(o, m, l, pv, m_blk, l_blk):  # noqa: E741
    """Fold one block's partials into the running online-softmax state."""
    m_new = jnp.maximum(m, m_blk)
    corr_old = jnp.exp(m - m_new)
    corr_blk = jnp.exp(m_blk - m_new)
    o = o * stats_to_out(corr_old) + pv * stats_to_out(corr_blk)
    l_new = l * corr_old + l_blk * corr_blk
    return o, m_new, l_new


def stats_to_out(x):
    """[B, H, Tq] softmax stats → [B, Tq, H, 1] for scaling o."""
    return jnp.moveaxis(x, -2, -1)[..., None]


def full_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    t_valid: jax.Array | None = None,  # bool [B, T] keys to attend to
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Dense single-device attention; also the serving path for short T."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    tq, tk = q.shape[1], k.shape[1]
    mask = jnp.ones((1, 1, tq, tk), bool)
    if causal:
        mask = mask & (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
    if t_valid is not None:
        mask = mask & t_valid[:, None, None, :]
    pv, m, l = block_attn(q, k, v, mask, scale, compute_dtype)  # noqa: E741
    l_safe = jnp.maximum(l, 1e-30)
    return (pv / stats_to_out(l_safe)).astype(q.dtype)
