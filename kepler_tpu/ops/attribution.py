"""The attribution kernel: Kepler's power math as one fused tensor program.

Reference parity (semantics, not structure):

- ``internal/monitor/node.go:10-84``  — per-zone: split the window's energy
  delta into ``active = Δ × usage_ratio`` and ``idle = Δ − active``; power =
  Δenergy / Δt.
- ``internal/monitor/process.go:123-145`` (and container.go/vm.go/pod.go —
  identical formula per workload kind) — per workload w, zone z:
  ``ratio_w = Δcpu_w / Δcpu_node``; ``energy[w,z] = ratio_w × active[z]``;
  ``power[w,z] = ratio_w × active_power[z]``.

The reference runs this as a per-workload Python-shaped loop,
O(workloads × zones) scalar ops. Here the whole thing is a rank-1 outer
product ``ratio[W] ⊗ active[Z]`` — one fused XLA computation; batched over
nodes it becomes ``einsum('nw,nz->nwz')``, an MXU-shaped contraction
(`attribute_fleet`).

Masking: invalid workload rows (padding) and invalid zones (read errors —
reference node.go:39-44 skips failed zones) contribute exactly zero, the
batched analog of the reference's skip-on-error behavior.

Dtypes: µJ deltas arrive as f32 (a 5 s RAPL delta < 2^32 µJ keeps ~1e-7
relative error); cumulative energy accumulation happens on the host in f64
(see ``kepler_tpu.monitor``) so long-running totals don't lose precision.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class NodeAttribution(NamedTuple):
    """Per-zone node-level results (reference NodeUsage, monitor/types.go)."""

    energy_uj: jax.Array  # [..., Z] total Δenergy this window
    active_uj: jax.Array  # [..., Z] Δ × usage_ratio
    idle_uj: jax.Array  # [..., Z] Δ − active
    power_uw: jax.Array  # [..., Z] Δ / Δt
    active_power_uw: jax.Array  # [..., Z]
    idle_power_uw: jax.Array  # [..., Z]


class WorkloadAttribution(NamedTuple):
    """Per-workload per-zone results (reference Usage maps)."""

    energy_uj: jax.Array  # [..., W, Z]
    power_uw: jax.Array  # [..., W, Z]
    cpu_ratio: jax.Array  # [..., W] attribution ratios (diagnostics)


class AttributionResult(NamedTuple):
    node: NodeAttribution
    workloads: WorkloadAttribution


def _node_split(
    zone_deltas_uj: jax.Array,
    zone_valid: jax.Array,
    usage_ratio: jax.Array,
    dt_s: jax.Array,
) -> NodeAttribution:
    deltas = jnp.where(zone_valid, zone_deltas_uj, 0.0)
    ratio = jnp.clip(usage_ratio, 0.0, 1.0)[..., None]  # broadcast over Z
    active = deltas * ratio
    idle = deltas - active
    # dt <= 0 (first window, or a clock anomaly) → power 0, never inf
    dt = dt_s[..., None]
    safe_dt = jnp.where(dt > 0.0, dt, 1.0)
    power = jnp.where(dt > 0.0, deltas / safe_dt, 0.0)  # µJ/s == µW
    return NodeAttribution(
        energy_uj=deltas,
        active_uj=active,
        idle_uj=idle,
        power_uw=power,
        active_power_uw=jnp.where(dt > 0.0, active / safe_dt, 0.0),
        idle_power_uw=jnp.where(dt > 0.0, idle / safe_dt, 0.0),
    )


def _workload_ratios(
    cpu_deltas: jax.Array,
    workload_valid: jax.Array,
    node_cpu_delta: jax.Array,
) -> jax.Array:
    deltas = jnp.where(workload_valid, cpu_deltas, 0.0)
    denom = node_cpu_delta[..., None]
    return jnp.where(denom > 0.0, deltas / jnp.maximum(denom, 1e-30), 0.0)


@jax.jit
def attribute(
    zone_deltas_uj: jax.Array,  # f32 [Z]
    zone_valid: jax.Array,  # bool [Z]
    usage_ratio: jax.Array,  # f32 scalar
    cpu_deltas: jax.Array,  # f32 [W]
    workload_valid: jax.Array,  # bool [W]
    node_cpu_delta: jax.Array,  # f32 scalar
    dt_s: jax.Array,  # f32 scalar
) -> AttributionResult:
    """Single-node attribution: the reference's entire hot loop, jitted.

    Invariant (conservation, the executable spec of
    ``monitor_snapshot_integration_test.go``): for any subset S of workloads
    with ``Σ_{w∈S} Δcpu_w == node_cpu_delta``,
    ``Σ_{w∈S} energy[w,z] == active[z]`` (up to f32 rounding).
    """
    node = _node_split(zone_deltas_uj, zone_valid, usage_ratio, dt_s)
    ratios = _workload_ratios(cpu_deltas, workload_valid, node_cpu_delta)
    # [W] ⊗ [Z] outer product — XLA fuses this with the masking above.
    energy = ratios[..., :, None] * node.active_uj[..., None, :]
    power = ratios[..., :, None] * node.active_power_uw[..., None, :]
    return AttributionResult(
        node=node,
        workloads=WorkloadAttribution(
            energy_uj=energy, power_uw=power, cpu_ratio=ratios
        ),
    )


@jax.jit
def attribute_fleet(
    zone_deltas_uj: jax.Array,  # f32 [N, Z]
    zone_valid: jax.Array,  # bool [N, Z]
    usage_ratio: jax.Array,  # f32 [N]
    cpu_deltas: jax.Array,  # f32 [N, W]
    workload_valid: jax.Array,  # bool [N, W]
    node_cpu_delta: jax.Array,  # f32 [N]
    dt_s: jax.Array,  # f32 [N]
) -> AttributionResult:
    """Cluster-batched attribution over ``[nodes × workloads × zones]``.

    One einsum-shaped contraction attributes an entire fleet; the node axis
    shards across TPU devices (see ``kepler_tpu.parallel.aggregator``).
    Missing/late nodes are handled by zeroed masks (the batched analog of the
    reference's per-zone-error skip; SURVEY §5 "pad + mask the node axis").
    """
    node = _node_split(zone_deltas_uj, zone_valid, usage_ratio, dt_s)
    ratios = _workload_ratios(cpu_deltas, workload_valid, node_cpu_delta)
    energy = jnp.einsum("nw,nz->nwz", ratios, node.active_uj)
    power = jnp.einsum("nw,nz->nwz", ratios, node.active_power_uw)
    return AttributionResult(
        node=node,
        workloads=WorkloadAttribution(
            energy_uj=energy, power_uw=power, cpu_ratio=ratios
        ),
    )


def pad_to_bucket(n: int, bucket: int) -> int:
    """Next multiple of ``bucket`` ≥ max(n, 1) — bounds the set of compiled
    shapes (SURVEY §7 hard part (a): ragged fleets must not trigger a
    recompile per pod-count)."""
    n = max(n, 1)
    return ((n + bucket - 1) // bucket) * bucket
