"""Self-telemetry plane: cycle span tracing and self-metrics.

Kepler's whole value is attribution of invisible costs, yet until this
module the reproduction could not attribute its own: the monitor's
refresh duration lived in one debug log line, fleet delivery latency was
unobservable end-to-end, and the watchdog could say *that* a refresh
stalled but not *where*. This module is the missing instrument: a
low-overhead, monotonic-clock span recorder wired through every hot path
(monitor refresh stages, exporter scrape, agent emit→spool→drain→send,
aggregator ingest→decode→merge).

Model:

- ``span(name)`` is a context manager timing one stage on the calling
  thread. Spans nest; the **outermost** span on a thread is a *cycle*.
  While a cycle is open, its spans accumulate in a per-thread buffer
  with no locking at all; when the outermost span closes, the whole
  trace is flushed to the sinks under ONE lock acquisition per cycle.
- Sink 1 — **self-metrics**: per-stage duration histograms
  (``kepler_self_stage_duration_seconds{stage=…}``) plus
  ``kepler_self_cycle_overrun_total{cycle=…}`` when a cycle exceeds its
  budget (the monitor passes ``monitor.interval``), exposed through the
  standard custom-collector hook (:func:`collector`).
- Sink 2 — **traces**: a bounded ring of the last N complete cycle
  traces, served by ``/debug/traces`` (:func:`make_traces_handler`) as
  plain JSON or Chrome trace-event format loadable in Perfetto /
  ``chrome://tracing``. The watchdog snapshots :func:`inflight` on a
  stall so the stale-snapshot report can name the stuck stage.

Cost contract:

- **Disabled (the default until configured): ~O(100ns) per span.** The
  module-level :func:`span` is one global read, one attribute check, and
  a shared no-op context manager — safe to leave inline in the monitor's
  refresh loop (tests pin < 1µs per call).
- **Enabled: no locks on the span path.** Timing uses
  ``time.monotonic`` only (NTP steps must never produce negative stage
  durations); wall time enters a trace once per cycle, through the
  injected clock seam, purely as the Chrome-trace anchor.
- **Telemetry must never break the host component.** Trace flushing
  consults the ``telemetry.drop`` fault site so chaos tests can prove
  the pipeline survives its own observability being dropped; dropped
  traces are counted (``kepler_self_traces_dropped_total``), never
  raised.
"""

from __future__ import annotations

# keplint: monotonic-only — span durations must survive NTP clock steps;
# wall time only via the injected clock seam (chrome-trace anchors).

import bisect
import collections
import contextlib
import logging
import threading
import time as _time
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Sequence

from kepler_tpu import fault

log = logging.getLogger("kepler.telemetry")

DEFAULT_RING_SIZE = 32

# stage histograms: monitor stages are sub-millisecond to tens of ms on
# CPU; a slow scrape or a compile-bearing refresh lands in the seconds
DEFAULT_STAGE_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 10.0,
)

# end-to-end fleet delivery: fresh sends are milliseconds; spool replays
# carry outage durations, so the tail reaches hours
DEFAULT_DELIVERY_BUCKETS = (
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 60.0,
    300.0, 1800.0, 3600.0, 21600.0,
)


class Histogram:
    """Fixed-bucket histogram accumulator.

    The shared shape for both telemetry sinks: per-stage durations here,
    the aggregator's delivery-latency families on its side. NOT
    internally locked — owners observe/snapshot under their own lock
    (one acquisition per cycle / per ingest, never per bucket)."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float]) -> None:
        self.bounds: tuple[float, ...] = tuple(float(b) for b in bounds)
        self.counts = [0] * len(self.bounds)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        if i < len(self.counts):
            self.counts[i] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> list[tuple[str, int]]:
        """prometheus exposition shape: [(le, cumulative_count), …,
        ("+Inf", total)]."""
        out: list[tuple[str, int]] = []
        running = 0
        for bound, n in zip(self.bounds, self.counts):
            running += n
            out.append((repr(bound), running))
        out.append(("+Inf", self.count))
        return out


@dataclass(frozen=True)
class SpanEvent:
    """One completed span inside a cycle trace."""

    name: str
    depth: int  # 0 = the cycle itself
    rel_start_s: float  # seconds after cycle start (monotonic)
    duration_s: float
    # histogram stage key: None = use `name`; "" = trace-only (the span
    # shows in /debug/traces but observes no stage histogram). Keeps
    # per-instance span names (window.h2d_delta.s<k>) from minting one
    # kepler_self_stage_duration_seconds series per shard/index.
    stage: str | None = None


@dataclass(frozen=True)
class CycleTrace:
    """One complete cycle: the outermost span plus everything it nested."""

    name: str
    thread: str
    thread_id: int
    start_wall: float  # wall-clock anchor (clock seam) at cycle start
    duration_s: float
    overrun: bool
    events: tuple[SpanEvent, ...]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "thread": self.thread,
            "start": self.start_wall,
            "duration_s": self.duration_s,
            "overrun": self.overrun,
            "spans": [
                {"name": e.name, "depth": e.depth,
                 "rel_start_s": e.rel_start_s,
                 "duration_s": e.duration_s,
                 **({"stage": e.stage} if e.stage is not None else {})}
                for e in self.events
            ],
        }


class _ThreadState:
    """Per-thread span buffer. Touched ONLY by its owner thread on the
    span path; :meth:`SpanRecorder.inflight` reads a snapshot of
    ``stack`` cross-thread (a copy of a list of tuples — safe under the
    GIL, and worst case one entry stale)."""

    __slots__ = ("stack", "events", "wall_anchor", "mono_anchor",
                 "thread_name", "thread_id")

    def __init__(self) -> None:
        t = threading.current_thread()
        self.stack: list[tuple[str, float, float | None]] = []
        self.events: list[SpanEvent] = []
        self.wall_anchor = 0.0
        self.mono_anchor = 0.0
        self.thread_name = t.name
        self.thread_id = t.ident or 0


class _Span:
    """Live span handle (enabled path). Re-entrant use of one handle is
    not supported — ``span()`` returns a fresh handle per with-block."""

    __slots__ = ("_rec", "_st", "_name", "_budget", "_t0", "_depth",
                 "_stage")

    def __init__(self, rec: "SpanRecorder", st: _ThreadState, name: str,
                 budget_s: float | None,
                 stage: str | None = None) -> None:
        self._rec = rec
        self._st = st
        self._name = name
        self._budget = budget_s
        self._stage = stage

    def __enter__(self) -> "_Span":
        st = self._st
        if not st.stack:
            st.events = []
            st.wall_anchor = self._rec._clock()
            st.mono_anchor = self._rec._monotonic()
        self._depth = len(st.stack)
        self._t0 = self._rec._monotonic()
        st.stack.append((self._name, self._t0, self._budget))
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        t1 = self._rec._monotonic()
        st = self._st
        if st.stack:
            st.stack.pop()
        st.events.append(SpanEvent(
            name=self._name, depth=self._depth,
            rel_start_s=self._t0 - st.mono_anchor,
            duration_s=max(0.0, t1 - self._t0),
            stage=self._stage))
        if not st.stack:
            self._rec._complete_cycle(st, self._budget)


class _NoopSpan:
    """Shared disabled-path context manager: zero state, zero work."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        return None


_NOOP = _NoopSpan()


class SpanRecorder:
    """Span sink: stage histograms, overrun counters, trace ring.

    One instance is installed process-wide (see the module-level
    :func:`span` / :func:`install`); tests build private instances."""

    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = DEFAULT_RING_SIZE,
        stage_buckets: Sequence[float] = DEFAULT_STAGE_BUCKETS,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
    ) -> None:
        self._enabled = bool(enabled)
        self._clock = clock or _time.time  # wall: chrome-trace anchors only
        self._monotonic = monotonic or _time.monotonic
        self._stage_buckets = tuple(float(b) for b in stage_buckets)
        self._tls = threading.local()
        self._lock = threading.Lock()
        # everything below is guarded by _lock and touched once per
        # COMPLETED cycle, never per span. The trace ring is partitioned
        # PER CYCLE NAME (each a deque of the last ring_size cycles): on
        # an aggregator, ingest POSTs complete hundreds of cycles per
        # second while a fleet window completes once per interval — one
        # shared ring would evict every window trace within milliseconds
        # of a scrape, turning /debug/traces into 32 identical ingest
        # cycles. Cycle-name cardinality is code-bounded (the stage
        # catalog in docs/developer/observability.md), so memory stays
        # O(cycle kinds × ring_size).
        self._ring_size = max(1, int(ring_size))
        self._rings: dict[str, collections.deque[CycleTrace]] = {}
        self._hist: dict[str, Histogram] = {}
        self._overruns: dict[str, int] = {}
        self._dropped = 0
        self._cycles = 0
        # thread-id → _ThreadState, for the cross-thread inflight view
        self._threads: dict[int, tuple[threading.Thread, _ThreadState]] = {}

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- span API ------------------------------------------------------------

    def span(self, name: str, budget_s: float | None = None,
             stage: str | None = None):
        """Context manager timing one stage. ``budget_s`` is meaningful
        on the OUTERMOST span of a cycle: exceeding it counts one
        ``kepler_self_cycle_overrun_total{cycle=name}``. ``stage``
        overrides the histogram key (``""`` = trace-only) — see
        :class:`SpanEvent`."""
        if not self._enabled:
            return _NOOP
        return _Span(self, self._state(), name, budget_s, stage)

    def _state(self) -> _ThreadState:
        st = getattr(self._tls, "state", None)
        if st is None:
            st = _ThreadState()
            self._tls.state = st
            with self._lock:
                # prune dead threads so a churny thread pool can't grow
                # the registry without bound
                for tid in [t for t, (th, _s) in self._threads.items()
                            if not th.is_alive()]:
                    del self._threads[tid]
                self._threads[st.thread_id] = (
                    threading.current_thread(), st)
        return st

    def _complete_cycle(self, st: _ThreadState,
                        budget_s: float | None) -> None:
        events = tuple(st.events)
        st.events = []
        outer = events[-1]  # outermost span exits last
        overrun = budget_s is not None and outer.duration_s > budget_s
        if fault.fire("telemetry.drop") is not None:
            with self._lock:
                self._dropped += 1
            return
        trace = CycleTrace(
            name=outer.name, thread=st.thread_name,
            thread_id=st.thread_id, start_wall=st.wall_anchor,
            duration_s=outer.duration_s, overrun=overrun, events=events)
        with self._lock:
            self._cycles += 1
            for ev in events:
                key = ev.name if ev.stage is None else ev.stage
                if not key:
                    continue  # trace-only span (stage="")
                hist = self._hist.get(key)
                if hist is None:
                    hist = self._hist[key] = Histogram(
                        self._stage_buckets)
                hist.observe(ev.duration_s)
            if overrun:
                self._overruns[outer.name] = \
                    self._overruns.get(outer.name, 0) + 1
                log.warning("cycle %s overran its budget: %.2f ms > "
                            "%.2f ms", outer.name,
                            outer.duration_s * 1e3, budget_s * 1e3)
            ring = self._rings.get(outer.name)
            if ring is None:
                ring = self._rings[outer.name] = collections.deque(
                    maxlen=self._ring_size)
            ring.append(trace)
        # the ONE timing debug log (replaces the monitor's ad-hoc
        # "refresh done in" line — one source of truth for cycle timing)
        log.debug("%s done in %.2f ms (%d spans)", outer.name,
                  outer.duration_s * 1e3, len(events))

    # -- read API ------------------------------------------------------------

    def recent_traces(self) -> list[CycleTrace]:
        """Complete cycle traces across every per-cycle ring, ordered by
        wall-clock start (newest last)."""
        with self._lock:
            traces = [t for ring in self._rings.values() for t in ring]
        traces.sort(key=lambda t: t.start_wall)
        return traces

    def inflight(self) -> list[dict]:
        """Open spans per thread, outermost first — the watchdog's
        where-is-it-stuck snapshot. Reads other threads' stacks without
        their cooperation: safe (list-of-tuples snapshot under the GIL),
        and at worst one span stale."""
        now = self._monotonic()
        with self._lock:
            states = [st for _th, st in self._threads.values()]
        out = []
        for st in states:
            stack = list(st.stack)
            if not stack:
                continue
            out.append({
                "thread": st.thread_name,
                "spans": [{"name": name,
                           "elapsed_s": max(0.0, now - t0)}
                          for name, t0, _budget in stack],
            })
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self._enabled, "cycles": self._cycles,
                    "dropped": self._dropped,
                    "overruns": dict(self._overruns),
                    "stages": sorted(self._hist)}

    # -- sink 1: prometheus self-metrics --------------------------------------

    def collect(self):
        """prometheus_client custom-collector hook (kepler_self_*)."""
        from prometheus_client.core import (
            CounterMetricFamily,
            HistogramMetricFamily,
        )
        with self._lock:
            hist_snap = [(stage, list(h.counts), h.sum, h.count)
                         for stage, h in sorted(self._hist.items())]
            overruns = dict(self._overruns)
            dropped = self._dropped
        stage_family = HistogramMetricFamily(
            "kepler_self_stage_duration_seconds",
            "Duration of one instrumented pipeline stage (span)",
            labels=["stage"])
        for stage, counts, total_sum, count in hist_snap:
            h = Histogram(self._stage_buckets)
            h.counts, h.sum, h.count = counts, total_sum, count
            stage_family.add_metric([stage], buckets=h.cumulative(),
                                    sum_value=total_sum)
        yield stage_family
        over = CounterMetricFamily(
            "kepler_self_cycle_overrun_total",
            "Cycles that exceeded their duration budget "
            "(monitor refreshes longer than monitor.interval)",
            labels=["cycle"])
        for cycle, n in sorted(overruns.items()):
            over.add_metric([cycle], n)
        yield over
        drop = CounterMetricFamily(
            "kepler_self_traces_dropped_total",
            "Completed cycle traces dropped before reaching the sinks "
            "(telemetry.drop fault site)")
        drop.add_metric([], dropped)
        yield drop

    # -- sink 2: trace export --------------------------------------------------

    def chrome_trace(self) -> dict:
        """Ring contents in Chrome trace-event format (Perfetto /
        chrome://tracing: complete "X" events on a wall-clock µs axis,
        plus thread-name metadata)."""
        events: list[dict] = []
        named: set[int] = set()
        for tr in self.recent_traces():
            base_us = tr.start_wall * 1e6
            if tr.thread_id not in named:
                named.add(tr.thread_id)
                events.append({"name": "thread_name", "ph": "M", "pid": 0,
                               "tid": tr.thread_id,
                               "args": {"name": tr.thread}})
            for ev in tr.events:
                events.append({
                    "name": ev.name, "ph": "X", "cat": "kepler",
                    "ts": base_us + ev.rel_start_s * 1e6,
                    "dur": ev.duration_s * 1e6,
                    "pid": 0, "tid": tr.thread_id,
                    "args": {"depth": ev.depth},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# module-level installed recorder (the cheap instrumentation surface)
# ---------------------------------------------------------------------------

# starts DISABLED: an unconfigured import (library use, unit tests) pays
# only the no-op fast path until a binary calls install_from_config
_active = SpanRecorder(enabled=False)


def recorder() -> SpanRecorder:
    return _active


def install(rec: SpanRecorder) -> SpanRecorder:
    """Install a recorder process-wide; instrumented layers pick it up on
    their next span."""
    global _active
    _active = rec
    return rec


def span(name: str, budget_s: float | None = None,
         stage: str | None = None):
    """The instrumentation point. Disabled cost: one global read, one
    attribute check, a shared no-op context manager. ``stage``
    re-keys the stage histogram (``""`` = trace-only), so per-instance
    span names never mint per-instance metric series."""
    rec = _active
    if not rec._enabled:
        return _NOOP
    return rec.span(name, budget_s, stage)


def inflight() -> list[dict]:
    return _active.inflight()


def recent_traces() -> list[CycleTrace]:
    return _active.recent_traces()


def install_from_config(cfg: Any) -> SpanRecorder:
    """Build + install a recorder from a ``TelemetryConfig`` (config.py).
    Shared by both binaries (cmd/main, cmd/aggregator)."""
    rec = SpanRecorder(
        enabled=cfg.enabled,
        ring_size=cfg.ring_size,
        stage_buckets=cfg.stage_buckets or DEFAULT_STAGE_BUCKETS,
    )
    return install(rec)


@contextlib.contextmanager
def installed(rec: SpanRecorder) -> Iterator[SpanRecorder]:
    """Test helper: install ``rec`` for a with-block, always restoring
    the previous recorder on exit."""
    prev = _active
    install(rec)
    try:
        yield rec
    finally:
        install(prev)


class SelfMetricsCollector:
    """Registry adapter yielding the INSTALLED recorder's families at
    scrape time (not the recorder captured at wiring time), so a late
    install_from_config or a test's :func:`installed` swap is always the
    one scraped."""

    def collect(self):
        yield from _active.collect()


def collector() -> SelfMetricsCollector:
    return SelfMetricsCollector()


# ---------------------------------------------------------------------------
# /debug/traces endpoint
# ---------------------------------------------------------------------------


def make_traces_handler(rec: SpanRecorder | None = None):
    """APIServer handler serving recent cycle traces.

    ``GET /debug/traces`` → ``{"enabled", "traces", "inflight"}`` JSON;
    ``GET /debug/traces?format=chrome`` → Chrome trace-event JSON
    (load in Perfetto / chrome://tracing). ``rec=None`` follows the
    installed recorder."""
    import json
    from urllib.parse import parse_qs, urlparse

    # keplint: thread-role=http-handler
    def handler(request) -> tuple[int, dict[str, str], bytes]:
        active = rec if rec is not None else _active
        qs = parse_qs(urlparse(request.path).query)
        fmt = qs.get("format", ["json"])[0]
        if fmt == "chrome":
            payload = active.chrome_trace()
        elif fmt == "json":
            payload = {
                "enabled": active.enabled,
                "traces": [t.to_dict() for t in active.recent_traces()],
                "inflight": active.inflight(),
            }
        else:
            return (400, {"Content-Type": "text/plain"},
                    f"unknown format {fmt!r}; use json or chrome\n".encode())
        return (200, {"Content-Type": "application/json"},
                json.dumps(payload).encode())

    return handler
