"""Hybrid Logical Clock for the fleet black box (causal event order).

A fleet incident — lease succession, membership epoch bump, rung
demotion cascade — spans processes whose wall clocks disagree. An HLC
stamp ``(phys_us, logical, node)`` gives every journal event a total
order that (a) never runs backwards on one node, (b) respects causality
across nodes whenever a message carries the sender's stamp, and (c)
stays within bounded skew of real time, so a merged fleet timeline reads
like a wall-clock trace with ties broken deterministically.

Threat model (KTL112 taint discipline, same as ring epochs): the stamp
rides the wire, so a hostile or broken peer can present an arbitrary
clock. :func:`parse_hlc` launders the wire text (bounded digits, bounded
printable node id, bools rejected) and :meth:`HlcClock.observe` clamps a
remote physical component more than ``max_drift_s`` ahead of the local
wall clock — the merge still advances causally past the clamped value,
but a single vaulted stamp can never drag the whole fleet's clocks years
into the future. Clamp events and the last observed offset are exported
(``kepler_fleet_hlc_clamped_total`` / ``kepler_fleet_hlc_drift_seconds``
via the journal collector).

Determinism: all wall reads go through the injected ``clock`` seam, so
kepchaos runs the HLC on the conductor's virtual clock and the merged
journal is bit-replayable.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, NamedTuple

__all__ = ["HLC", "HlcClock", "MAX_NODE_LEN", "parse_hlc"]

# wire-field bounds (laundering caps, not protocol limits)
MAX_NODE_LEN = 128          # matches the wire name cap's order of magnitude
_MAX_PHYS_DIGITS = 17       # < 2**56 µs ≈ year 4254; rejects vault-to-inf
_MAX_LOGICAL = 1 << 20      # ties within one µs; a hostile 2**63 is clamped
DEFAULT_MAX_DRIFT_S = 60.0

_PHYS_RE = re.compile(r"^[0-9]{1,%d}$" % _MAX_PHYS_DIGITS)
_LOGICAL_RE = re.compile(r"^[0-9]{1,9}$")


class HLC(NamedTuple):
    """One stamp. NamedTuple ordering IS the causal total order:
    ``(phys_us, logical, node)`` lexicographic."""

    phys_us: int
    logical: int
    node: str

    def encode(self) -> str:
        """Wire text ``phys_us:logical:node`` (node may itself contain
        colons — parse splits from the left)."""
        return f"{self.phys_us}:{self.logical}:{self.node}"

    def to_dict(self) -> dict[str, object]:
        return {"phys_us": self.phys_us, "logical": self.logical,
                "node": self.node}


def _sane_node(node: str) -> bool:
    if len(node) > MAX_NODE_LEN:
        return False
    return all(" " < ch <= "~" for ch in node)


def parse_hlc(text: object) -> HLC | None:
    """Launder a wire-borne HLC stamp; hostile input → ``None``, never an
    exception and never a poisoned value.

    Rejected: non-strings (incl. bools/ints), wrong field count,
    signed/float/overlong numerics, logical above the tie cap, node ids
    that are overlong or non-printable.
    """
    # keplint: sanitizes
    if not isinstance(text, str):
        return None
    parts = text.split(":", 2)
    if len(parts) != 3:
        return None
    phys_s, logical_s, node = parts
    if not _PHYS_RE.match(phys_s) or not _LOGICAL_RE.match(logical_s):
        return None
    logical = int(logical_s)
    if logical > _MAX_LOGICAL:
        return None
    if not _sane_node(node):
        return None
    return HLC(int(phys_s), logical, node)


class HlcClock:
    """The per-process clock: ``now()`` to stamp a local event or an
    outgoing message, ``observe()`` to merge an inbound stamp."""

    __slots__ = ("_clock", "_last_drift_s", "_clamped", "_lock",
                 "_logical", "_max_drift_s", "_node", "_phys_us")

    def __init__(self, node: str = "", *,
                 clock: Callable[[], float] = time.time,
                 max_drift_s: float = DEFAULT_MAX_DRIFT_S) -> None:
        self._node = node
        self._clock = clock
        self._max_drift_s = float(max_drift_s)
        self._lock = threading.Lock()
        self._phys_us = 0
        self._logical = 0
        self._last_drift_s = 0.0
        self._clamped = 0

    @property
    def node(self) -> str:
        return self._node

    def now(self) -> HLC:
        """Advance for a local/send event."""
        with self._lock:
            wall_us = int(self._clock() * 1e6)
            if wall_us > self._phys_us:
                self._phys_us = wall_us
                self._logical = 0
            else:
                self._logical += 1
            return HLC(self._phys_us, self._logical, self._node)

    def observe(self, remote: HLC) -> HLC:
        """Merge an inbound stamp (receive event). A remote physical
        component more than ``max_drift_s`` ahead of the local wall
        clock is clamped to the drift bound before merging, so a
        vaulted peer advances us at most one drift window."""
        with self._lock:
            wall_us = int(self._clock() * 1e6)
            limit_us = wall_us + int(self._max_drift_s * 1e6)
            self._last_drift_s = (remote.phys_us - wall_us) / 1e6
            r_phys, r_logical = remote.phys_us, remote.logical
            if r_phys > limit_us:
                self._clamped += 1
                r_phys, r_logical = limit_us, 0
            prev_phys, prev_logical = self._phys_us, self._logical
            phys = max(prev_phys, r_phys, wall_us)
            if phys == prev_phys and phys == r_phys:
                logical = max(prev_logical, r_logical) + 1
            elif phys == prev_phys:
                logical = prev_logical + 1
            elif phys == r_phys:
                logical = r_logical + 1
            else:
                logical = 0
            self._phys_us, self._logical = phys, logical
            return HLC(phys, logical, self._node)

    def drift_seconds(self) -> float:
        """Signed offset (remote − local wall) of the last observed
        stamp; the ``kepler_fleet_hlc_drift_seconds`` gauge."""
        with self._lock:
            return self._last_drift_s

    def clamped_total(self) -> int:
        with self._lock:
            return self._clamped
