"""Self-telemetry plane: span tracing, self-metrics, trace export.

See :mod:`kepler_tpu.telemetry.spans` for the model and cost contract.
"""

from kepler_tpu.telemetry.spans import (
    DEFAULT_DELIVERY_BUCKETS,
    DEFAULT_RING_SIZE,
    DEFAULT_STAGE_BUCKETS,
    CycleTrace,
    Histogram,
    SelfMetricsCollector,
    SpanEvent,
    SpanRecorder,
    collector,
    inflight,
    install,
    install_from_config,
    installed,
    make_traces_handler,
    recent_traces,
    recorder,
    span,
)

__all__ = [
    "DEFAULT_DELIVERY_BUCKETS",
    "DEFAULT_RING_SIZE",
    "DEFAULT_STAGE_BUCKETS",
    "CycleTrace",
    "Histogram",
    "SelfMetricsCollector",
    "SpanEvent",
    "SpanRecorder",
    "collector",
    "inflight",
    "install",
    "install_from_config",
    "installed",
    "make_traces_handler",
    "recent_traces",
    "recorder",
    "span",
]
