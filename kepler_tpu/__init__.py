"""kepler-tpu: a TPU-native power-attribution framework.

A ground-up re-design of Kepler's capability surface (reference:
``sthaha/kepler``, a single-node Go Prometheus exporter that reads Intel RAPL
energy counters and attributes power to processes/containers/VMs/pods by
CPU-time-delta ratios) as a TPU-first framework:

- Host Python does I/O (sysfs RAPL counters, /proc scans, Kubernetes watch).
- The attribution core is a pure, jittable tensor function (``kepler_tpu.ops``)
  evaluated on TPU — a single fused gather + outer-product instead of the
  reference's per-workload scalar loop (reference
  ``internal/monitor/process.go:123-145``).
- Learned power models (linear / MLP, the kepler-model-server capability) run
  batched alongside ratio attribution (``kepler_tpu.models``).
- A cluster aggregator shards ``[nodes x pods x features]`` batches across a
  ``jax.sharding.Mesh`` (``kepler_tpu.parallel``) so one TPU attributes an
  entire fleet.

Layer map (mirrors reference SURVEY §1, re-expressed TPU-first)::

    RAPL sysfs ──> device ──┐
    /proc ───────> resource ─┼─> monitor (jitted attribution) ─> exporters ─> server
    K8s API ─────> k8s.pod ──┘
    wired by: config + service lifecycle
"""

from kepler_tpu.version import __version__

__all__ = ["__version__"]
