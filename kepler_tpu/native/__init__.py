"""Native (C++) fast path for host-side IO.

The TPU attribution math is one fused device program; what remains
host-bound is the per-tick procfs scan and sysfs counter reads (SURVEY §7
hard part (d)). ``src/scan.cpp`` batches those into single C calls; this
module builds it on demand with ``g++`` (no pybind11 in the toolchain —
plain C ABI via ctypes) and exposes a typed wrapper.

Everything degrades gracefully: if no compiler or the build fails, callers
get ``None`` from :func:`load` and fall back to the pure-Python readers.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("kepler.native")

_SRC = os.path.join(os.path.dirname(__file__), "src", "scan.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_BUILD_DIR, "libkepler_scan.so")
_ABI_VERSION = 1

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def lib_path() -> str:
    return _LIB


def ensure_built(force: bool = False) -> str | None:
    """Compile the shared library if missing/stale. Returns its path or None.

    Rebuilds when the source is newer than the .so (dev loop) — the compile
    is ~1 s and happens at most once per process.
    """
    with _lock:
        have_lib = os.path.exists(_LIB)
        if not os.path.exists(_SRC):
            # source-less install (e.g. prebuilt image): use the .so as-is
            return _LIB if have_lib else None
        if (not force and have_lib
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # compile to a pid-suffixed temp and rename: concurrent processes
        # (the in-process lock can't see them) each build privately and the
        # atomic rename means readers never dlopen a half-written .so
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
            "-Wall", "-Wextra", _SRC, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
        except (OSError, subprocess.SubprocessError) as err:
            detail = getattr(err, "stderr", b"") or b""
            log.warning("native build failed (%s): %s — using pure-Python "
                        "readers", err, detail.decode("utf-8", "replace")[:500])
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return _LIB


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None on any failure."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("KEPLER_NO_NATIVE"):
        return None
    path = ensure_built()
    if path is None:
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.kepler_native_abi_version.restype = ctypes.c_int
        if lib.kepler_native_abi_version() != _ABI_VERSION:
            raise OSError(
                f"ABI mismatch: {lib.kepler_native_abi_version()} "
                f"!= {_ABI_VERSION}")
        lib.kepler_scan_procs.restype = ctypes.c_int
        lib.kepler_scan_procs.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32,
        ]
        lib.kepler_read_stat_totals.restype = ctypes.c_int
        lib.kepler_read_stat_totals.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.kepler_read_counter_files.restype = ctypes.c_int
        lib.kepler_read_counter_files.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
        ]
    except (OSError, AttributeError) as err:
        # AttributeError: a stale/foreign .so missing expected symbols
        log.warning("native load failed: %s — using pure-Python readers", err)
        _load_failed = True
        return None
    _lib = lib
    return lib


class NativeScanner:
    """Typed wrapper over the C calls. One instance is thread-safe."""

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib

    def scan_procs(self, procfs: str = "/proc",
                   cap: int = 8192) -> tuple[np.ndarray, np.ndarray]:
        """→ (pids int32 [n], cpu_seconds f64 [n]) for all live PIDs."""
        procfs_b = procfs.encode()
        while True:
            pids = np.empty(cap, np.int32)
            cpu = np.empty(cap, np.float64)
            n = self._lib.kepler_scan_procs(
                procfs_b,
                pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                cpu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                cap,
            )
            if n == -2:  # more PIDs than cap — grow and rescan
                cap *= 4
                continue
            if n < 0:
                raise OSError(f"cannot scan {procfs}")
            return pids[:n].copy(), cpu[:n].copy()

    def stat_totals(self, procfs: str = "/proc") -> tuple[float, float]:
        """→ (active, total) jiffies from the aggregate 'cpu' line."""
        active = ctypes.c_double()
        total = ctypes.c_double()
        rc = self._lib.kepler_read_stat_totals(
            procfs.encode(), ctypes.byref(active), ctypes.byref(total))
        if rc != 0:
            raise OSError(f"cannot read {procfs}/stat")
        return active.value, total.value

    def read_counters(self, paths: list[str]) -> np.ndarray:
        """Batch-read uint64 counter files; failures → UINT64_MAX."""
        out = np.empty(len(paths), np.uint64)
        if not paths:
            return out
        blob = b"\0".join(p.encode() for p in paths) + b"\0"
        self._lib.kepler_read_counter_files(
            blob, len(paths),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out


def scanner() -> NativeScanner | None:
    """The process-wide scanner, or None when native is unavailable."""
    lib = load()
    return NativeScanner(lib) if lib is not None else None
