"""Native (C++) fast path for host-side IO.

The TPU attribution math is one fused device program; what remains
host-bound is the per-tick procfs scan and sysfs counter reads (SURVEY §7
hard part (d)). ``src/scan.cpp`` batches those into single C calls; this
module builds it on demand with ``g++`` (no pybind11 in the toolchain —
plain C ABI via ctypes) and exposes a typed wrapper.

Everything degrades gracefully: if no compiler or the build fails, callers
get ``None`` from :func:`load` and fall back to the pure-Python readers.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("kepler.native")

_SRC = os.path.join(os.path.dirname(__file__), "src", "scan.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_BUILD_DIR, "libkepler_scan.so")
_ABI_VERSION = 3

# comm slot width in kepler_scan_procs output (scan.cpp kCommSlot)
_COMM_SLOT = 32

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def lib_path() -> str:
    return _LIB


def ensure_built(force: bool = False) -> str | None:
    """Compile the shared library if missing/stale. Returns its path or None.

    Rebuilds when the source is newer than the .so (dev loop) — the compile
    is ~1 s and happens at most once per process.
    """
    with _lock:
        have_lib = os.path.exists(_LIB)
        if not os.path.exists(_SRC):
            # source-less install (e.g. prebuilt image): use the .so as-is
            return _LIB if have_lib else None
        if (not force and have_lib
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # compile to a pid-suffixed temp and rename: concurrent processes
        # (the in-process lock can't see them) each build privately and the
        # atomic rename means readers never dlopen a half-written .so
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
            "-Wall", "-Wextra", _SRC, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
        except (OSError, subprocess.SubprocessError) as err:
            detail = getattr(err, "stderr", b"") or b""
            log.warning("native build failed (%s): %s — using pure-Python "
                        "readers", err, detail.decode("utf-8", "replace")[:500])
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return _LIB


# keplint: role-boundary — one-time lazy build+dlopen, memoized in _lib;
# after the first call this is a pointer return, so hot-loop callers only
# ever pay the subprocess/compile cost once at startup
def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None on any failure."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("KEPLER_NO_NATIVE"):
        return None
    path = ensure_built()
    if path is None:
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.kepler_native_abi_version.restype = ctypes.c_int
        if lib.kepler_native_abi_version() != _ABI_VERSION:
            raise OSError(
                f"ABI mismatch: {lib.kepler_native_abi_version()} "
                f"!= {_ABI_VERSION}")
        lib.kepler_scan_procs.restype = ctypes.c_int
        lib.kepler_scan_procs.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_char),
            ctypes.c_int32,
        ]
        lib.kepler_scan_open.restype = ctypes.c_void_p
        lib.kepler_scan_open.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        lib.kepler_scan_free.restype = None
        lib.kepler_scan_free.argtypes = [ctypes.c_void_p]
        lib.kepler_scan_tick.restype = ctypes.c_int
        lib.kepler_scan_tick.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_char),
            ctypes.c_int32,
        ]
        lib.kepler_read_stat_totals.restype = ctypes.c_int
        lib.kepler_read_stat_totals.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.kepler_read_counter_files.restype = ctypes.c_int
        lib.kepler_read_counter_files.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kepler_read_files.restype = ctypes.c_int
        lib.kepler_read_files.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.kepler_read_links.restype = ctypes.c_int
        lib.kepler_read_links.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.kepler_fmt_double.restype = ctypes.c_int
        lib.kepler_fmt_double.argtypes = [
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_char),
        ]
        lib.kepler_render_samples.restype = ctypes.c_int64
        lib.kepler_render_samples.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_double,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char),
            ctypes.c_int64,
        ]
    except (OSError, AttributeError) as err:
        # AttributeError: a stale/foreign .so missing expected symbols
        log.warning("native load failed: %s — using pure-Python readers", err)
        _load_failed = True
        return None
    _lib = lib
    return lib


class NativeScanner:
    """Typed wrapper over the C calls. One instance is thread-safe.

    Scans go through a per-procfs *scan handle* (``kepler_scan_open``),
    which keeps each PID's stat fd open across ticks and preads it —
    ~5× faster than open/read/close per PID at 10k procs. One handle
    lives per distinct procfs path (a real agent has exactly one); a
    process-global fd budget in the C layer keeps many-handle test
    suites within RLIMIT_NOFILE. Handles are never auto-freed (freeing
    one under a concurrent scan would be use-after-free) — tests that
    churn thousands of fake trees can call :meth:`close_handles`.
    """

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        self._handles: dict[str, int] = {}  # procfs → C handle
        self._handles_lock = threading.Lock()

    def _handle(self, procfs: str) -> int | None:
        with self._handles_lock:
            h = self._handles.get(procfs)
            if h is not None:
                return h
            h = self._lib.kepler_scan_open(procfs.encode(), 0)
            if not h:
                return None
            self._handles[procfs] = h
            return h

    def close_handles(self) -> None:
        """Release every scan handle (and its cached fds)."""
        with self._handles_lock:
            for h in self._handles.values():
                self._lib.kepler_scan_free(h)
            self._handles.clear()

    def scan_procs(self, procfs: str = "/proc", cap: int = 8192,
                   want_comms: bool = True
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
        """→ (pids int32 [n], cpu_seconds f64 [n], comms S32 [n] | None)
        for all live PIDs. comms are the stat-line command names — the
        same field /proc/<pid>/comm serves, so callers skip per-PID comm
        reads entirely."""
        procfs_b = procfs.encode()
        handle = self._handle(procfs)
        while True:
            pids = np.empty(cap, np.int32)
            cpu = np.empty(cap, np.float64)
            comms = (np.zeros(cap, f"S{_COMM_SLOT}") if want_comms else None)
            comms_ptr = (comms.ctypes.data_as(ctypes.POINTER(ctypes.c_char))
                         if comms is not None else None)
            if handle is not None:
                n = self._lib.kepler_scan_tick(
                    handle,
                    pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    cpu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    comms_ptr, cap)
            else:
                n = self._lib.kepler_scan_procs(
                    procfs_b,
                    pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    cpu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    comms_ptr, cap)
            if n == -2:  # more PIDs than cap — grow and rescan
                cap *= 4
                continue
            if n < 0:
                raise OSError(f"cannot scan {procfs}")
            return (pids[:n].copy(), cpu[:n].copy(),
                    comms[:n].copy() if comms is not None else None)

    def stat_totals(self, procfs: str = "/proc") -> tuple[float, float]:
        """→ (active, total) jiffies from the aggregate 'cpu' line."""
        active = ctypes.c_double()
        total = ctypes.c_double()
        rc = self._lib.kepler_read_stat_totals(
            procfs.encode(), ctypes.byref(active), ctypes.byref(total))
        if rc != 0:
            raise OSError(f"cannot read {procfs}/stat")
        return active.value, total.value

    def read_counters(self, paths: list[str]) -> np.ndarray:
        """Batch-read uint64 counter files; failures → UINT64_MAX."""
        out = np.empty(len(paths), np.uint64)
        if not paths:
            return out
        blob = b"\0".join(p.encode() for p in paths) + b"\0"
        self._lib.kepler_read_counter_files(
            blob, len(paths),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return out

    def read_files(self, paths: list[str], per_cap: int = 8192
                   ) -> list[bytes | None]:
        """Batch-read small files (threaded in C); None per failed path.
        Contents truncate at ``per_cap - 1`` bytes — size accordingly."""
        n = len(paths)
        if n == 0:
            return []
        blob = b"\0".join(p.encode() for p in paths) + b"\0"
        out = np.empty(n * per_cap, np.uint8)
        sizes = np.empty(n, np.int32)
        rc = self._lib.kepler_read_files(
            blob, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            per_cap,
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc < 0:
            raise OSError("batched file read failed")
        return [
            (out[i * per_cap:i * per_cap + sizes[i]].tobytes()
             if sizes[i] >= 0 else None)
            for i in range(n)
        ]

    def read_links(self, paths: list[str], per_cap: int = 1024
                   ) -> list[str | None]:
        """Batch-readlink (threaded in C); None per failed path."""
        n = len(paths)
        if n == 0:
            return []
        blob = b"\0".join(p.encode() for p in paths) + b"\0"
        out = np.empty(n * per_cap, np.uint8)
        sizes = np.empty(n, np.int32)
        rc = self._lib.kepler_read_links(
            blob, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            per_cap,
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc < 0:
            raise OSError("batched readlink failed")
        return [
            (out[i * per_cap:i * per_cap + sizes[i]].tobytes()
             .decode("utf-8", "replace")) if sizes[i] >= 0 else None
            for i in range(n)
        ]

    def fmt_double(self, v: float) -> bytes:
        """floatToGoString-compatible formatting (parity-tested)."""
        buf = ctypes.create_string_buffer(48)
        n = self._lib.kepler_fmt_double(float(v), buf)
        return buf.raw[:n]

    def render_samples(self, name: bytes, prefix_blob: bytes,
                       prefix_off: np.ndarray, ztail_blob: bytes,
                       ztail_off: np.ndarray, values: np.ndarray,
                       div: float, round6: bool = False) -> bytes:
        """Render one metric family's sample lines (see scan.cpp).

        ``values`` must be C-contiguous float64 ``[n, nz]`` with
        ``n == len(prefix_off) - 1`` and ``nz == len(ztail_off) - 1``;
        ``prefix_off``/``ztail_off`` are int64/int32 byte offsets into the
        blobs. Returns the rendered classic-text bytes.
        """
        n = len(prefix_off) - 1
        nz = len(ztail_off) - 1
        values = np.ascontiguousarray(values, np.float64)
        if values.shape != (n, nz):
            raise ValueError(f"values shape {values.shape} != ({n}, {nz})")
        # worst case per sample: name + prefix + ztail + 48-char float + \n.
        # np.empty = malloc without memset (create_string_buffer would
        # zero-fill megabytes per scrape for nothing)
        cap = (nz * len(prefix_blob) + n * len(ztail_blob)
               + n * nz * (len(name) + 49) + 64)
        out = np.empty(cap, np.uint8)
        rc = self._lib.kepler_render_samples(
            name, len(name), prefix_blob,
            prefix_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, ztail_blob,
            ztail_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            nz,
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            float(div), 1 if round6 else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)), cap)
        if rc < 0:
            raise OSError("native sample render failed (buffer overflow?)")
        return out[:rc].tobytes()


def scanner() -> NativeScanner | None:
    """The process-wide scanner, or None when native is unavailable."""
    lib = load()
    return NativeScanner(lib) if lib is not None else None
