// Native batched procfs/sysfs readers — the host-side hot path.
//
// Reference parity: the per-PID /proc/<pid>/stat scan of
// internal/resource/procfs_reader.go (CPUTime = (utime+stime)/USER_HZ,
// :73-82), the /proc/stat usage-ratio totals (:107-141), and the per-zone
// energy_uj reads of internal/device/rapl_sysfs_power_meter.go — but done
// as ONE C call per tick instead of thousands of Python open/read/parse
// round-trips. SURVEY §7 hard part (d): the procfs scan, not the TPU math,
// is the per-node bottleneck; this is its fast path.
//
// Pure C ABI (called via ctypes — no pybind11 in this toolchain). Callers
// own every OUTPUT buffer; the scan allocates transient working vectors
// (dirent names + per-entry results) and, for large trees, a few
// short-lived threads. All C++ exceptions are caught at the ABI boundary
// and surfaced as -1 (callers fall back to the pure-Python reader) — no
// exception may unwind into ctypes frames.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <dirent.h>
#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <string>
#include <thread>
#include <vector>

namespace {

// Reference hardcodes USER_HZ=100 (procfs_reader.go:73-82); Linux has had
// CONFIG_HZ-independent USER_HZ=100 since 2.6, so parity and correctness
// agree.
constexpr double kUserHz = 100.0;

// Read a small file fully into buf (NUL-terminated). Returns bytes read or
// -1. procfs files must be read in one pass; short buffers truncate safely.
int ReadSmallFile(const char* path, char* buf, int cap) {
  int fd = open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -1;
  int n = 0;
  while (n < cap - 1) {
    ssize_t r = read(fd, buf + n, cap - 1 - n);
    if (r < 0) {
      close(fd);
      return -1;
    }
    if (r == 0) break;
    n += static_cast<int>(r);
  }
  close(fd);
  buf[n] = '\0';
  return n;
}

bool AllDigits(const char* s) {
  if (*s == '\0') return false;
  for (; *s; ++s) {
    if (*s < '0' || *s > '9') return false;
  }
  return true;
}

}  // namespace

extern "C" {

// ABI version for the ctypes loader to sanity-check.
int kepler_native_abi_version() { return 1; }

// Parse one <pid>/stat file; true on success. Thread-safe: all state is
// caller-provided.
static bool ParseProcStat(const char* procfs, const char* name,
                          int32_t* pid, double* cpu_seconds) {
  char path[512];
  char buf[4096];
  snprintf(path, sizeof(path), "%s/%s/stat", procfs, name);
  if (ReadSmallFile(path, buf, sizeof(buf)) <= 0) return false;
  // comm may contain spaces/parens; fields resume after the LAST ')'
  // (same parse as the Python reader and the reference's procfs lib).
  char* rparen = strrchr(buf, ')');
  if (rparen == nullptr || rparen[1] == '\0') return false;
  char* rest = rparen + 2;
  // After the ')' the next fields are state(0) ... utime(11) stime(12),
  // 0-indexed — i.e. stat fields 14 and 15 in proc(5) numbering.
  unsigned long long utime = 0, stime = 0;
  int tok = 0;
  bool ok = false;
  char* save = nullptr;
  for (char* t = strtok_r(rest, " ", &save); t != nullptr;
       t = strtok_r(nullptr, " ", &save), ++tok) {
    // endptr checks: a corrupt stat line (non-numeric utime/stime) must
    // skip the process, matching the pure-Python reader's raise-and-skip
    // semantics — not admit it with cpu_seconds=0. strtok_r tokens are
    // NUL-terminated, so a fully-numeric token ends exactly at '\0'.
    char* end = nullptr;
    if (tok == 11) {
      utime = strtoull(t, &end, 10);
      if (end == t || *end != '\0') return false;
    } else if (tok == 12) {
      stime = strtoull(t, &end, 10);
      if (end == t || *end != '\0') return false;
      ok = true;
      break;
    }
  }
  if (!ok) return false;
  *pid = static_cast<int32_t>(strtol(name, nullptr, 10));
  *cpu_seconds = static_cast<double>(utime + stime) / kUserHz;
  return true;
}

// Scan every numeric entry of `procfs`, parse <pid>/stat, and fill
// pids[i] / cpu_seconds[i] with the PID and (utime+stime)/USER_HZ.
// Returns the number of entries filled, -1 if procfs can't be opened, or
// -2 if more than `cap` processes exist (caller retries with a bigger
// buffer). PIDs that vanish mid-scan are skipped, matching the reference's
// skip-on-ESRCH behavior (informer.go:186-190).
//
// Large trees fan the per-PID open/read/parse out to a few threads — the
// scan is syscall-latency bound (one open+read+close per PID), and the
// kernel serves independent /proc files concurrently. Output order stays
// the directory order regardless of thread count.
int kepler_scan_procs(const char* procfs, int32_t* pids, double* cpu_seconds,
                      int32_t cap) try {
  DIR* dir = opendir(procfs);
  if (dir == nullptr) return -1;
  std::vector<std::string> names;
  struct dirent* entry;
  while ((entry = readdir(dir)) != nullptr) {
    if (AllDigits(entry->d_name)) names.emplace_back(entry->d_name);
  }
  closedir(dir);
  const size_t n = names.size();
  if (cap < 0) return -1;  // -2 would make callers grow-and-retry forever
  if (n > static_cast<size_t>(cap)) return -2;

  std::vector<int32_t> got_pid(n);
  std::vector<double> got_cpu(n);
  std::vector<char> ok(n, 0);  // vector<bool> is not thread-writable
  auto work = [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i) {
      ok[i] = ParseProcStat(procfs, names[i].c_str(), &got_pid[i],
                            &got_cpu[i]);
    }
  };
  unsigned hw = std::thread::hardware_concurrency();
  unsigned nt = (n > 512 && hw > 1)
                    ? std::min(4u, hw)
                    : 1u;  // small trees: threads cost more than they save
  if (nt <= 1) {
    work(0, n);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(nt);
    const size_t chunk = (n + nt - 1) / nt;
    try {
      for (unsigned t = 0; t < nt; ++t) {
        const size_t lo = t * chunk;
        if (lo >= n) break;
        threads.emplace_back(work, lo, std::min(lo + chunk, n));
      }
    } catch (...) {
      // thread spawn failed mid-loop (EAGAIN under task limits): join
      // what started — a joinable thread's destructor would terminate()
      for (auto& th : threads) th.join();
      throw;  // outer catch returns -1 → pure-Python fallback
    }
    for (auto& th : threads) th.join();
  }
  int count = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!ok[i]) continue;
    pids[count] = got_pid[i];
    cpu_seconds[count] = got_cpu[i];
    ++count;
  }
  return count;
} catch (...) {
  // bad_alloc / system_error must not unwind into ctypes frames; -1 sends
  // callers to the pure-Python reader (graceful-degradation contract)
  return -1;
}

// Aggregate 'cpu' line of <procfs>/stat → (active, total) jiffies, where
// active = total − idle − iowait (procfs_reader.go:107-141). Returns 0 on
// success.
int kepler_read_stat_totals(const char* procfs, double* active,
                            double* total) {
  char path[512];
  char buf[8192];
  snprintf(path, sizeof(path), "%s/stat", procfs);
  if (ReadSmallFile(path, buf, sizeof(buf)) <= 0) return -1;
  if (strncmp(buf, "cpu", 3) != 0) return -1;
  char* nl = strchr(buf, '\n');
  if (nl != nullptr) *nl = '\0';
  char* save = nullptr;
  char* t = strtok_r(buf, " ", &save);  // consumes the "cpu" label
  if (t == nullptr) return -1;
  double sum = 0.0, idle = 0.0, iowait = 0.0;
  int i = 0;
  for (t = strtok_r(nullptr, " ", &save); t != nullptr;
       t = strtok_r(nullptr, " ", &save), ++i) {
    double v = strtod(t, nullptr);
    sum += v;
    if (i == 3) idle = v;
    if (i == 4) iowait = v;
  }
  *active = sum - idle - iowait;
  *total = sum;
  return 0;
}

// Batch-read `n` counter files (NUL-separated concatenated `paths`,
// e.g. RAPL energy_uj) into out[i]; failed reads leave UINT64_MAX (the
// batched analog of the reference's per-zone skip-on-error, node.go:39-44).
// Returns the number of successful reads.
int kepler_read_counter_files(const char* paths, int32_t n, uint64_t* out) {
  const char* p = paths;
  int ok = 0;
  char buf[64];
  for (int i = 0; i < n; ++i) {
    out[i] = UINT64_MAX;
    if (ReadSmallFile(p, buf, sizeof(buf)) > 0) {
      char* end = nullptr;
      unsigned long long v = strtoull(buf, &end, 10);
      if (end != buf) {
        out[i] = v;
        ++ok;
      }
    }
    p += strlen(p) + 1;
  }
  return ok;
}

}  // extern "C"
