// Native batched procfs/sysfs readers + text-exposition renderer — the
// host-side hot path.
//
// Reference parity: the per-PID /proc/<pid>/stat scan of
// internal/resource/procfs_reader.go (CPUTime = (utime+stime)/USER_HZ,
// :73-82), the /proc/stat usage-ratio totals (:107-141), the per-zone
// energy_uj reads of internal/device/rapl_sysfs_power_meter.go, and the
// classic-text sample rendering the reference gets from Go's
// prometheus/common/expfmt — but done as ONE C call per tick/scrape
// instead of thousands of Python open/read/parse (or format/append)
// round-trips. SURVEY §7 hard part (d): the procfs scan, not the TPU
// math, is the per-node bottleneck; this file is its fast path.
//
// Pure C ABI (called via ctypes — no pybind11 in this toolchain). Callers
// own every OUTPUT buffer; the scan allocates transient working vectors
// (dirent names + per-entry results) and, for large trees, a few
// short-lived threads. All C++ exceptions are caught at the ABI boundary
// and surfaced as -1 (callers fall back to the pure-Python paths) — no
// exception may unwind into ctypes frames.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <charconv>
#include <cmath>

#include <dirent.h>
#include <fcntl.h>
#include <sys/resource.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// Reference hardcodes USER_HZ=100 (procfs_reader.go:73-82); Linux has had
// CONFIG_HZ-independent USER_HZ=100 since 2.6, so parity and correctness
// agree.
constexpr double kUserHz = 100.0;

// comm slot width in the scan output: TASK_COMM_LEN is 16 (15 chars +
// NUL) on every kernel, but test fixtures may write longer names, so
// slots are 32 bytes (31 chars + NUL) to keep native/Python readers
// byte-identical on synthetic trees too.
constexpr int kCommSlot = 32;

// Read a small file fully into buf (NUL-terminated). Returns bytes read or
// -1. procfs files must be read in one pass; short buffers truncate safely.
int ReadSmallFile(const char* path, char* buf, int cap) {
  int fd = open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return -1;
  int n = 0;
  while (n < cap - 1) {
    ssize_t r = read(fd, buf + n, cap - 1 - n);
    if (r < 0) {
      close(fd);
      return -1;
    }
    if (r == 0) break;
    n += static_cast<int>(r);
  }
  close(fd);
  buf[n] = '\0';
  return n;
}

bool AllDigits(const char* s) {
  if (*s == '\0') return false;
  for (; *s; ++s) {
    if (*s < '0' || *s > '9') return false;
  }
  return true;
}

// Worker-thread count for a batch of n independent file operations. The
// work is syscall-latency bound; the kernel serves independent /proc
// files concurrently. Small batches stay single-threaded (threads cost
// more than they save). KEPLER_SCAN_THREADS overrides (0 = auto).
unsigned ThreadsFor(size_t n) {
  static int env_threads = [] {
    const char* s = getenv("KEPLER_SCAN_THREADS");
    return s != nullptr ? atoi(s) : 0;
  }();
  if (env_threads > 0) return std::min<unsigned>(env_threads, 64);
  unsigned hw = std::thread::hardware_concurrency();
  if (n <= 512 || hw <= 1) return 1;
  // one thread per ~1k entries, capped by cores and a sane ceiling
  unsigned want = static_cast<unsigned>((n + 1023) / 1024);
  return std::min({want, hw, 16u});
}

// Run work(lo, hi) over [0, n) on ThreadsFor(n) threads. Exceptions from
// spawning propagate after joining what started (a joinable thread's
// destructor would terminate()).
template <typename Fn>
void ParallelFor(size_t n, Fn work) {
  unsigned nt = ThreadsFor(n);
  if (nt <= 1) {
    work(static_cast<size_t>(0), n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  const size_t chunk = (n + nt - 1) / nt;
  try {
    for (unsigned t = 0; t < nt; ++t) {
      const size_t lo = t * chunk;
      if (lo >= n) break;
      threads.emplace_back(work, lo, std::min(lo + chunk, n));
    }
  } catch (...) {
    for (auto& th : threads) th.join();
    throw;
  }
  for (auto& th : threads) th.join();
}

// ---- float formatting (Python-repr + prometheus floatToGoString) --------
//
// The classic-text renderer must be byte-identical to
// prometheus_client.utils.floatToGoString, which is Python repr() plus a
// Go-style mantissa-exponent munge for positive fixed-notation values
// with >6 integer digits. Python repr is shortest-roundtrip digits
// (unique — Ryu/Grisu class algorithms agree) formatted fixed when the
// decimal exponent is in [-4, 16) and scientific (e±XX, ≥2 exponent
// digits) otherwise. std::to_chars(scientific) yields exactly those
// shortest digits; this reformats them per Python's rules.

// Python repr(float). Returns length. out must hold ≥40 bytes.
int PyReprDouble(double v, char* out) {
  if (v == 0.0) {
    if (std::signbit(v)) {
      memcpy(out, "-0.0", 5);
      return 4;
    }
    memcpy(out, "0.0", 4);
    return 3;
  }
  char sci[40];
  auto res = std::to_chars(sci, sci + sizeof(sci), v,
                           std::chars_format::scientific);
  *res.ptr = '\0';
  const char* p = sci;
  bool neg = (*p == '-');
  if (neg) ++p;
  char digits[24];
  int nd = 0;
  digits[nd++] = *p++;
  if (*p == '.') {
    ++p;
    while (*p != '\0' && *p != 'e') digits[nd++] = *p++;
  }
  int exp10 = atoi(p + 1);  // *p == 'e'
  char* q = out;
  if (neg) *q++ = '-';
  if (exp10 >= -4 && exp10 < 16) {
    if (exp10 >= nd - 1) {  // integral: digits, zeros, ".0"
      memcpy(q, digits, nd);
      q += nd;
      for (int i = 0; i < exp10 - (nd - 1); ++i) *q++ = '0';
      *q++ = '.';
      *q++ = '0';
    } else if (exp10 >= 0) {  // dot inside the digit run
      memcpy(q, digits, exp10 + 1);
      q += exp10 + 1;
      *q++ = '.';
      memcpy(q, digits + exp10 + 1, nd - exp10 - 1);
      q += nd - exp10 - 1;
    } else {  // 0.00ddd
      *q++ = '0';
      *q++ = '.';
      for (int i = 0; i < -exp10 - 1; ++i) *q++ = '0';
      memcpy(q, digits, nd);
      q += nd;
    }
  } else {  // scientific, exponent ≥2 digits with sign (Python style)
    *q++ = digits[0];
    if (nd > 1) {
      *q++ = '.';
      memcpy(q, digits + 1, nd - 1);
      q += nd - 1;
    }
    *q++ = 'e';
    *q++ = exp10 < 0 ? '-' : '+';
    int e = exp10 < 0 ? -exp10 : exp10;
    if (e < 10) {
      *q++ = '0';
      *q++ = static_cast<char>('0' + e);
    } else {
      char tmp[8];
      int t = 0;
      while (e > 0) {
        tmp[t++] = static_cast<char>('0' + e % 10);
        e /= 10;
      }
      while (t > 0) *q++ = tmp[--t];
    }
  }
  *q = '\0';
  return static_cast<int>(q - out);
}

// floatToGoString. out must hold ≥48 bytes. Returns length.
int FmtGoDouble(double v, char* out) {
  if (std::isnan(v)) {
    memcpy(out, "NaN", 4);
    return 3;
  }
  if (std::isinf(v)) {
    memcpy(out, v > 0 ? "+Inf" : "-Inf", 5);
    return 4;
  }
  char repr[40];
  int rlen = PyReprDouble(v, repr);
  const char* dot = static_cast<const char*>(memchr(repr, '.', rlen));
  int dotpos = dot != nullptr ? static_cast<int>(dot - repr) : -1;
  if (v > 0 && dotpos > 6) {
    // mantissa = repr[0] '.' (repr digits sans dot), rstrip any of "0."
    char m[44];
    int k = 0;
    m[k++] = repr[0];
    m[k++] = '.';
    for (int i = 1; i < rlen; ++i) {
      if (i != dotpos) m[k++] = repr[i];
    }
    while (k > 0 && (m[k - 1] == '0' || m[k - 1] == '.')) --k;
    m[k] = '\0';
    // Python: f"{mantissa}e+0{dot-1}" — literal '0' prefix, no width pad
    return snprintf(out, 48, "%se+0%d", m, dotpos - 1);
  }
  memcpy(out, repr, rlen + 1);
  return rlen;
}

}  // namespace

extern "C" {

// ABI version for the ctypes loader to sanity-check.
int kepler_native_abi_version() { return 3; }

// Parse a <pid>/stat buffer (mutated in place); true on success.
// Thread-safe: all state is caller-provided. comm receives the
// (NUL-terminated, ≤kCommSlot-1 byte) command name from the stat line —
// the same field /proc/<pid>/comm serves, so readers need no separate
// comm read per tick.
static bool ParseStatBuf(char* buf, const char* name, int32_t* pid,
                         double* cpu_seconds, char* comm) {
  // comm may contain spaces/parens; fields resume after the LAST ')'
  // (same parse as the Python reader and the reference's procfs lib).
  char* lparen = strchr(buf, '(');
  char* rparen = strrchr(buf, ')');
  if (lparen == nullptr || rparen == nullptr || rparen < lparen ||
      rparen[1] == '\0') {
    return false;
  }
  if (comm != nullptr) {
    int clen = std::min<int>(static_cast<int>(rparen - lparen) - 1,
                             kCommSlot - 1);
    if (clen < 0) clen = 0;
    memcpy(comm, lparen + 1, clen);
    memset(comm + clen, 0, kCommSlot - clen);
  }
  char* rest = rparen + 2;
  // After the ')' the next fields are state(0) ... utime(11) stime(12),
  // 0-indexed — i.e. stat fields 14 and 15 in proc(5) numbering.
  unsigned long long utime = 0, stime = 0;
  int tok = 0;
  bool ok = false;
  char* save = nullptr;
  for (char* t = strtok_r(rest, " ", &save); t != nullptr;
       t = strtok_r(nullptr, " ", &save), ++tok) {
    // endptr checks: a corrupt stat line (non-numeric utime/stime) must
    // skip the process, matching the pure-Python reader's raise-and-skip
    // semantics — not admit it with cpu_seconds=0. strtok_r tokens are
    // NUL-terminated, so a fully-numeric token ends exactly at '\0'.
    char* end = nullptr;
    if (tok == 11) {
      utime = strtoull(t, &end, 10);
      if (end == t || *end != '\0') return false;
    } else if (tok == 12) {
      stime = strtoull(t, &end, 10);
      if (end == t || *end != '\0') return false;
      ok = true;
      break;
    }
  }
  if (!ok) return false;
  *pid = static_cast<int32_t>(strtol(name, nullptr, 10));
  *cpu_seconds = static_cast<double>(utime + stime) / kUserHz;
  return true;
}

// Read + parse one <pid>/stat file; true on success.
static bool ParseProcStat(const char* procfs, const char* name,
                          int32_t* pid, double* cpu_seconds, char* comm) {
  char path[512];
  char buf[4096];
  snprintf(path, sizeof(path), "%s/%s/stat", procfs, name);
  if (ReadSmallFile(path, buf, sizeof(buf)) <= 0) return false;
  return ParseStatBuf(buf, name, pid, cpu_seconds, comm);
}

// Scan every numeric entry of `procfs`, parse <pid>/stat, and fill
// pids[i] / cpu_seconds[i] / comms[i*32] with the PID, cpu seconds
// ((utime+stime)/USER_HZ), and command name (NUL-terminated 32-byte
// slots; pass NULL to skip). Returns the number of entries filled, -1 if
// procfs can't be opened, or -2 if more than `cap` processes exist
// (caller retries with a bigger buffer). PIDs that vanish mid-scan are
// skipped, matching the reference's skip-on-ESRCH behavior
// (informer.go:186-190).
//
// Large trees fan the per-PID open/read/parse out to worker threads (see
// ThreadsFor). Output order stays the directory order regardless of
// thread count.
int kepler_scan_procs(const char* procfs, int32_t* pids, double* cpu_seconds,
                      char* comms, int32_t cap) try {
  DIR* dir = opendir(procfs);
  if (dir == nullptr) return -1;
  std::vector<std::string> names;
  struct dirent* entry;
  while ((entry = readdir(dir)) != nullptr) {
    if (AllDigits(entry->d_name)) names.emplace_back(entry->d_name);
  }
  closedir(dir);
  const size_t n = names.size();
  if (cap < 0) return -1;  // -2 would make callers grow-and-retry forever
  if (n > static_cast<size_t>(cap)) return -2;

  std::vector<int32_t> got_pid(n);
  std::vector<double> got_cpu(n);
  std::vector<char> got_comm(comms != nullptr ? n * kCommSlot : 0);
  std::vector<char> ok(n, 0);  // vector<bool> is not thread-writable
  ParallelFor(n, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i) {
      ok[i] = ParseProcStat(
          procfs, names[i].c_str(), &got_pid[i], &got_cpu[i],
          comms != nullptr ? &got_comm[i * kCommSlot] : nullptr);
    }
  });
  int count = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!ok[i]) continue;
    pids[count] = got_pid[i];
    cpu_seconds[count] = got_cpu[i];
    if (comms != nullptr) {
      memcpy(comms + static_cast<size_t>(count) * kCommSlot,
             &got_comm[i * kCommSlot], kCommSlot);
    }
    ++count;
  }
  return count;
} catch (...) {
  // bad_alloc / system_error must not unwind into ctypes frames; -1 sends
  // callers to the pure-Python reader (graceful-degradation contract)
  return -1;
}

// ---- stateful scan handle (fd cache + pread) ----------------------------
//
// The one-shot scan pays open+read+close (3 syscalls + 2 path walks) per
// PID per tick. A monitoring daemon reads the SAME files every tick, so
// the handle keeps each PID's stat fd open across ticks and does ONE
// pread per live PID (measured 5× faster at 10k procs on a 1-core
// host). procfs semantics make this sound: a stat fd of a dead task
// reads 0/ESRCH (it does not pin the task), which both detects
// termination and guards PID reuse — on any failed pread the fd is
// reopened once via openat before the PID is declared gone. The fd
// budget respects RLIMIT_NOFILE with headroom; PIDs beyond it fall back
// to open/pread/close per tick.

struct ScanHandle {
  std::mutex mu;  // calls are cheap; callers may share a handle
  std::string procfs;
  int dfd = -1;  // procfs dirfd for openat ("<pid>/stat" relative paths)
  struct Entry {
    int fd;
    uint64_t epoch;
  };
  std::unordered_map<int32_t, Entry> fds;
  size_t max_fds = 0;
  uint64_t epoch = 0;
};

// Cached stat fds across ALL handles — many-handle processes (test
// suites over many fake trees) share one RLIMIT_NOFILE.
static std::atomic<size_t> g_cached_fds{0};

// Open a scan handle for `procfs`. max_fds caps the fd cache (0 = derive
// from RLIMIT_NOFILE with 1024 headroom, capped at 65536). NULL on error.
void* kepler_scan_open(const char* procfs, int32_t max_fds) try {
  int dfd = open(procfs, O_RDONLY | O_DIRECTORY | O_CLOEXEC);
  if (dfd < 0) return nullptr;
  auto* h = new ScanHandle();
  h->procfs = procfs;
  h->dfd = dfd;
  if (max_fds > 0) {
    h->max_fds = static_cast<size_t>(max_fds);
  } else {
    // derive from RLIMIT_NOFILE in every case — a flat default could
    // exhaust the whole limit on low-rlimit hosts (the rest of the agent
    // needs sockets/sysfs fds too). Generous limits keep 1024 headroom;
    // tight ones cede half. PIDs past the budget still scan, just via
    // the uncached open/pread/close path.
    rlimit rl{};
    size_t budget = 256;
    if (getrlimit(RLIMIT_NOFILE, &rl) == 0 && rl.rlim_cur > 0) {
      size_t cur = static_cast<size_t>(rl.rlim_cur);
      budget = cur > 2048 ? cur - 1024 : cur / 2;
    }
    h->max_fds = std::min<size_t>(budget, 65536);
  }
  return h;
} catch (...) {
  return nullptr;
}

void kepler_scan_free(void* handle) {
  if (handle == nullptr) return;
  auto* h = static_cast<ScanHandle*>(handle);
  for (auto& kv : h->fds) close(kv.second.fd);
  g_cached_fds.fetch_sub(h->fds.size());
  close(h->dfd);
  delete h;
}

// One tick: enumerate `procfs`, pread every live PID's stat (cached fd
// when available), fill pids/cpu_seconds/comms exactly like
// kepler_scan_procs. Returns count, -1 on error, -2 when cap is too
// small.
int kepler_scan_tick(void* handle, int32_t* pids, double* cpu_seconds,
                     char* comms, int32_t cap) try {
  if (handle == nullptr || cap < 0) return -1;
  auto* h = static_cast<ScanHandle*>(handle);
  std::lock_guard<std::mutex> lock(h->mu);
  DIR* dir = opendir(h->procfs.c_str());
  if (dir == nullptr) return -1;
  std::vector<std::string> names;
  struct dirent* entry;
  while ((entry = readdir(dir)) != nullptr) {
    if (AllDigits(entry->d_name)) names.emplace_back(entry->d_name);
  }
  closedir(dir);
  const size_t n = names.size();
  if (n > static_cast<size_t>(cap)) return -2;
  ++h->epoch;

  // split: rows with a cached fd (pread, parallel-safe — no map writes)
  // vs first-sight rows (openat below, serial map inserts)
  std::vector<int32_t> name_pid(n);
  std::vector<int> row_fd(n, -1);
  for (size_t i = 0; i < n; ++i) {
    name_pid[i] = static_cast<int32_t>(strtol(names[i].c_str(), nullptr, 10));
    auto it = h->fds.find(name_pid[i]);
    if (it != h->fds.end()) {
      row_fd[i] = it->second.fd;
      it->second.epoch = h->epoch;
    }
  }
  std::vector<int32_t> got_pid(n);
  std::vector<double> got_cpu(n);
  std::vector<char> got_comm(comms != nullptr ? n * kCommSlot : 0);
  std::vector<char> ok(n, 0);
  std::vector<char> need_reopen(n, 0);
  ParallelFor(n, [&](size_t lo, size_t hi) {
    char buf[4096];
    for (size_t i = lo; i < hi; ++i) {
      if (row_fd[i] < 0) continue;
      ssize_t r = pread(row_fd[i], buf, sizeof(buf) - 1, 0);
      if (r <= 0) {
        // dead task behind the fd (or PID reuse): retry via openat below
        need_reopen[i] = 1;
        continue;
      }
      buf[r] = '\0';
      ok[i] = ParseStatBuf(buf, names[i].c_str(), &got_pid[i], &got_cpu[i],
                           comms != nullptr ? &got_comm[i * kCommSlot]
                                            : nullptr);
      if (!ok[i]) need_reopen[i] = 1;  // corrupt read: retry once fresh
    }
  });
  // first sight + reopen rows (serial: mutates the fd map)
  char buf[4096];
  char rel[320];
  for (size_t i = 0; i < n; ++i) {
    if (row_fd[i] >= 0 && !need_reopen[i]) continue;
    if (need_reopen[i]) {
      auto it = h->fds.find(name_pid[i]);
      if (it != h->fds.end()) {
        close(it->second.fd);
        g_cached_fds.fetch_sub(1);
        h->fds.erase(it);
      }
      ok[i] = 0;
    }
    snprintf(rel, sizeof(rel), "%s/stat", names[i].c_str());
    int fd = openat(h->dfd, rel, O_RDONLY | O_CLOEXEC);
    if (fd < 0) continue;  // vanished mid-scan
    ssize_t r = pread(fd, buf, sizeof(buf) - 1, 0);
    if (r <= 0) {
      close(fd);
      continue;
    }
    buf[r] = '\0';
    ok[i] = ParseStatBuf(buf, names[i].c_str(), &got_pid[i], &got_cpu[i],
                         comms != nullptr ? &got_comm[i * kCommSlot]
                                          : nullptr);
    if (ok[i] && g_cached_fds.load() < h->max_fds) {
      h->fds.emplace(name_pid[i], ScanHandle::Entry{fd, h->epoch});
      g_cached_fds.fetch_add(1);
    } else {
      close(fd);
    }
  }
  // sweep fds of vanished PIDs
  for (auto it = h->fds.begin(); it != h->fds.end();) {
    if (it->second.epoch != h->epoch) {
      close(it->second.fd);
      g_cached_fds.fetch_sub(1);
      it = h->fds.erase(it);
    } else {
      ++it;
    }
  }
  int count = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!ok[i]) continue;
    pids[count] = got_pid[i];
    cpu_seconds[count] = got_cpu[i];
    if (comms != nullptr) {
      memcpy(comms + static_cast<size_t>(count) * kCommSlot,
             &got_comm[i * kCommSlot], kCommSlot);
    }
    ++count;
  }
  return count;
} catch (...) {
  return -1;
}

// Aggregate 'cpu' line of <procfs>/stat → (active, total) jiffies, where
// active = total − idle − iowait (procfs_reader.go:107-141). Returns 0 on
// success.
int kepler_read_stat_totals(const char* procfs, double* active,
                            double* total) {
  char path[512];
  char buf[8192];
  snprintf(path, sizeof(path), "%s/stat", procfs);
  if (ReadSmallFile(path, buf, sizeof(buf)) <= 0) return -1;
  if (strncmp(buf, "cpu", 3) != 0) return -1;
  char* nl = strchr(buf, '\n');
  if (nl != nullptr) *nl = '\0';
  char* save = nullptr;
  char* t = strtok_r(buf, " ", &save);  // consumes the "cpu" label
  if (t == nullptr) return -1;
  double sum = 0.0, idle = 0.0, iowait = 0.0;
  int i = 0;
  for (t = strtok_r(nullptr, " ", &save); t != nullptr;
       t = strtok_r(nullptr, " ", &save), ++i) {
    double v = strtod(t, nullptr);
    sum += v;
    if (i == 3) idle = v;
    if (i == 4) iowait = v;
  }
  *active = sum - idle - iowait;
  *total = sum;
  return 0;
}

// Batch-read `n` counter files (NUL-separated concatenated `paths`,
// e.g. RAPL energy_uj) into out[i]; failed reads leave UINT64_MAX (the
// batched analog of the reference's per-zone skip-on-error, node.go:39-44).
// Returns the number of successful reads.
int kepler_read_counter_files(const char* paths, int32_t n, uint64_t* out) {
  const char* p = paths;
  int ok = 0;
  char buf[64];
  for (int i = 0; i < n; ++i) {
    out[i] = UINT64_MAX;
    if (ReadSmallFile(p, buf, sizeof(buf)) > 0) {
      char* end = nullptr;
      unsigned long long v = strtoull(buf, &end, 10);
      if (end != buf) {
        out[i] = v;
        ++ok;
      }
    }
    p += strlen(p) + 1;
  }
  return ok;
}

// Batch-read `n` small files (NUL-separated concatenated `paths`) into
// fixed `per_cap`-byte slots of `out` (contents NUL-terminated,
// truncated at per_cap-1). sizes[i] = bytes read, or -1 on failure.
// Threaded like the proc scan — this keeps first-sight classification
// bursts (mass pod reschedule) on the native path: Python hands over the
// cgroup/cmdline/environ paths of every NEW pid and gets all contents in
// one call. Returns the number of successful reads, or -1 on internal
// failure.
int kepler_read_files(const char* paths, int32_t n, char* out,
                      int32_t per_cap, int32_t* sizes) try {
  if (n < 0 || per_cap < 2) return -1;
  std::vector<const char*> ptrs(n);
  const char* p = paths;
  for (int i = 0; i < n; ++i) {
    ptrs[i] = p;
    p += strlen(p) + 1;
  }
  ParallelFor(static_cast<size_t>(n), [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i) {
      sizes[i] = ReadSmallFile(ptrs[i], out + i * per_cap, per_cap);
    }
  });
  int ok = 0;
  for (int i = 0; i < n; ++i) {
    if (sizes[i] >= 0) ++ok;
  }
  return ok;
} catch (...) {
  return -1;
}

// Batch-readlink `n` paths (NUL-separated) into per_cap-byte slots
// (NUL-terminated). sizes[i] = link length (truncated at per_cap-1) or -1.
// Returns successful count, or -1 on internal failure. Used for
// /proc/<pid>/exe on first sight.
int kepler_read_links(const char* paths, int32_t n, char* out,
                      int32_t per_cap, int32_t* sizes) try {
  if (n < 0 || per_cap < 2) return -1;
  std::vector<const char*> ptrs(n);
  const char* p = paths;
  for (int i = 0; i < n; ++i) {
    ptrs[i] = p;
    p += strlen(p) + 1;
  }
  ParallelFor(static_cast<size_t>(n), [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i) {
      ssize_t r = readlink(ptrs[i], out + i * per_cap, per_cap - 1);
      if (r < 0) {
        out[i * per_cap] = '\0';
        sizes[i] = -1;
      } else {
        out[i * per_cap + r] = '\0';
        sizes[i] = static_cast<int32_t>(r);
      }
    }
  });
  int ok = 0;
  for (int i = 0; i < n; ++i) {
    if (sizes[i] >= 0) ++ok;
  }
  return ok;
} catch (...) {
  return -1;
}

// prometheus floatToGoString (Python-repr-compatible) — exposed for the
// byte-parity tests. out must hold ≥48 bytes; returns length.
int kepler_fmt_double(double v, char* out) { return FmtGoDouble(v, out); }

// Render n*nz classic-text sample lines:
//   for i < n, z < nz:
//     out += name + prefix[i] + ztail[z] + fmt(values[i*nz + z] / div) + "\n"
// where prefix/ztail are concatenated blobs addressed by byte offsets
// (prefix_off[i]..prefix_off[i+1], ztail_off[z]..ztail_off[z+1]) and fmt
// is floatToGoString. flags bit0: round the value to 6 decimals first
// (snprintf %.6f → strtod), matching Python's float(f"{v:.6f}") pipeline
// for kepler_process_cpu_seconds_total. Returns bytes written, or -1 if
// `cap` would overflow (caller grows and retries).
//
// This is the scrape hot loop: one call renders a whole metric family
// (10k workloads × Z zones) with zero Python string work. Label blocks
// (the prefixes) are cached Python-side across scrapes; only values are
// formatted here, every scrape.
int64_t kepler_render_samples(const char* name, int32_t name_len,
                              const char* prefix_blob,
                              const int64_t* prefix_off, int32_t n,
                              const char* ztail_blob,
                              const int32_t* ztail_off, int32_t nz,
                              const double* values, double div,
                              int32_t flags, char* out, int64_t cap) try {
  if (n < 0 || nz <= 0 || div == 0.0) return -1;
  char* q = out;
  char* end = out + cap;
  char fbuf[48];
  char rbuf[64];
  const bool round6 = (flags & 1) != 0;
  for (int32_t i = 0; i < n; ++i) {
    const char* prefix = prefix_blob + prefix_off[i];
    const int64_t plen = prefix_off[i + 1] - prefix_off[i];
    for (int32_t z = 0; z < nz; ++z) {
      const char* ztail = ztail_blob + ztail_off[z];
      const int32_t zlen = ztail_off[z + 1] - ztail_off[z];
      double v = values[static_cast<int64_t>(i) * nz + z] / div;
      if (round6) {
        snprintf(rbuf, sizeof(rbuf), "%.6f", v);
        v = strtod(rbuf, nullptr);
      }
      int flen = FmtGoDouble(v, fbuf);
      if (q + name_len + plen + zlen + flen + 1 > end) return -1;
      memcpy(q, name, name_len);
      q += name_len;
      memcpy(q, prefix, plen);
      q += plen;
      memcpy(q, ztail, zlen);
      q += zlen;
      memcpy(q, fbuf, flen);
      q += flen;
      *q++ = '\n';
    }
  }
  return q - out;
} catch (...) {
  return -1;
}

}  // extern "C"
