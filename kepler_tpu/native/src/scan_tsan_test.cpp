// ThreadSanitizer harness for the native scanner (the -race analog the
// reference gets for free from `go test -race`, Makefile:131).
//
// Builds scan.cpp with -fsanitize=thread into a standalone binary and
// hammers every exported call from concurrent threads over a fake /proc
// tree. Any data race aborts the run with a TSAN report; a clean exit is
// the pass. Run via `make native-tsan` (also wired into tests/test_native
// when the toolchain supports TSAN).
//
// scan.cpp's thread-safety contract is "no shared mutable state — every
// call works on caller-provided buffers"; this harness exists to keep
// that contract honest as the file grows.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <sys/stat.h>
#include <thread>
#include <vector>

extern "C" {
int kepler_native_abi_version();
int kepler_scan_procs(const char* procfs, int32_t* pids, double* cpu_seconds,
                      char* comms, int32_t cap);
int kepler_read_stat_totals(const char* procfs, double* active,
                            double* total);
int kepler_read_counter_files(const char* paths, int32_t n, uint64_t* out);
int kepler_read_files(const char* paths, int32_t n, char* out,
                      int32_t per_cap, int32_t* sizes);
int kepler_read_links(const char* paths, int32_t n, char* out,
                      int32_t per_cap, int32_t* sizes);
int kepler_fmt_double(double v, char* out);
int64_t kepler_render_samples(const char* name, int32_t name_len,
                              const char* prefix_blob,
                              const int64_t* prefix_off, int32_t n,
                              const char* ztail_blob,
                              const int32_t* ztail_off, int32_t nz,
                              const double* values, double div,
                              int32_t flags, char* out, int64_t cap);
}

namespace {

void write_file(const std::string& path, const std::string& content) {
  FILE* f = fopen(path.c_str(), "w");
  if (!f) {
    perror(path.c_str());
    exit(2);
  }
  fputs(content.c_str(), f);
  fclose(f);
}

std::string make_fake_proc(const std::string& root, int n_procs) {
  std::string proc = root + "/proc";
  mkdir(proc.c_str(), 0755);
  write_file(proc + "/stat", "cpu  100 20 300 4000 500 60 70 0 0 0\n");
  for (int pid = 100; pid < 100 + n_procs; ++pid) {
    std::string d = proc + "/" + std::to_string(pid);
    mkdir(d.c_str(), 0755);
    char line[256];
    snprintf(line, sizeof(line),
             "%d (proc %d) S 1 1 1 0 -1 4194560 100 0 0 0 "
             "%d %d 0 0 20 0 1 0 100 0 0 0 0 0 0 0 0 0 0 0 0 0 0 0 0 0 "
             "0 0 0 0 0 0 0 0 0 0 0 0 0",
             pid, pid, pid * 7, pid * 3);
    write_file(d + "/stat", line);
  }
  return proc;
}

}  // namespace

int main() {
  if (kepler_native_abi_version() <= 0) return 2;
  char tmpl[] = "/tmp/kepler-tsan-XXXXXX";
  if (!mkdtemp(tmpl)) return 2;
  const std::string root(tmpl);
  const std::string proc = make_fake_proc(root, 64);
  const std::string counter_a = root + "/energy_a";
  const std::string counter_b = root + "/energy_b";
  write_file(counter_a, "1000\n");
  write_file(counter_b, "2000\n");
  // NUL-joined path blob, the read_counter_files wire format
  std::string blob = counter_a;
  blob.push_back('\0');
  blob += counter_b;
  blob.push_back('\0');

  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 8; ++t) {
    threads.emplace_back([&, t] {
      int32_t pids[256];
      double cpu[256];
      char comms[256 * 32];
      double active = 0, total = 0;
      uint64_t counters[2];
      char files_out[2 * 128];
      int32_t files_sizes[2];
      char fmt_out[48];
      char render_out[512];
      const char* prefix_blob = "{pid=\"1\"}{pid=\"2\"}";
      const int64_t prefix_off[3] = {0, 9, 18};
      const char* ztail_blob = ",zone=\"pkg\"} ";
      const int32_t ztail_off[2] = {0, 13};
      const double render_vals[2] = {1.5, 2.5e8};
      for (int i = 0; i < 200; ++i) {
        // pid dirs are never mutated: the scan count is a hard invariant
        int n = kepler_scan_procs(proc.c_str(), pids, cpu, comms, 256);
        if (n != 64) failures.fetch_add(1);
        // stat/counter files race a truncating writer below — transient
        // read errors are the mid-write window (callers skip it); what
        // TSAN checks is that the concurrent calls themselves are clean
        (void)kepler_read_stat_totals(proc.c_str(), &active, &total);
        int ok = kepler_read_counter_files(blob.c_str(), 2, counters);
        if (ok < 0 || ok > 2) failures.fetch_add(1);
        ok = kepler_read_files(blob.c_str(), 2, files_out, 128, files_sizes);
        if (ok < 0 || ok > 2) failures.fetch_add(1);
        if (kepler_fmt_double(1234.5 + i, fmt_out) <= 0) failures.fetch_add(1);
        int64_t r = kepler_render_samples(
            "kepler_x", 8, prefix_blob, prefix_off, 2, ztail_blob, ztail_off,
            1, render_vals, 1.0, 0, render_out, sizeof(render_out));
        if (r <= 0) failures.fetch_add(1);
        if (t == 0 && i % 10 == 0) {
          // one writer mutates the tree while others scan (live /proc)
          write_file(counter_a, std::to_string(1000 + i) + "\n");
          write_file(proc + "/stat",
                     "cpu  " + std::to_string(100 + i) +
                         " 20 300 4000 500 60 70 0 0 0\n");
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  if (failures.load() != 0) {
    fprintf(stderr, "FAIL: %d call failures\n", failures.load());
    return 1;
  }
  printf("tsan harness clean: 8 threads x 200 iterations\n");
  return 0;
}
