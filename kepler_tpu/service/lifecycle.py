"""Service lifecycle framework.

Reference parity: ``internal/service/`` — duck-typed lifecycle where a
"service" optionally implements Init / Run / Shutdown:

- ``init_services``: sequential Init; on the first failure, already-initialized
  services are shut down in reverse order (rollback;
  ``internal/service/initializer.go:15-58``).
- ``run_services``: concurrent Run, one thread per Runner; the first Runner to
  return (or raise) cancels the shared context, interrupting all others, then
  every service's Shutdown runs (``internal/service/run.go:16-65``, modeled on
  oklog/run).
- ``SignalHandler``: a Runner that exits on SIGINT/SIGTERM
  (``internal/service/signal_handler.go:13-39``).

Python idiom: instead of Go interfaces we use runtime ``hasattr`` duck typing
plus a ``CancelContext`` (threading.Event-backed) standing in for Go's
context cancellation.
"""

from __future__ import annotations

# keplint: monotonic-only — restart backoff schedules must survive NTP steps

import logging
import random
import signal
import threading
from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable

log = logging.getLogger("kepler.service")


class CancelContext:
    """Cooperative cancellation token shared by all running services."""

    def __init__(self) -> None:
        self._event = threading.Event()

    def cancel(self) -> None:
        self._event.set()

    def cancelled(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until cancelled (or timeout); returns True if cancelled."""
        return self._event.wait(timeout)


@runtime_checkable
class Service(Protocol):
    """Every service has a name (reference service.go:9-12)."""

    def name(self) -> str: ...


class ServiceError(Exception):
    pass


def backoff_with_jitter(initial: float, cap: float, attempt: int,
                        rng: random.Random) -> float:
    """Equal-jitter exponential backoff: ``min(cap, initial·2^(n-1))``,
    half deterministic + half random. The ONE schedule shared by the
    restart policy and the fleet agent's send retries — the jitter keeps
    a fleet of restarting/retrying nodes from synchronizing against a
    recovering dependency."""
    base = min(cap, initial * (2 ** max(0, attempt - 1)))
    return base / 2 + rng.uniform(0, base / 2)


@dataclass(frozen=True)
class RestartPolicy:
    """Supervised restart-with-backoff for ``run_services`` Runners.

    A Runner that RAISES is restarted after an exponential backoff with
    jitter, up to ``max_restarts`` times per service; only when a service
    exhausts its budget does the group fail. A Runner that RETURNS cleanly
    still cancels the whole group (the oklog/run semantics are unchanged —
    a deliberate exit, e.g. the SignalHandler, must keep meaning
    "shut everything down").

    The restart counter is per service and never resets: a service that
    crashes ``max_restarts + 1`` times over any span ends the group. That
    keeps the policy a bounded self-heal for transient faults (meter
    hiccup, aggregator hiccup), not a crash-loop hider.
    """

    max_restarts: int = 3
    backoff_initial: float = 0.5
    backoff_max: float = 30.0
    seed: int | None = None

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Delay before restart ``attempt`` (1-based), equal-jittered."""
        return backoff_with_jitter(self.backoff_initial, self.backoff_max,
                                   attempt, rng)

    @staticmethod
    def from_config(service_cfg) -> "RestartPolicy | None":
        """Policy from a ``ServiceConfig`` (None = reference semantics).
        Shared by both binaries; duck-typed so lifecycle stays decoupled
        from the config package."""
        if service_cfg.restart_max <= 0:
            return None
        return RestartPolicy(
            max_restarts=service_cfg.restart_max,
            backoff_initial=service_cfg.restart_backoff_initial,
            backoff_max=service_cfg.restart_backoff_max,
        )


def init_services(services: Sequence[Service]) -> None:
    """Sequentially Init services; roll back (Shutdown) on first failure.

    Reference ``internal/service/initializer.go:15-58``.
    """
    initialized: list[Service] = []
    for svc in services:
        init = getattr(svc, "init", None)
        if init is None:
            continue
        try:
            log.debug("initializing service", extra={"service": svc.name()})
            # one telemetry cycle per service init: slow startups (XLA
            # warmup, spool recovery scans) become visible stages in
            # /debug/traces instead of an opaque boot delay
            from kepler_tpu import telemetry
            with telemetry.span(f"service.init.{svc.name()}"):
                init()
            initialized.append(svc)
        except Exception as err:
            log.error("initialization failed for %s: %s", svc.name(), err)
            for done in reversed(initialized):
                shutdown = getattr(done, "shutdown", None)
                if shutdown is None:
                    continue
                try:
                    shutdown()
                except Exception as rollback_err:  # best-effort rollback
                    log.warning(
                        "rollback shutdown of %s failed: %s",
                        done.name(), rollback_err,
                    )
            raise ServiceError(
                f"failed to initialize service {svc.name()}: {err}"
            ) from err


def run_services(ctx: CancelContext, services: Sequence[Service],
                 restart: RestartPolicy | None = None) -> None:
    """Run all Runner services concurrently until the first one returns.

    Semantics (reference ``internal/service/run.go:16-65`` / oklog/run):
    each Runner gets a thread running ``svc.run(ctx)``; when any returns or
    raises, the shared ctx is cancelled so all others unwind; finally every
    service's ``shutdown()`` runs (reverse order). The first error is raised.

    With a ``restart`` policy, a Runner that raises is instead restarted
    after a jittered exponential backoff, up to ``restart.max_restarts``
    times per service — the supervised mode (ISSUE: restart-with-backoff).
    Clean returns and exhausted budgets end the group as before.
    """
    runners = [s for s in services if hasattr(s, "run")]
    first_error: list[BaseException] = []
    done = threading.Event()
    threads: list[threading.Thread] = []
    rng = random.Random(restart.seed) if restart is not None else None

    def actor(svc: Service) -> None:
        attempts = 0
        try:
            while True:
                try:
                    svc.run(ctx)  # type: ignore[attr-defined]
                    return  # clean return: deliberate group shutdown
                except Exception as err:
                    if restart is not None and not ctx.cancelled() \
                            and attempts < restart.max_restarts:
                        attempts += 1
                        delay = restart.backoff(attempts, rng)
                        log.warning(
                            "service %s crashed (%s); restart %d/%d in "
                            "%.2fs", svc.name(), err, attempts,
                            restart.max_restarts, delay)
                        if ctx.wait(delay):
                            return
                        continue
                    if not first_error:
                        first_error.append(err)
                    log.error("service %s exited with error: %s",
                              svc.name(), err)
                    return
        finally:
            done.set()  # first (final) return interrupts the whole group

    try:
        for svc in runners:
            t = threading.Thread(target=actor, args=(svc,),
                                 name=f"svc-{svc.name()}", daemon=True)
            t.start()
            threads.append(t)
        if runners:
            done.wait()
    finally:
        ctx.cancel()
        for t in threads:
            t.join(timeout=10.0)
        for svc in reversed(list(services)):
            shutdown = getattr(svc, "shutdown", None)
            if shutdown is None:
                continue
            try:
                shutdown()
            except Exception as err:
                log.warning("shutdown of %s failed: %s", svc.name(), err)
    if first_error:
        raise ServiceError("service group failed") from first_error[0]


class SignalHandler:
    """A Runner that returns when SIGINT/SIGTERM arrives.

    Reference ``internal/service/signal_handler.go:13-39``.

    CPython only installs signal handlers on the main thread, but Runners
    execute on worker threads — so handlers are installed during ``init()``
    (``init_services`` runs sequentially on the caller's thread, normally
    main) and ``run()`` merely waits on the event. Off the main thread,
    installation degrades to waiting for programmatic ``trigger()``.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGINT, signal.SIGTERM)):
        self._signals = tuple(signals)
        self._received = threading.Event()
        self._previous: dict[int, object] = {}

    def name(self) -> str:
        return "signal-handler"

    def init(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            log.warning("not on main thread; OS signals will not be caught")
            return
        for sig in self._signals:
            self._previous[sig] = signal.signal(
                sig, lambda *_: self._received.set()
            )

    def run(self, ctx: CancelContext) -> None:
        while not ctx.cancelled():
            if self._received.wait(0.2):
                log.info("received shutdown signal")
                return

    def shutdown(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return
        for sig, handler in self._previous.items():
            signal.signal(sig, handler)  # type: ignore[arg-type]
        self._previous.clear()

    def trigger(self) -> None:
        """Programmatic shutdown (tests)."""
        self._received.set()
