"""Service lifecycle framework (reference ``internal/service/``)."""

from kepler_tpu.service.lifecycle import (
    CancelContext,
    RestartPolicy,
    Service,
    ServiceError,
    SignalHandler,
    init_services,
    run_services,
)

__all__ = [
    "CancelContext",
    "RestartPolicy",
    "Service",
    "ServiceError",
    "SignalHandler",
    "init_services",
    "run_services",
]
