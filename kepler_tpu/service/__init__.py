"""Service lifecycle framework (reference ``internal/service/``)."""

from kepler_tpu.service.lifecycle import (
    CancelContext,
    Service,
    ServiceError,
    SignalHandler,
    init_services,
    run_services,
)

__all__ = [
    "CancelContext",
    "Service",
    "ServiceError",
    "SignalHandler",
    "init_services",
    "run_services",
]
