"""Stdout exporter: periodic terminal table of node power.

Reference parity: ``internal/exporter/stdout/stdout.go`` — a 2 s ticker dumps
a table of node zone energy/power (tablewriter); when enabled, application
logs move to stderr so the table stays readable
(``cmd/kepler/main.go:34-38`` — handled by the CLI).
"""

from __future__ import annotations

import sys
from typing import IO

from kepler_tpu.device.energy import JOULE, WATT
from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.service.lifecycle import CancelContext


def _render_table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))

    def line(cells, fill=" "):
        return ("| " + " | ".join(
            c.ljust(w, fill) for c, w in zip(cells, widths)) + " |")

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep, line(headers), sep]
    out += [line(r) for r in rows]
    out.append(sep)
    return "\n".join(out)


class StdoutExporter:
    def __init__(
        self,
        monitor: PowerMonitor,
        interval: float = 2.0,
        writer: IO[str] | None = None,
    ) -> None:
        self._monitor = monitor
        self._interval = interval
        self._writer = writer or sys.stdout

    def name(self) -> str:
        return "stdout-exporter"

    def run(self, ctx: CancelContext) -> None:
        # wait for the first snapshot before printing anything
        while not ctx.cancelled():
            if self._monitor.data_channel().wait(0.2):
                break
        while not ctx.cancelled():
            self.write_once()
            if ctx.wait(self._interval):
                return

    def write_once(self) -> None:
        snap = self._monitor.snapshot()
        node = snap.node
        rows = []
        for z, zone in enumerate(node.zone_names):
            rows.append([
                zone,
                f"{node.energy_uj[z] / JOULE:.2f}",
                f"{node.power_uw[z] / WATT:.2f}",
                f"{node.active_power_uw[z] / WATT:.2f}",
                f"{node.idle_power_uw[z] / WATT:.2f}",
            ])
        table = _render_table(
            ["Zone", "Energy (J)", "Power (W)", "Active (W)", "Idle (W)"],
            rows)
        counts = (f"workloads: {len(snap.processes)} procs, "
                  f"{len(snap.containers)} containers, "
                  f"{len(snap.virtual_machines)} vms, {len(snap.pods)} pods; "
                  f"cpu usage {node.usage_ratio:.1%}")
        print(table, file=self._writer)
        print(counts + "\n", file=self._writer, flush=True)

    def shutdown(self) -> None:
        try:
            self._writer.flush()
        except ValueError:  # closed writer
            pass
