"""Exporters (reference ``internal/exporter/``)."""

from kepler_tpu.exporter.stdout import StdoutExporter

__all__ = ["StdoutExporter"]
