"""Build-info and CPU-info collectors.

Reference parity: ``collector/build_info.go:21-53`` (``kepler_build_info``
gauge with arch/branch/revision/version labels) and ``collector/cpuinfo.go:
44-63`` (``kepler_node_cpu_info`` from ``/proc/cpuinfo``).
"""

from __future__ import annotations

import os
import platform

from prometheus_client.core import GaugeMetricFamily

from kepler_tpu import version


class BuildInfoCollector:
    def collect(self):
        info = version.info()
        family = GaugeMetricFamily(
            "kepler_build_info",
            "A metric with a constant '1' value labeled by version info "
            "from which kepler was built",
            labels=["arch", "branch", "revision", "version", "goversion"])
        family.add_metric(
            [platform.machine(), info.git_branch, info.git_commit,
             info.version, f"python{info.python_version}"],
            1.0)
        yield family


class PowerMeterInfoCollector:
    """``kepler_node_cpu_power_meter{source=...} 1`` — which hardware
    backend feeds attribution (reference proposal EP-002 §Metrics:
    ``rapl-powercap`` vs ``rapl-msr``; plus ``fake`` for the dev meter).
    """

    def __init__(self, source: str) -> None:
        self._source = source

    def collect(self):
        family = GaugeMetricFamily(
            "kepler_node_cpu_power_meter",
            "A metric with a constant '1' value labeled by the active "
            "CPU power meter backend",
            labels=["source"])
        family.add_metric([self._source], 1.0)
        yield family


class HealthCollector:
    """``kepler_component_healthy{component=...}`` gauges from the API
    server's health registry — the same probes behind ``/healthz``
    (agent circuit breaker, monitor watchdog, aggregator quarantine)
    exposed on the scrape plane so degradation is alertable without a
    separate probe poller."""

    def __init__(self, health) -> None:
        self._health = health

    def collect(self):
        family = GaugeMetricFamily(
            "kepler_component_healthy",
            "1 while the component's health probe reports ok, else 0",
            labels=["component"])
        _, components = self._health.check_health()
        for name, result in sorted(components.items()):
            family.add_metric([name], 1.0 if result.get("ok") else 0.0)
        yield family


class CPUInfoCollector:
    def __init__(self, procfs: str = "/proc") -> None:
        self._path = os.path.join(procfs, "cpuinfo")

    def _cpus(self):
        cpus: list[dict[str, str]] = []
        current: dict[str, str] = {}
        try:
            with open(self._path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        if current:
                            cpus.append(current)
                            current = {}
                        continue
                    if ":" in line:
                        k, _, v = line.partition(":")
                        current[k.strip()] = v.strip()
        except OSError:
            return []
        if current:
            cpus.append(current)
        return cpus

    def collect(self):
        family = GaugeMetricFamily(
            "kepler_node_cpu_info",
            "CPU information from procfs",
            labels=["processor", "vendor_id", "model_name", "physical_id",
                    "core_id"])
        for cpu in self._cpus():
            family.add_metric(
                [cpu.get("processor", ""), cpu.get("vendor_id", ""),
                 cpu.get("model name", ""), cpu.get("physical id", ""),
                 cpu.get("core id", "")],
                1.0)
        yield family
