"""Prometheus exporter service.

Reference parity: ``internal/exporter/prometheus/prometheus.go`` — owns its
own registry (no global default-registry pollution), optional debug
collectors ("go" → Python runtime collectors here), registers ``/metrics``
on the shared API server with OpenMetrics-capable exposition.
"""

from __future__ import annotations

import logging
from typing import Sequence

from prometheus_client import CollectorRegistry
from prometheus_client.exposition import CONTENT_TYPE_LATEST

from kepler_tpu import telemetry
from kepler_tpu.config.level import Level
from kepler_tpu.exporter.prometheus.fastexpo import fast_generate_latest
from kepler_tpu.exporter.prometheus.collector import PowerCollector
from kepler_tpu.exporter.prometheus.info_collectors import (
    BuildInfoCollector,
    CPUInfoCollector,
)
from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.server.http import APIServer

log = logging.getLogger("kepler.exporter.prometheus")


def create_collectors(
    monitor: PowerMonitor,
    node_name: str = "",
    metrics_level: Level = Level.all(),
    procfs: str = "/proc",
    ready_timeout: float = 0.0,
    meter_source: str = "",
) -> list:
    """Standard collector set (reference CreateCollectors :139-158)."""
    collectors = [
        PowerCollector(monitor, node_name=node_name,
                       metrics_level=metrics_level,
                       ready_timeout=ready_timeout),
        BuildInfoCollector(),
        CPUInfoCollector(procfs=procfs),
    ]
    if meter_source:
        from kepler_tpu.exporter.prometheus.info_collectors import (
            PowerMeterInfoCollector,
        )

        collectors.append(PowerMeterInfoCollector(meter_source))
    return collectors


class PrometheusExporter:
    def __init__(
        self,
        server: APIServer,
        collectors: Sequence[object],
        debug_collectors: Sequence[str] = ("go",),
    ) -> None:
        self._server = server
        self._collectors = list(collectors)
        self._debug = list(debug_collectors)
        self._registry = CollectorRegistry()
        # classic-text scrapes render PowerCollectors via their direct
        # snapshot→text fast path and everything else through the registry;
        # ordering (power first) matches create_collectors' registration
        # order so the fast output is byte-identical to a full registry
        # render (tests/test_exporter_wire.py pins it)
        self._power = [c for c in self._collectors
                       if isinstance(c, PowerCollector)]
        self._aux_registry = CollectorRegistry()

    def name(self) -> str:
        return "prometheus-exporter"

    def init(self) -> None:
        # classic-text byte-identity with a full registry render requires
        # every PowerCollector to be registered BEFORE any aux collector
        # (the fast path concatenates power-then-aux); enforce rather than
        # assume create_collectors' ordering
        seen_aux = False
        for c in self._collectors:
            if isinstance(c, PowerCollector):
                if seen_aux:
                    raise ValueError(
                        "PowerCollector registered after a non-power "
                        "collector; the classic-text fast path renders "
                        "power families first, so this ordering would "
                        "change family order vs the stock renderer")
            else:
                seen_aux = True
        for c in self._collectors:
            self._registry.register(c)  # type: ignore[arg-type]
            if not isinstance(c, PowerCollector):
                self._aux_registry.register(c)  # type: ignore[arg-type]
        if "go" in self._debug or "process" in self._debug:
            # Python-runtime analog of the Go runtime collectors
            try:
                from prometheus_client import (
                    GC_COLLECTOR,
                    PLATFORM_COLLECTOR,
                    PROCESS_COLLECTOR,
                )
                for c in (GC_COLLECTOR, PLATFORM_COLLECTOR,
                          PROCESS_COLLECTOR):
                    for reg in (self._registry, self._aux_registry):
                        try:
                            reg.register(c)
                        except ValueError:
                            pass  # already registered into this registry
            except ImportError:  # pragma: no cover
                log.debug("runtime collectors unavailable")
        self._server.register(
            "/metrics", "Metrics", "Prometheus metrics", self._handle)
        log.info("prometheus exporter ready at /metrics")

    def _handle(self, request) -> tuple[int, dict[str, str], bytes]:
        # content negotiation (reference enables OpenMetrics on its
        # promhttp handler): serve the OpenMetrics exposition when the
        # scraper asks for it, classic text format otherwise. BOTH paths
        # use the collectors' direct fast render — modern Prometheus
        # negotiates OpenMetrics by default, so it is just as hot as
        # classic; only the tiny aux registry goes through the stock
        # renderer (which also supplies the `# EOF` terminator).
        from kepler_tpu.exporter.prometheus.fastexpo import (
            wants_openmetrics,
        )

        # the scrape is its own telemetry cycle: kepler_self_stage_
        # duration_seconds{stage="exporter.scrape"} is the render cost a
        # Prometheus server actually pays per scrape
        with telemetry.span("exporter.scrape"):
            if wants_openmetrics(request):
                from prometheus_client.openmetrics import (
                    exposition as om_exposition,
                )
                payload = (b"".join(c.render_text(openmetrics=True)
                                    for c in self._power)
                           + om_exposition.generate_latest(
                               self._aux_registry))
                return (200,
                        {"Content-Type": om_exposition.CONTENT_TYPE_LATEST},
                        payload)
            payload = (b"".join(c.render_text() for c in self._power)
                       + fast_generate_latest(self._aux_registry))
            return 200, {"Content-Type": CONTENT_TYPE_LATEST}, payload

    @property
    def registry(self) -> CollectorRegistry:
        return self._registry


def make_registry_handler(registry: CollectorRegistry):
    """Generic /metrics handler over one registry with content
    negotiation, both formats on the fast renderers (byte-identical to
    the stock ones, with wholesale fallback). The aggregator's
    fleet-metrics endpoint uses this; the node exporter has its own
    handler because its power families bypass the registry entirely."""
    from prometheus_client.openmetrics import exposition as om_exposition

    from kepler_tpu.exporter.prometheus.fastexpo import (
        fast_generate_openmetrics,
        wants_openmetrics,
    )

    def handler(request) -> tuple[int, dict[str, str], bytes]:
        with telemetry.span("exporter.scrape"):
            if wants_openmetrics(request):
                return (200,
                        {"Content-Type": om_exposition.CONTENT_TYPE_LATEST},
                        fast_generate_openmetrics(registry))
            return (200, {"Content-Type": CONTENT_TYPE_LATEST},
                    fast_generate_latest(registry))

    return handler
