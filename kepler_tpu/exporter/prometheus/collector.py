"""Prometheus power collector.

Reference parity: ``internal/exporter/prometheus/collector/power_collector.go``
— one ``collect()`` takes exactly one ``Snapshot()`` so all series in a scrape
are consistent (:215); metric families/labels match ``docs/user/metrics.md``;
a readiness gate waits for the monitor's first refresh (:142-152); the
metrics-level bitmask selects which families are emitted.

Metric families (names/labels identical to the reference):
  kepler_node_cpu_joules_total{zone,path}                + active/idle variants
  kepler_node_cpu_watts{zone,path}                       + active/idle variants
  kepler_node_cpu_usage_ratio
  kepler_process_cpu_joules_total{pid,comm,exe,type,state,container_id,vm_id,zone}
  kepler_process_cpu_watts{...}, kepler_process_cpu_seconds_total{...}
  kepler_container_cpu_joules_total{container_id,container_name,runtime,state,zone,pod_id}
  kepler_vm_cpu_joules_total{vm_id,vm_name,hypervisor,state,zone}
  kepler_pod_cpu_joules_total{pod_id,pod_name,pod_namespace,state,zone}
"""

from __future__ import annotations

import logging
from typing import Iterable

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from kepler_tpu.config.level import Level
from kepler_tpu.device.energy import JOULE, WATT
from kepler_tpu.monitor.monitor import (PowerMonitor,
                                        SnapshotUnavailableError)
from kepler_tpu.monitor.snapshot import WorkloadTable

log = logging.getLogger("kepler.exporter.prometheus")

_META_LABEL_SETS = {
    "process": ("pid", "comm", "exe", "type", "container_id", "vm_id"),
    "container": ("container_id", "container_name", "runtime", "pod_id"),
    "vm": ("vm_id", "vm_name", "hypervisor"),
    "pod": ("pod_id", "pod_name", "pod_namespace"),
}


class PowerCollector:
    """Custom collector; registered into the exporter's registry."""

    def __init__(
        self,
        monitor: PowerMonitor,
        node_name: str = "",
        metrics_level: Level = Level.all(),
        ready_timeout: float = 0.0,
    ) -> None:
        self._monitor = monitor
        self._node_name = node_name
        self._level = metrics_level
        self._ready_timeout = ready_timeout

    def _is_ready(self) -> bool:
        return self._monitor.data_channel().wait(self._ready_timeout)

    def collect(self):
        if not self._is_ready():
            log.debug("collector not ready: no snapshot yet")
            return
        try:
            snap = self._monitor.snapshot()  # ONE snapshot per scrape
        except SnapshotUnavailableError as err:
            # defined degradation: an empty scrape (plus a warning) beats a
            # 500 with a traceback — Prometheus records the target up with
            # no kepler families, and the next scrape retries the refresh
            log.warning("scrape skipped: %s", err)
            return
        const = {"node_name": self._node_name} if self._node_name else {}

        if Level.NODE in self._level:
            yield from self._node_metrics(snap, const)
            ratio = GaugeMetricFamily(
                "kepler_node_cpu_usage_ratio",
                "CPU usage ratio of a node (active/total)",
                labels=list(const))
            yield self._with_const(ratio, [], snap.node.usage_ratio, const)
        kind_level = {
            "process": (Level.PROCESS, snap.processes,
                        snap.terminated_processes),
            "container": (Level.CONTAINER, snap.containers,
                          snap.terminated_containers),
            "vm": (Level.VM, snap.virtual_machines,
                   snap.terminated_virtual_machines),
            "pod": (Level.POD, snap.pods, snap.terminated_pods),
        }
        zone_names = snap.node.zone_names
        for kind, (level, running, terminated) in kind_level.items():
            if level not in self._level:
                continue
            yield from self._workload_metrics(
                kind, zone_names, running, terminated, const)

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _with_const(family, labels: list[str], value: float,
                    const: dict[str, str]):
        family.add_metric(labels + list(const.values()), value)
        return family

    def _node_metrics(self, snap, const: dict[str, str]):
        node = snap.node
        variants = (
            ("joules_total", CounterMetricFamily, "Energy consumption of cpu",
             (node.energy_uj, node.active_uj, node.idle_uj), 1 / JOULE),
            ("watts", GaugeMetricFamily, "Power consumption of cpu",
             (node.power_uw, node.active_power_uw, node.idle_power_uw),
             1 / WATT),
        )
        const_keys = list(const)
        for suffix, ctor, desc, (total, active, idle), scale in variants:
            for state, values in (("", total), ("active_", active),
                                  ("idle_", idle)):
                name = f"kepler_node_cpu_{state}{suffix}"
                family = ctor(
                    name,
                    f"{desc}{' in ' + state.rstrip('_') + ' state' if state else ''}"
                    " at node level",
                    labels=["zone", "path"] + const_keys)
                for z, zone in enumerate(node.zone_names):
                    family.add_metric(
                        [zone, ""] + list(const.values()),
                        float(values[z]) * scale)
                yield family

    def _workload_metrics(self, kind: str, zone_names,
                          running: WorkloadTable, terminated: WorkloadTable,
                          const: dict[str, str]):
        label_names = list(_META_LABEL_SETS[kind])
        full_labels = label_names + ["state", "zone"] + list(const)
        joules = CounterMetricFamily(
            f"kepler_{kind}_cpu_joules_total",
            f"Energy consumption of cpu at {kind} level in joules",
            labels=full_labels)
        watts = GaugeMetricFamily(
            f"kepler_{kind}_cpu_watts",
            f"Power consumption of cpu at {kind} level in watts",
            labels=full_labels)
        seconds = None
        if kind == "process":
            seconds = CounterMetricFamily(
                "kepler_process_cpu_seconds_total",
                "Total user and system time of the process in seconds",
                labels=label_names + ["state"] + list(const))
        for state, table in (("running", running), ("terminated", terminated)):
            for i, wid in enumerate(table.ids):
                meta = table.meta[i]
                values = self._label_values(kind, wid, meta, label_names)
                for z, zone in enumerate(zone_names):
                    lv = values + [state, zone] + list(const.values())
                    joules.add_metric(lv, float(table.energy_uj[i, z]) / JOULE)
                    watts.add_metric(lv, float(table.power_uw[i, z]) / WATT)
                if seconds is not None and "_cpu_total_seconds" in meta:
                    seconds.add_metric(
                        values + [state] + list(const.values()),
                        float(meta["_cpu_total_seconds"]))
        yield joules
        yield watts
        if seconds is not None:
            yield seconds

    @staticmethod
    def _label_values(kind: str, wid: str, meta, label_names: Iterable[str]
                      ) -> list[str]:
        id_label = {"process": "pid", "container": "container_id",
                    "vm": "vm_id", "pod": "pod_id"}[kind]
        alias = {"pod_name": "pod_name", "pod_namespace": "namespace",
                 "vm_name": "vm_name"}
        out = []
        for name in label_names:
            if name == id_label:
                out.append(wid)
            elif name in meta:
                out.append(str(meta[name]))
            elif name in alias and alias[name] in meta:
                out.append(str(meta[alias[name]]))
            else:
                out.append("")
        return out
