"""Prometheus power collector.

Reference parity: ``internal/exporter/prometheus/collector/power_collector.go``
— one ``collect()`` takes exactly one ``Snapshot()`` so all series in a scrape
are consistent (:215); metric families/labels match ``docs/user/metrics.md``;
a readiness gate waits for the monitor's first refresh (:142-152); the
metrics-level bitmask selects which families are emitted.

Metric families (names/labels identical to the reference):
  kepler_node_cpu_joules_total{zone,path}                + active/idle variants
  kepler_node_cpu_watts{zone,path}                       + active/idle variants
  kepler_node_cpu_usage_ratio
  kepler_process_cpu_joules_total{pid,comm,exe,type,state,container_id,vm_id,zone}
  kepler_process_cpu_watts{...}, kepler_process_cpu_seconds_total{...}
  kepler_container_cpu_joules_total{container_id,container_name,runtime,state,zone,pod_id}
  kepler_vm_cpu_joules_total{vm_id,vm_name,hypervisor,state,zone}
  kepler_pod_cpu_joules_total{pod_id,pod_name,pod_namespace,state,zone}
"""

from __future__ import annotations

import logging
from typing import Iterable

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from kepler_tpu.config.level import Level
from kepler_tpu.device.energy import JOULE, WATT
from kepler_tpu.monitor.monitor import (PowerMonitor,
                                        SnapshotUnavailableError)
from kepler_tpu.monitor.snapshot import WorkloadTable

log = logging.getLogger("kepler.exporter.prometheus")

_META_LABEL_SETS = {
    "process": ("pid", "comm", "exe", "type", "container_id", "vm_id"),
    "container": ("container_id", "container_name", "runtime", "pod_id"),
    "vm": ("vm_id", "vm_name", "hypervisor"),
    "pod": ("pod_id", "pod_name", "pod_namespace"),
}

# ONE definition of the family tables, consumed by both the registry path
# (collect()) and the direct text fast path (render_text()) — keep them
# here so the two renders cannot diverge.
#   kind → (level bit, Snapshot attr, terminated Snapshot attr)
_KIND_TABLES = (
    ("process", Level.PROCESS, "processes", "terminated_processes"),
    ("container", Level.CONTAINER, "containers", "terminated_containers"),
    ("vm", Level.VM, "virtual_machines", "terminated_virtual_machines"),
    ("pod", Level.POD, "pods", "terminated_pods"),
)
#   (name suffix, type, doc stem, NodeUsage attrs (total, active, idle),
#    unit scale)
_NODE_VARIANTS = (
    ("joules_total", "counter", "Energy consumption of cpu",
     ("energy_uj", "active_uj", "idle_uj"), 1 / JOULE),
    ("watts", "gauge", "Power consumption of cpu",
     ("power_uw", "active_power_uw", "idle_power_uw"), 1 / WATT),
)


def _node_family_doc(desc: str, state: str) -> str:
    return (f"{desc}"
            f"{' in ' + state.rstrip('_') + ' state' if state else ''}"
            " at node level")


_NATIVE_RENDERER = False  # False = unresolved; None = unavailable


def _native_renderer():
    """Process-wide native sample renderer, or None (pure-Python render)."""
    global _NATIVE_RENDERER
    if _NATIVE_RENDERER is False:
        try:
            from kepler_tpu import native
            _NATIVE_RENDERER = native.scanner()
        except Exception:  # no compiler / load failure → Python fallback
            _NATIVE_RENDERER = None
    return _NATIVE_RENDERER


class PowerCollector:
    """Custom collector; registered into the exporter's registry."""

    def __init__(
        self,
        monitor: PowerMonitor,
        node_name: str = "",
        metrics_level: Level = Level.all(),
        ready_timeout: float = 0.0,
    ) -> None:
        self._monitor = monitor
        self._node_name = node_name
        self._level = metrics_level
        self._ready_timeout = ready_timeout
        # render_text()'s cached per-row label block holds every label
        # EXCEPT zone and is reused verbatim with `,zone="…"` appended —
        # sound only while every other label name sorts before "zone".
        # Enforce here (not via assert: -O must not silently change series
        # identity) so a future label addition fails loudly at construction.
        const_keys = ["node_name"] if node_name else []
        for kind, names in _META_LABEL_SETS.items():
            bad = [k for k in [*names, "state", *const_keys] if k >= "zone"]
            if bad:
                raise ValueError(
                    f"label names {bad} for kind {kind!r} sort at/after "
                    "'zone'; the cached-prefix text render requires all "
                    "non-zone labels to sort before it")

    def _is_ready(self) -> bool:
        return self._monitor.data_channel().wait(self._ready_timeout)

    def collect(self):
        if not self._is_ready():
            log.debug("collector not ready: no snapshot yet")
            return
        try:
            snap = self._monitor.snapshot()  # ONE snapshot per scrape
        except SnapshotUnavailableError as err:
            # defined degradation: an empty scrape (plus a warning) beats a
            # 500 with a traceback — Prometheus records the target up with
            # no kepler families, and the next scrape retries the refresh
            log.warning("scrape skipped: %s", err)
            return
        const = {"node_name": self._node_name} if self._node_name else {}

        if Level.NODE in self._level:
            yield from self._node_metrics(snap, const)
            ratio = GaugeMetricFamily(
                "kepler_node_cpu_usage_ratio",
                "CPU usage ratio of a node (active/total)",
                labels=list(const))
            yield self._with_const(ratio, [], snap.node.usage_ratio, const)
        zone_names = snap.node.zone_names
        for kind, level, run_attr, term_attr in _KIND_TABLES:
            if level not in self._level:
                continue
            yield from self._workload_metrics(
                kind, zone_names, getattr(snap, run_attr),
                getattr(snap, term_attr), const)

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _with_const(family, labels: list[str], value: float,
                    const: dict[str, str]):
        family.add_metric(labels + list(const.values()), value)
        return family

    def _node_metrics(self, snap, const: dict[str, str]):
        node = snap.node
        const_keys = list(const)
        for suffix, mtype, desc, attrs, scale in _NODE_VARIANTS:
            ctor = (CounterMetricFamily if mtype == "counter"
                    else GaugeMetricFamily)
            for state, attr in zip(("", "active_", "idle_"), attrs):
                values = getattr(node, attr)
                name = f"kepler_node_cpu_{state}{suffix}"
                family = ctor(
                    name, _node_family_doc(desc, state),
                    labels=["zone", "path"] + const_keys)
                for z, zone in enumerate(node.zone_names):
                    family.add_metric(
                        [zone, ""] + list(const.values()),
                        float(values[z]) * scale)
                yield family

    def _workload_metrics(self, kind: str, zone_names,
                          running: WorkloadTable, terminated: WorkloadTable,
                          const: dict[str, str]):
        label_names = list(_META_LABEL_SETS[kind])
        full_labels = label_names + ["state", "zone"] + list(const)
        joules = CounterMetricFamily(
            f"kepler_{kind}_cpu_joules_total",
            f"Energy consumption of cpu at {kind} level in joules",
            labels=full_labels)
        watts = GaugeMetricFamily(
            f"kepler_{kind}_cpu_watts",
            f"Power consumption of cpu at {kind} level in watts",
            labels=full_labels)
        seconds = None
        if kind == "process":
            seconds = CounterMetricFamily(
                "kepler_process_cpu_seconds_total",
                "Total user and system time of the process in seconds",
                labels=label_names + ["state"] + list(const))
        for state, table in (("running", running), ("terminated", terminated)):
            for i, wid in enumerate(table.ids):
                meta = table.meta[i]
                values = self._label_values(kind, wid, meta, label_names)
                for z, zone in enumerate(zone_names):
                    lv = values + [state, zone] + list(const.values())
                    joules.add_metric(lv, float(table.energy_uj[i, z]) / JOULE)
                    watts.add_metric(lv, float(table.power_uw[i, z]) / WATT)
                if seconds is not None and table.seconds is not None:
                    # 6-decimal rounding matches the reference's seconds
                    # formatting (and the native renderer's round6 flag)
                    seconds.add_metric(
                        values + [state] + list(const.values()),
                        float(f"{float(table.seconds[i]):.6f}"))
        yield joules
        yield watts
        if seconds is not None:
            yield seconds

    # -- direct text render (the node hot path) ---------------------------
    #
    # Rendering 10k processes through prometheus_client costs ~650 ms per
    # scrape (per-sample Metric objects + per-sample label re-escaping);
    # the snapshot already holds everything in table form, so the exporter
    # renders the kepler families straight to classic text, caching each
    # workload's escaped label block across scrapes (labels change on
    # exec/reclassify; counters change every tick). Output is byte-
    # identical to prometheus_client's generate_latest over this collector
    # — pinned by tests/test_exporter_wire.py.

    def render_text(self, openmetrics: bool = False) -> bytes:
        """Text exposition of this collector's families (fast path).
        Empty bytes when not ready / snapshot unavailable — the same
        scrapes collect() would skip.

        Per-row label blocks are cached as bytes across scrapes (labels
        change on exec/reclassify; values change every tick); when the
        native library is present the value formatting and line assembly
        for a whole family happen in ONE C call
        (``kepler_render_samples``), so a 10k-process scrape does no
        per-sample Python work at all. Byte parity with the stock
        renderer is pinned by tests/test_exporter_wire.py either way.

        ``openmetrics=True`` emits the OpenMetrics exposition instead —
        sample lines are byte-identical to classic text for these
        families; only the counter HELP/TYPE header names differ (base
        name instead of ``*_total``). Modern Prometheus negotiates
        OpenMetrics BY DEFAULT, so this path is just as hot as classic.
        The caller appends the ``# EOF`` terminator (the exporter
        concatenates several renders first).
        """
        from kepler_tpu.exporter.prometheus.fastexpo import _escape_value

        if not self._is_ready():
            return b""
        try:
            # no deep clone: the render only reads, and published
            # snapshots are immutable (see PowerMonitor.snapshot)
            snap = self._monitor.snapshot(clone=False)
        except SnapshotUnavailableError as err:
            log.warning("scrape skipped: %s", err)
            return b""
        const = {"node_name": self._node_name} if self._node_name else {}
        out: list[bytes] = []
        if Level.NODE in self._level:
            node_out: list[str] = []
            self._render_node_text(node_out, snap, const, openmetrics)
            out.append("".join(node_out).encode("utf-8"))
        ezones = [(z, _escape_value(z)) for z in snap.node.zone_names]
        new_cache: dict = {}
        for kind, level, run_attr, term_attr in _KIND_TABLES:
            if level not in self._level:
                continue
            self._render_workload_text(out, kind, ezones,
                                       getattr(snap, run_attr),
                                       getattr(snap, term_attr), const,
                                       new_cache, openmetrics)
        self._label_cache = new_cache  # drop vanished workloads' entries
        return b"".join(out)

    @staticmethod
    def _header_name(sample_name: str, openmetrics: bool) -> str:
        """OpenMetrics HELP/TYPE lines carry the FAMILY name (no _total);
        classic text carries the suffixed sample name."""
        if openmetrics and sample_name.endswith("_total"):
            return sample_name[:-len("_total")]
        return sample_name

    def _render_node_text(self, out: list[str], snap, const,
                          openmetrics: bool = False) -> None:
        from prometheus_client.utils import floatToGoString

        from kepler_tpu.exporter.prometheus.fastexpo import _escape_value

        node = snap.node
        for suffix, mtype, desc, attrs, scale in _NODE_VARIANTS:
            for state, attr in zip(("", "active_", "idle_"), attrs):
                values = getattr(node, attr)
                name = f"kepler_node_cpu_{state}{suffix}"
                doc = _node_family_doc(desc, state)
                hname = self._header_name(name, openmetrics)
                out.append(f"# HELP {hname} {doc}\n")
                out.append(f"# TYPE {hname} {mtype}\n")
                for z, zone in enumerate(node.zone_names):
                    pairs = sorted({"zone": zone, "path": "",
                                    **const}.items())
                    labelstr = ",".join(
                        f'{k}="{_escape_value(v)}"' for k, v in pairs)
                    out.append(f"{name}{{{labelstr}}} "
                               f"{floatToGoString(values[z] * scale)}\n")
        name = "kepler_node_cpu_usage_ratio"
        out.append(f"# HELP {name} CPU usage ratio of a node "
                   "(active/total)\n")
        out.append(f"# TYPE {name} gauge\n")
        if const:
            pairs = sorted(const.items())
            labelstr = "{%s}" % ",".join(
                f'{k}="{_escape_value(v)}"' for k, v in pairs)
        else:
            labelstr = ""
        out.append(f"{name}{labelstr} "
                   f"{floatToGoString(node.usage_ratio)}\n")

    def _render_workload_text(self, out: list[bytes], kind: str, ezones,
                              running: WorkloadTable,
                              terminated: WorkloadTable, const,
                              new_cache: dict,
                              openmetrics: bool = False) -> None:
        from kepler_tpu.exporter.prometheus.fastexpo import (_escape_value,
                                                            fmt_float)

        label_names = list(_META_LABEL_SETS[kind])
        # the cached per-row block holds every label EXCEPT zone; sound
        # because every other label name sorts before "zone" — enforced
        # with a real ValueError in __init__
        nonzone = label_names + ["state"] + list(const)
        order = sorted(range(len(nonzone)), key=lambda i: nonzone[i])
        jname = f"kepler_{kind}_cpu_joules_total"
        wname = f"kepler_{kind}_cpu_watts"
        cache = getattr(self, "_label_cache", {})
        const_vals = tuple(const.values())
        is_process = kind == "process"
        states = (("running", running), ("terminated", terminated))
        # pass 1: per-row label blocks. The whole (prefix list, joined
        # blob, offsets) is cached per state keyed on the table's id and
        # meta tuples — meta dicts are object-cached by the informer, so
        # in the steady state (values change, labels don't) this is two
        # tuple comparisons, not 10k dict probes.
        prefixes_by_state: list[tuple[list[bytes], bytes, object]] = []
        blob_cache = getattr(self, "_blob_cache", {})
        new_blobs = {}
        for state, table in states:
            bkey = (kind, state)
            blob_cached = blob_cache.get(bkey)
            if (blob_cached is not None and blob_cached[0] == table.ids
                    and blob_cached[1] == table.meta):
                new_blobs[bkey] = blob_cached
                prefixes_by_state.append(blob_cached[2])
                # keep the per-row cache warm for the next membership
                # change (one C-level bulk copy, no per-row Python)
                new_cache.update(blob_cached[3])
                continue
            metas = table.meta
            prefixes: list[bytes] = []
            row_cache: dict = {}
            for i, wid in enumerate(table.ids):
                meta = metas[i]
                key = (kind, state, wid)
                cached = cache.get(key)
                if cached is not None and (cached[0] is meta
                                           or cached[0] == meta):
                    prefix = cached[1]
                    new_cache[key] = cached
                else:
                    values = self._label_values(kind, wid, meta,
                                                label_names)
                    row = tuple(values) + (state,) + const_vals
                    prefix = ("{" + ",".join(
                        f'{nonzone[i_]}="{_escape_value(row[i_])}"'
                        for i_ in order)).encode("utf-8")
                    cached = (meta, prefix)
                    new_cache[key] = cached
                row_cache[key] = cached
                prefixes.append(prefix)
            import numpy as np
            off = np.zeros(len(prefixes) + 1, np.int64)
            if prefixes:
                np.cumsum([len(p) for p in prefixes], out=off[1:])
            entry3 = (prefixes, b"".join(prefixes), off)
            new_blobs[bkey] = (table.ids, table.meta, entry3, row_cache)
            prefixes_by_state.append(entry3)
        blob_cache.update(new_blobs)
        self._blob_cache = blob_cache
        ztails = [f',zone="{ez}"}} '.encode("utf-8") for _, ez in ezones]
        native = _native_renderer()
        # pass 2: families — joules, watts, then (processes) seconds; each
        # family lists running rows then terminated rows, matching the
        # registry renderer's sample order
        jhead = self._header_name(jname, openmetrics)
        out.append(f"# HELP {jhead} Energy consumption of cpu at {kind} "
                   f"level in joules\n# TYPE {jhead} counter\n".encode())
        self._render_family(out, jname.encode(), prefixes_by_state, states,
                            "energy_uj", ztails, JOULE, native, fmt_float)
        out.append(f"# HELP {wname} Power consumption of cpu at {kind} "
                   f"level in watts\n# TYPE {wname} gauge\n".encode())
        self._render_family(out, wname.encode(), prefixes_by_state, states,
                            "power_uw", ztails, WATT, native, fmt_float)
        if is_process:
            shead = self._header_name("kepler_process_cpu_seconds_total",
                                      openmetrics)
            out.append(f"# HELP {shead} Total user and system time of "
                       f"the process in seconds\n"
                       f"# TYPE {shead} counter\n".encode())
            self._render_family(out, b"kepler_process_cpu_seconds_total",
                                prefixes_by_state, states, "seconds",
                                [b"} "], 1.0, native, fmt_float,
                                round6=True)

    @staticmethod
    def _render_family(out: list[bytes], name: bytes,
                       prefixes_by_state, states, attr: str,
                       ztails: list[bytes], div: float, native,
                       fmt_float, round6: bool = False) -> None:
        """One family's sample lines (running then terminated rows):
        native renderer when available, else a per-sample Python loop
        producing identical bytes."""
        import numpy as np
        for (prefixes, blob, off), (_state, table) in zip(
                prefixes_by_state, states):
            values = getattr(table, attr)
            if values is None or not len(prefixes):
                continue
            if values.ndim == 1:
                values = values[:, None]
            if native is not None:
                zoff = np.zeros(len(ztails) + 1, np.int32)
                np.cumsum([len(z) for z in ztails], out=zoff[1:])
                out.append(native.render_samples(
                    name, blob, off, b"".join(ztails), zoff,
                    values, div, round6=round6))
                continue
            for i, prefix in enumerate(prefixes):
                for z, ztail in enumerate(ztails):
                    v = float(values[i, z]) / div
                    if round6:
                        v = float(f"{v:.6f}")
                    out.append(name + prefix + ztail
                               + fmt_float(v).encode() + b"\n")

    @staticmethod
    def _label_values(kind: str, wid: str, meta, label_names: Iterable[str]
                      ) -> list[str]:
        id_label = {"process": "pid", "container": "container_id",
                    "vm": "vm_id", "pod": "pod_id"}[kind]
        alias = {"pod_name": "pod_name", "pod_namespace": "namespace",
                 "vm_name": "vm_name"}
        out = []
        for name in label_names:
            if name == id_label:
                out.append(wid)
            elif name in meta:
                out.append(str(meta[name]))
            elif name in alias and alias[name] in meta:
                out.append(str(meta[alias[name]]))
            else:
                out.append("")
        return out
