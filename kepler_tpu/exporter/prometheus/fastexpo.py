"""Fast Prometheus text exposition.

``prometheus_client.generate_latest`` spends >80% of a large scrape
re-validating and re-escaping every label NAME of every sample with
regexes (measured: 10k-process scrape ≈ 640 ms of ``sample_line``, of
which the attribution math is ~3%). Label names in a metric family are
static — validating them per-sample is pure waste on the node exporter's
hot path, where the reference's Go renderer is effectively free.

``fast_generate_latest`` renders byte-identical classic text format
(`text/plain; version=0.0.4`) for registries whose metric and label names
are legacy-valid (all kepler families are), validating each distinct
label-name tuple once per family instead of once per sample. Anything
non-legacy falls back to ``prometheus_client`` wholesale, so output is
ALWAYS exactly what the stock renderer would produce —
``tests/test_exporter_wire.py`` pins the byte equality.

Label VALUES still escape per sample (they are dynamic), with the same
replace chain as ``openmetrics._escape(ALLOWUTF8)``.
"""

from __future__ import annotations

import re
from math import copysign as _copysign

from prometheus_client.exposition import generate_latest
from prometheus_client.registry import Collector
from prometheus_client.utils import floatToGoString

_LEGACY_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LEGACY_LABEL = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# OpenMetrics sample/family names: NO colons — the stock OM renderer
# escapes colons to underscores, so colon names must take the fallback
_OM_NAME = _LEGACY_LABEL

# OpenMetrics sample suffixes that the classic format renders as trailing
# gauges (mirrors generate_latest's om_samples munging)
_OM_SUFFIXES = ("_created", "_gsum", "_gcount")


def fmt_float(v: float) -> str:
    """floatToGoString with the overwhelmingly-common cases inlined (zeros
    and plain positive decimals); exponent-range, infinite, and negative
    values delegate to the real thing. Byte parity pinned in tests."""
    if v > 0.0:
        s = repr(v)
        dot = s.find(".")
        if 0 < dot <= 6:
            return s
        if dot == -1 and s[0] != "i":
            return s  # exponent repr like 1e-05: stock returns it verbatim
        return floatToGoString(v)  # inf, or ≥7 integer digits (Go-style e+)
    if v == 0.0:
        # copysign distinguishes -0.0; stock emits repr as-is
        return "0.0" if _copysign(1.0, v) > 0 else "-0.0"
    return floatToGoString(v)


def _escape_value(v: str) -> str:
    """openmetrics._escape(s, ALLOWUTF8, ...) replace chain, inlined."""
    if "\\" in v:
        v = v.replace("\\", "\\\\")
    if "\n" in v:
        v = v.replace("\n", "\\n")
    if '"' in v:
        v = v.replace('"', '\\"')
    return v


def _escape_doc(doc: str) -> str:
    return doc.replace("\\", "\\\\").replace("\n", "\\n")


def wants_openmetrics(request) -> bool:
    """Content negotiation shared by every /metrics handler: does the
    scraper's Accept header ask for the OpenMetrics exposition? (Default
    Prometheus does.)"""
    accept = ""
    if request is not None and getattr(request, "headers", None):
        accept = request.headers.get("Accept") or ""
    return "application/openmetrics-text" in accept


def fast_generate_openmetrics(registry: Collector) -> bytes:
    """Byte-identical ``openmetrics.exposition.generate_latest`` with
    per-family label-name validation (the OM twin of
    :func:`fast_generate_latest`). OM keeps each family's BASE name in
    HELP/TYPE (no classic ``_total``/``_info`` munging), renders sample
    lines identically, and terminates with ``# EOF``. Falls back to the
    stock renderer for anything beyond the simple counter/gauge/info
    families the kepler registries hold (exemplars, created timestamps,
    non-legacy names)."""
    from prometheus_client.openmetrics import exposition as om

    output: list[str] = []
    for metric in registry.collect():
        mname = metric.name
        if metric.type not in ("counter", "gauge", "info", "unknown"):
            return om.generate_latest(registry)  # histograms etc.: stock
        if not _OM_NAME.match(mname) or metric.unit:
            # colon names get underscore-escaped by the stock renderer;
            # units grow a suffix — both take the wholesale fallback
            return om.generate_latest(registry)
        # OM escapes quotes in HELP text too (classic does not); one
        # chain, same order as the stock renderer's _escape(ALLOWUTF8)
        doc = (metric.documentation.replace("\\", "\\\\")
               .replace("\n", "\\n").replace('"', '\\"'))
        output.append(f"# HELP {mname} {doc}\n")
        output.append(f"# TYPE {mname} {metric.type}\n")
        key_cache: tuple[str, ...] | None = None
        sorted_keys: list[str] = []
        for s in metric.samples:
            if (s.timestamp is not None or s.exemplar is not None
                    or not _OM_NAME.match(s.name)):
                return om.generate_latest(registry)
            if metric.type == "counter" and s.name.endswith("_created"):
                return om.generate_latest(registry)
            keys = tuple(s.labels)
            if keys != key_cache:
                if not all(_LEGACY_LABEL.match(k) for k in keys):
                    return om.generate_latest(registry)
                sorted_keys = sorted(keys)
                key_cache = keys
            labels = s.labels
            if labels:
                labelstr = "{%s}" % ",".join(
                    f'{k}="{_escape_value(labels[k])}"'
                    for k in sorted_keys)
            else:
                labelstr = ""
            output.append(
                f"{s.name}{labelstr} {floatToGoString(s.value)}\n")
    output.append("# EOF\n")
    return "".join(output).encode("utf-8")


def fast_generate_latest(registry: Collector) -> bytes:
    """Byte-identical ``generate_latest`` with per-family (not per-sample)
    label-name validation. Falls back to prometheus_client when any name
    is not legacy-valid."""
    output: list[str] = []
    for metric in registry.collect():
        mname = metric.name
        mtype = metric.type
        if mtype == "counter":
            mname += "_total"
        elif mtype == "info":
            mname += "_info"
            mtype = "gauge"
        elif mtype == "stateset":
            mtype = "gauge"
        elif mtype == "gaugehistogram":
            mtype = "histogram"
        elif mtype == "unknown":
            mtype = "untyped"
        if not _LEGACY_NAME.match(mname):
            return generate_latest(registry)  # rare: full fallback
        doc = _escape_doc(metric.documentation)
        output.append(f"# HELP {mname} {doc}\n")
        output.append(f"# TYPE {mname} {mtype}\n")

        key_cache: tuple[str, ...] | None = None
        sorted_keys: list[str] = []
        om_samples: dict[str, list[str]] = {}
        for s in metric.samples:
            if not _LEGACY_NAME.match(s.name):
                return generate_latest(registry)
            keys = tuple(s.labels)
            if keys != key_cache:
                if not all(_LEGACY_LABEL.match(k) for k in keys):
                    return generate_latest(registry)
                sorted_keys = sorted(keys)
                key_cache = keys
            labels = s.labels
            if labels:
                labelstr = "{%s}" % ",".join(
                    f'{k}="{_escape_value(labels[k])}"'
                    for k in sorted_keys)
            else:
                labelstr = ""
            ts = ""
            if s.timestamp is not None:
                ts = f" {int(float(s.timestamp) * 1000):d}"
            line = f"{s.name}{labelstr} {floatToGoString(s.value)}{ts}\n"
            for suffix in _OM_SUFFIXES:
                if s.name == metric.name + suffix:
                    om_samples.setdefault(suffix, []).append(line)
                    break
            else:
                output.append(line)
        for suffix, lines in sorted(om_samples.items()):
            output.append(f"# HELP {metric.name}{suffix} {doc}\n")
            output.append(f"# TYPE {metric.name}{suffix} gauge\n")
            output.extend(lines)
    return "".join(output).encode("utf-8")
