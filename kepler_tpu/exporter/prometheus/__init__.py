"""Prometheus exporter (reference ``internal/exporter/prometheus/``)."""

from kepler_tpu.exporter.prometheus.collector import PowerCollector
from kepler_tpu.exporter.prometheus.exporter import (
    PrometheusExporter,
    create_collectors,
)
from kepler_tpu.exporter.prometheus.info_collectors import (
    BuildInfoCollector,
    CPUInfoCollector,
    HealthCollector,
)

__all__ = [
    "BuildInfoCollector",
    "CPUInfoCollector",
    "HealthCollector",
    "PowerCollector",
    "PrometheusExporter",
    "create_collectors",
]
