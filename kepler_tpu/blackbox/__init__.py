"""Fleet black-box reader: merge per-replica journals into one timeline.

The fleet's incident story is scattered across N replicas' event
journals (``/debug/journal``), incident bundles (``/debug/bundle``) and
durable ``.kepj`` spool files. This package loads any mix of those
sources, merges the events into one causally-ordered fleet timeline
(HLC order: ``(phys_us, logical, node)``), and flags the two classic
fleet pathologies on the way out:

- **split-brain** — two nodes adopting a coordinator lease for the same
  epoch with different holders, or two membership applies at one epoch
  disagreeing on the peer set;
- **flapping** — a breaker or rung oscillating (≥ ``_FLAP_N``
  transitions on one node inside ``_FLAP_WINDOW_S``).

Everything here is deterministic: same inputs → byte-identical merged
timeline → same SHA-256 (``make blackbox`` pins this). No wall-clock
reads, no set iteration without sorting.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Iterable

from kepler_tpu.fleet.journal import canonical_json, read_frames

__all__ = [
    "analyze",
    "chrome_trace",
    "fetch_journal",
    "load_source",
    "merge_events",
    "render_text",
    "timeline_sha256",
]

SCHEMA = "kepler-blackbox/v1"
_FLAP_N = 4
_FLAP_WINDOW_S = 120.0


def _hlc_key(entry: dict[str, Any]) -> tuple[int, int, str]:
    h = entry.get("hlc") or {}
    return (int(h.get("phys_us", 0)), int(h.get("logical", 0)),
            str(h.get("node", "")))


def merge_events(journals: Iterable[list[dict[str, Any]]]
                 ) -> list[dict[str, Any]]:
    """Merge journal dumps into one HLC-ordered timeline, dropping
    exact duplicates (one node's journal seen via two sources)."""
    seen: set[tuple[int, int, str, str]] = set()
    merged: list[dict[str, Any]] = []
    for journal in journals:
        for entry in journal:
            if not isinstance(entry, dict) or "hlc" not in entry:
                continue
            key = _hlc_key(entry) + (str(entry.get("kind", "")),)
            if key in seen:
                continue
            seen.add(key)
            merged.append(entry)
    merged.sort(key=_hlc_key)
    return merged


def load_source(path: str) -> list[list[dict[str, Any]]]:
    """One on-disk source → journal dumps. Accepts a ``/debug/bundle``
    snapshot, a raw ``/debug/journal`` response, a bare event list, or
    a durable ``.kepj`` frame file."""
    if path.endswith(".kepj"):
        return [read_frames(path)]
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return [doc]
    if isinstance(doc, dict):
        if isinstance(doc.get("journal"), list):        # bundle
            return [doc["journal"]]
        if isinstance(doc.get("events"), list):         # /debug/journal
            return [doc["events"]]
    raise ValueError(f"{path}: not a bundle, journal dump, or .kepj file")


def fetch_journal(endpoint: str, timeout: float = 10.0,
                  page: int = 512) -> list[dict[str, Any]]:
    """Drain a live replica's ``/debug/journal`` via cursor pagination.
    ``endpoint`` is ``host:port`` (or a full ``http://`` URL prefix)."""
    import urllib.request

    base = (endpoint if endpoint.startswith("http")
            else f"http://{endpoint}")
    events: list[dict[str, Any]] = []
    cursor = ""
    while True:
        url = f"{base}/debug/journal?limit={page}"
        if cursor:
            url += f"&since={cursor}"
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            doc = json.load(resp)
        batch = doc.get("events", [])
        events.extend(batch)
        cursor = doc.get("cursor", "")
        if not batch or not cursor:
            return events


# -- findings ---------------------------------------------------------------


def analyze(merged: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Deterministic pathology scan over the merged timeline."""
    findings: list[dict[str, Any]] = []
    # split-brain: same epoch, conflicting lease holders
    holders: dict[int, dict[str, str]] = {}
    peer_sets: dict[int, dict[str, str]] = {}
    for entry in merged:
        kind = entry.get("kind", "")
        fields = entry.get("fields", {}) or {}
        node = str((entry.get("hlc") or {}).get("node", ""))
        if kind == "lease.adopt" and "epoch" in fields:
            holders.setdefault(int(fields["epoch"]), {})[node] = str(
                fields.get("holder", ""))
        elif kind == "membership.apply" and "epoch" in fields:
            peers = ",".join(sorted(fields.get("peers", []) or []))
            peer_sets.setdefault(int(fields["epoch"]), {})[node] = peers
    for epoch in sorted(holders):
        views = holders[epoch]
        if len(set(views.values())) > 1:
            findings.append({
                "finding": "split_brain_lease", "epoch": epoch,
                "holders": {n: views[n] for n in sorted(views)}})
    for epoch in sorted(peer_sets):
        views = peer_sets[epoch]
        if len(set(views.values())) > 1:
            findings.append({
                "finding": "split_brain_membership", "epoch": epoch,
                "views": {n: views[n] for n in sorted(views)}})
    # flapping: breaker / rung oscillation per node inside the window
    for family, kinds in (("breaker", ("breaker.open", "breaker.close")),
                          ("rung", ("rung.transition",))):
        per_node: dict[str, list[int]] = {}
        for entry in merged:
            if entry.get("kind") in kinds:
                node = str((entry.get("hlc") or {}).get("node", ""))
                per_node.setdefault(node, []).append(
                    int(entry["hlc"]["phys_us"]))
        for node in sorted(per_node):
            stamps = per_node[node]
            window_us = int(_FLAP_WINDOW_S * 1e6)
            for i in range(len(stamps) - _FLAP_N + 1):
                if stamps[i + _FLAP_N - 1] - stamps[i] <= window_us:
                    findings.append({
                        "finding": f"{family}_flap", "node": node,
                        "transitions": _FLAP_N,
                        "window_s": _FLAP_WINDOW_S})
                    break
    return findings


# -- renders ----------------------------------------------------------------


def render_text(merged: list[dict[str, Any]],
                findings: list[dict[str, Any]]) -> str:
    lines: list[str] = []
    base_us = merged[0]["hlc"]["phys_us"] if merged else 0
    for entry in merged:
        h = entry["hlc"]
        rel = (h["phys_us"] - base_us) / 1e6
        kv = " ".join(f"{k}={entry['fields'][k]}"
                      for k in sorted(entry.get("fields", {})))
        lines.append(f"+{rel:10.3f}s .{h['logical']:<3d} "
                     f"[{h['node']}] {entry['kind']} {kv}".rstrip())
    lines.append(f"-- {len(merged)} events, {len(findings)} findings")
    for f in findings:
        kv = " ".join(f"{k}={f[k]}" for k in sorted(f)
                      if k != "finding")
        lines.append(f"!! {f['finding']} {kv}".rstrip())
    return "\n".join(lines) + "\n"


def chrome_trace(merged: list[dict[str, Any]]) -> dict[str, Any]:
    """Chrome trace-event render: instant events on the HLC physical-µs
    axis, one track per node — loads in Perfetto beside /debug/traces'
    span export (both use wall-clock µs timestamps)."""
    nodes = sorted({str(e["hlc"]["node"]) for e in merged})
    pid_of = {n: i + 1 for i, n in enumerate(nodes)}
    events: list[dict[str, Any]] = []
    for node in nodes:
        events.append({"name": "process_name", "ph": "M",
                       "pid": pid_of[node], "tid": 0,
                       "args": {"name": node or "(unnamed)"}})
    for entry in merged:
        h = entry["hlc"]
        events.append({
            "name": entry["kind"], "ph": "i", "s": "p",
            "cat": "kepler-blackbox", "ts": h["phys_us"],
            "pid": pid_of[str(h["node"])], "tid": 0,
            "args": dict(entry.get("fields", {}))})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def timeline_sha256(merged: list[dict[str, Any]],
                    findings: list[dict[str, Any]]) -> str:
    return hashlib.sha256(canonical_json(
        {"schema": SCHEMA, "events": merged,
         "findings": findings})).hexdigest()
