"""``python -m kepler_tpu.blackbox`` — reconstruct the fleet timeline.

Sources are positional and mixed freely:

- an incident bundle file (``/debug/bundle`` snapshot),
- a raw ``/debug/journal`` response or bare event-list JSON,
- a durable ``.kepj`` spool file,
- a live replica ``host:port`` (fetched over HTTP with cursor
  pagination; anything that is not an existing file is treated as an
  endpoint).

Output (``--format``): ``text`` (human timeline + findings), ``json``
(canonical — byte-deterministic, the ``make blackbox`` SHA-256 pin), or
``trace`` (Chrome trace events; load in Perfetto beside /debug/traces).
``--sha`` prints only the timeline SHA-256.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

from kepler_tpu.blackbox import (
    SCHEMA,
    analyze,
    chrome_trace,
    fetch_journal,
    load_source,
    merge_events,
    render_text,
    timeline_sha256,
)
from kepler_tpu.fleet.journal import canonical_json


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kepler_tpu.blackbox", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("sources", nargs="+",
                        help="bundle/journal/.kepj files or live "
                             "host:port endpoints")
    parser.add_argument("--format", choices=("text", "json", "trace"),
                        default="text")
    parser.add_argument("--sha", action="store_true",
                        help="print only the merged-timeline SHA-256")
    parser.add_argument("--timeout", type=float, default=10.0,
                        help="HTTP timeout for live endpoints")
    args = parser.parse_args(argv)

    journals: list[list[dict[str, Any]]] = []
    for src in args.sources:
        try:
            if os.path.exists(src):
                journals.extend(load_source(src))
            else:
                journals.append(fetch_journal(src, timeout=args.timeout))
        except (OSError, ValueError) as err:
            print(f"error: {src}: {err}", file=sys.stderr)
            return 1
    merged = merge_events(journals)
    findings = analyze(merged)
    if args.sha:
        print(timeline_sha256(merged, findings))
        return 0
    if args.format == "text":
        sys.stdout.write(render_text(merged, findings))
    elif args.format == "json":
        sys.stdout.buffer.write(canonical_json(
            {"schema": SCHEMA, "events": merged,
             "findings": findings}))
        sys.stdout.write("\n")
    else:
        json.dump(chrome_trace(merged), sys.stdout)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
