"""Fake CPU power meter for dev/test.

Reference parity: ``internal/device/fake_cpu_power_meter.go`` — synthetic
monotonic zones whose counters advance by a random increment per read and
wrap at 1 MJ; enabled via ``dev.fake-cpu-meter`` config (never a CLI flag).

Determinism: pass a seeded ``random.Random`` for reproducible tests; the
increment scales with elapsed wall time so derived power is plausible.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Sequence

from kepler_tpu.device.energy import JOULE, Energy
from kepler_tpu.device.meter import EnergyZone, zone_rank

FAKE_MAX_ENERGY = 1_000_000 * JOULE  # 1 MJ wrap point (reference :30)
DEFAULT_FAKE_ZONES = ("package", "core", "dram", "uncore")


class FakeEnergyZone:
    """Monotonic synthetic counter (reference fakeEnergyZone, :52-60)."""

    def __init__(self, name: str, index: int = 0,
                 rng: random.Random | None = None,
                 watts_range: tuple[float, float] = (5.0, 50.0)) -> None:
        self._name = name
        self._index = index
        self._rng = rng or random.Random()
        self._watts_range = watts_range
        self._counter = self._rng.randrange(0, FAKE_MAX_ENERGY)
        self._last_read = time.monotonic()
        self._lock = threading.Lock()

    def name(self) -> str:
        return self._name

    def index(self) -> int:
        return self._index

    def path(self) -> str:
        return f"fake://{self._name}"

    def max_energy(self) -> Energy:
        return Energy(FAKE_MAX_ENERGY)

    def energy(self) -> Energy:
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._last_read, 1e-3)
            self._last_read = now
            watts = self._rng.uniform(*self._watts_range)
            self._counter = int(
                (self._counter + watts * dt * JOULE) % FAKE_MAX_ENERGY
            )
            return Energy(self._counter)


class FakeCPUMeter:
    def __init__(self, zones: Sequence[str] = (), seed: int | None = None):
        names = list(zones) or list(DEFAULT_FAKE_ZONES)
        rng = random.Random(seed)
        self._zones: list[EnergyZone] = [
            FakeEnergyZone(n, i, random.Random(rng.random()))
            for i, n in enumerate(names)
        ]

    def name(self) -> str:
        return "fake-cpu-meter"

    def init(self) -> None:
        pass

    def zones(self) -> Sequence[EnergyZone]:
        return self._zones

    def primary_energy_zone(self) -> EnergyZone:
        return min(self._zones, key=lambda z: (zone_rank(z.name()), z.name()))
