"""Device layer: hardware energy meters (reference ``internal/device/``)."""

from kepler_tpu.device.aggregated import AggregatedZone
from kepler_tpu.device.energy import Energy, Power
from kepler_tpu.device.fake import FakeCPUMeter, FakeEnergyZone
from kepler_tpu.device.meter import (
    CPUPowerMeter,
    EnergyZone,
    ZONE_PRIORITY,
    zone_rank,
)
from kepler_tpu.device.rapl import RaplPowerMeter, SysfsRaplZone

__all__ = [
    "AggregatedZone",
    "CPUPowerMeter",
    "Energy",
    "EnergyZone",
    "FakeCPUMeter",
    "FakeEnergyZone",
    "Power",
    "RaplPowerMeter",
    "SysfsRaplZone",
    "ZONE_PRIORITY",
    "zone_rank",
]
