"""Hardware-meter abstractions.

Reference parity: ``internal/device/cpu_power_meter.go:10-40`` — a power meter
exposes named ``EnergyZone``s with monotonically-increasing, wrapping µJ
counters, and designates one "primary" zone used for terminated-workload
ranking (priority psys > package > core > dram > uncore,
``rapl_sysfs_power_meter.go:197-231``).
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable

from kepler_tpu.device.energy import Energy


@runtime_checkable
class EnergyZone(Protocol):
    """One measurable energy domain (e.g. RAPL package/core/dram)."""

    def name(self) -> str: ...
    def index(self) -> int: ...
    def path(self) -> str: ...
    def energy(self) -> Energy:
        """Current cumulative counter in µJ; wraps at ``max_energy()``."""
        ...
    def max_energy(self) -> Energy:
        """Wraparound point of the counter (``max_energy_range_uj``)."""
        ...


@runtime_checkable
class CPUPowerMeter(Protocol):
    def name(self) -> str: ...
    def zones(self) -> Sequence[EnergyZone]: ...
    def primary_energy_zone(self) -> EnergyZone:
        """Highest-priority zone representing overall package energy."""
        ...


# Zone-name priority for primary-zone selection (reference
# rapl_sysfs_power_meter.go:197-231). Lower rank = higher priority.
ZONE_PRIORITY = ("psys", "package", "core", "dram", "uncore")


def zone_rank(zone_name: str) -> int:
    """Rank of a zone name for primary selection; unknown names rank last.

    Package zones appear as "package-0"/"package-1" in sysfs — match by
    prefix, case-insensitive.
    """
    lowered = zone_name.lower()
    for i, prio in enumerate(ZONE_PRIORITY):
        if lowered == prio or lowered.startswith(prio + "-"):
            return i
    return len(ZONE_PRIORITY)
