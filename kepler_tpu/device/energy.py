"""Energy and Power unit types.

Reference parity: ``internal/device/energy.go:14,41`` — ``Energy`` is a uint64
microjoule counter, ``Power`` a float64 microwatt value, with display helpers.

TPU-first note: these wrappers are *host-side* bookkeeping types. On device,
energy deltas travel as float32 arrays in microjoules (a 5 s RAPL delta is
< 2^30 µJ, so f32 keeps ~1e-7 relative error) while cumulative accumulators
stay in numpy int64/float64 on the host to avoid TPU f64 emulation.
"""

from __future__ import annotations

# Unit constants, µJ-denominated (reference energy.go:16-20).
MICRO_JOULE = 1
MILLI_JOULE = 1_000 * MICRO_JOULE
JOULE = 1_000 * MILLI_JOULE
KILO_JOULE = 1_000 * JOULE

# µW-denominated (reference energy.go:43-47).
MICRO_WATT = 1.0
MILLI_WATT = 1_000 * MICRO_WATT
WATT = 1_000 * MILLI_WATT
KILO_WATT = 1_000 * WATT


class Energy(int):
    """A cumulative energy counter in microjoules.

    Subclasses ``int`` so arithmetic/wraparound math stays exact (the
    reference uses uint64; Python ints are unbounded, wraparound is handled
    explicitly where counters wrap — see ``kepler_tpu.ops.deltas``).
    """

    __slots__ = ()

    @property
    def micro_joules(self) -> int:
        return int(self)

    @property
    def joules(self) -> float:
        return int(self) / JOULE

    def __str__(self) -> str:  # reference energy.go String(): "1.23J"
        return f"{self.joules:.2f}J"


class Power(float):
    """Instantaneous power in microwatts (reference energy.go:41)."""

    __slots__ = ()

    @property
    def micro_watts(self) -> float:
        return float(self)

    @property
    def watts(self) -> float:
        return float(self) / WATT

    def __str__(self) -> str:
        return f"{self.watts:.2f}W"
