"""AggregatedZone: N same-named zones (multi-socket) as one logical zone.

Reference parity: ``internal/device/energy_zone.go:47-155`` — per-subzone
wraparound handling, combined ``max_energy`` with overflow clamp, and a lock
so concurrent readers see consistent state.

The aggregate counter is the *sum of per-zone deltas* accumulated since the
first read — each subzone's wrap is detected and corrected independently
(a subzone wrapping must not make the aggregate jump backwards).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Sequence

_log = logging.getLogger("kepler.device")

from kepler_tpu.device.energy import Energy
from kepler_tpu.device.meter import EnergyZone

_UINT64_MAX = 2**64 - 1


class AggregatedZone:
    def __init__(self, zones: Sequence[EnergyZone]) -> None:
        if not zones:
            raise ValueError("AggregatedZone requires at least one zone")
        self._zones = list(zones)
        self._name = zones[0].name()
        self._lock = threading.Lock()
        self._last: dict[int, int] = {}  # per-zone previous raw reading
        self._warn_logged = float("-inf")  # stale-read warning throttle
        self._total: int = 0  # accumulated aggregate µJ
        self._path_counts: list[int] | None = None  # per-subzone, cached

    def name(self) -> str:
        return self._name

    def index(self) -> int:
        return -1  # aggregated zones have no single hardware index

    def path(self) -> str:
        return ""

    def max_energy(self) -> Energy:
        total = 0
        for z in self._zones:
            total += int(z.max_energy())
            if total > _UINT64_MAX:  # overflow clamp (energy_zone.go:152)
                return Energy(_UINT64_MAX)
        return Energy(total)

    def energy(self) -> Energy:
        # subzone reads happen INSIDE the lock: an interleaved pair of
        # readers could otherwise regress a subzone counter and fake a
        # wraparound (the documented concurrent-reader guarantee)
        with self._lock:
            return self._combine_locked([int(z.energy())
                                         for z in self._zones])

    def _combine_locked(self, currents: Sequence[int]) -> Energy:
        for i, (z, current) in enumerate(zip(self._zones, currents)):
            if i in self._last:
                prev = self._last[i]
                if current >= prev:
                    delta = current - prev
                elif prev - current > int(z.max_energy()) // 2:
                    # genuine wraparound: counters wrap from near-max to
                    # near-zero, so the regression spans most of the range
                    delta = (int(z.max_energy()) - prev) + current
                else:
                    # small regression = a stale reading (e.g. a batched
                    # raw value sampled before a concurrent energy() call
                    # advanced _last) — counting it as a wrap would inject
                    # ~max_energy of phantom µJ; skip the window instead.
                    # Ambiguity caveat: a GENUINE wrap where the subzone
                    # accumulated more than max_energy/2 between reads
                    # (~430 W sustained on a 2^32 µJ zone at a 5 s
                    # interval) is indistinguishable and also lands here,
                    # undercounting one wrap — hence the (throttled)
                    # warning, so sustained-high-power fleets can detect
                    # the miscount. Concurrent-reader races hit this
                    # branch benignly, so throttle to one line per 30 s.
                    now = time.monotonic()
                    if now - self._warn_logged >= 30.0:
                        self._warn_logged = now
                        _log.warning(
                            "zone %s subzone %d: counter regressed %d µJ "
                            "(< half max_energy %d); treating as stale "
                            "read and dropping the window — if this node "
                            "sustains >max_energy/2 per interval, raise "
                            "the read rate", self._name, i,
                            prev - current, int(z.max_energy()))
                    delta = 0
                    current = prev  # keep the newer reading as the anchor
                self._total += delta
            else:
                # First read seeds the aggregate at the sum of current
                # readings so restarts resume from hardware counters.
                self._total += current
            self._last[i] = current
        # The aggregate itself wraps at combined max_energy so downstream
        # wraparound math (ops.deltas) stays uniform across zone kinds.
        max_e = int(self.max_energy())
        if max_e and self._total >= max_e:
            self._total %= max_e
        return Energy(self._total)

    # -- batched-read support (native fast path) ---------------------------

    def energy_paths(self) -> list[str]:
        """Concatenated subzone counter files (order matches
        :meth:`energy_from_raw`'s expectation). Raises AttributeError when
        a subzone can't be batch-read — callers treat that as 'no fast
        path' and fall back to :meth:`energy`."""
        per_zone = [z.energy_paths() for z in self._zones]
        if self._path_counts is None:
            self._path_counts = [len(p) for p in per_zone]
        return [p for zone_paths in per_zone for p in zone_paths]

    def energy_from_raw(self, values: Sequence[int]) -> Energy:
        """Combine raw batch-read subzone values with the same per-subzone
        wraparound handling as :meth:`energy`.

        The values were read OUTSIDE the lock (one native call covering
        every zone) — safe because batched reads come only from the
        monitor's single refresh task (singleflight); the lock still
        serialises against any concurrent :meth:`energy` caller."""
        if self._path_counts is None:
            self.energy_paths()  # populate the per-subzone counts once
        currents = []
        offset = 0
        for z, n in zip(self._zones, self._path_counts):
            currents.append(int(z.energy_from_raw(values[offset:offset + n])))
            offset += n
        with self._lock:
            return self._combine_locked(currents)
