"""MSR RAPL power meter — the fallback the reference only proposed.

Implements `/root/reference/docs/developer/proposal/
EP-002-MSR-Fallback-Power-Meter.md`: when the powercap sysfs tree is
unavailable (disabled kernels, restricted containers) but the MSR device
files are, read the RAPL energy counters straight from the CPU registers:

    UNIT   0x606  IA32_RAPL_POWER_UNIT   (bits 12:8 = energy-status unit:
                                          1 / 2^ESU joules per count)
    PKG    0x611  MSR_PKG_ENERGY_STATUS
    PP0    0x639  MSR_PP0_ENERGY_STATUS  → "core"
    DRAM   0x619  MSR_DRAM_ENERGY_STATUS
    PP1    0x641  MSR_PP1_ENERGY_STATUS  → "uncore"

Counters are 32-bit and wrap at 2^32 counts; values convert to µJ via the
unit register so the monitor's wraparound delta math works unchanged
(``max_energy`` = 2^32 counts in µJ). Multi-socket CPUs read each
package's lowest-numbered CPU's MSR device and aggregate same-named
zones via :class:`AggregatedZone` — identical zone semantics to the
sysfs meter, so everything downstream (primary-zone priority, the jitted
attribution, exporters) is unaware of the backend.

SECURITY: MSR access enables PLATYPUS-class attacks (CVE-2020-8694/95);
the backend is strictly opt-in (``device.msr.enabled``, YAML-only — no
CLI flag, per the proposal) and logs a warning when it activates.
"""

from __future__ import annotations

import logging
import os
import re
import struct
from collections import defaultdict
from typing import Sequence

from kepler_tpu.device.aggregated import AggregatedZone
from kepler_tpu.device.energy import Energy
from kepler_tpu.device.meter import EnergyZone, zone_rank

log = logging.getLogger("kepler.device.msr")

MSR_RAPL_POWER_UNIT = 0x606
_ENERGY_MSRS = (
    # (register, zone name stem) — names match the sysfs meter's so the
    # primary-zone priority and metric labels are backend-independent
    (0x611, "package"),
    (0x639, "core"),
    (0x619, "dram"),
    (0x641, "uncore"),
)
_COUNTER_BITS = 32
_CPU_DIR_RE = re.compile(r"^\d+$")


def read_msr(path: str, register: int) -> int:
    """One 8-byte little-endian read of ``register`` from an MSR device."""
    fd = os.open(path, os.O_RDONLY)
    try:
        raw = os.pread(fd, 8, register)
    finally:
        os.close(fd)
    if len(raw) != 8:
        raise OSError(f"short MSR read from {path}@{register:#x}")
    return struct.unpack("<Q", raw)[0]


def energy_unit_uj(unit_raw: int) -> float:
    """µJ per counter unit from IA32_RAPL_POWER_UNIT bits 12:8."""
    esu = (unit_raw >> 8) & 0x1F
    return 1e6 / (1 << esu)


class MsrZone:
    """One energy MSR on one package (reference proposal §3)."""

    def __init__(self, msr_path: str, register: int, name: str,
                 package: int, unit_uj: float) -> None:
        self._path = msr_path
        self._register = register
        self._name = name
        self._package = package
        self._unit_uj = unit_uj

    def name(self) -> str:
        return self._name

    def index(self) -> int:
        return self._package

    def path(self) -> str:
        return f"{self._path}#{self._register:#x}"

    def energy(self) -> Energy:
        raw = read_msr(self._path, self._register) & ((1 << _COUNTER_BITS)
                                                      - 1)
        return Energy(int(raw * self._unit_uj))

    def max_energy(self) -> Energy:
        return Energy(int((1 << _COUNTER_BITS) * self._unit_uj))


def _package_of_cpu(topology_root: str, cpu: int) -> int:
    path = os.path.join(topology_root, f"cpu{cpu}", "topology",
                        "physical_package_id")
    try:
        with open(path, encoding="ascii") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0  # single-package fallback (also: minimal fake trees)


class MsrPowerMeter:
    """CPUPowerMeter over ``/dev/cpu/*/msr`` (reference proposal EP-002).

    ``device_path``: the MSR device tree (``/dev/cpu``).
    ``topology_path``: sysfs CPU topology root used to find one CPU per
    package (``/sys/devices/system/cpu``); injectable for fake trees.
    """

    def __init__(self, device_path: str = "/dev/cpu",
                 topology_path: str = "/sys/devices/system/cpu",
                 zone_filter: Sequence[str] = ()) -> None:
        self._device_path = device_path
        self._topology_path = topology_path
        self._filter = {z.lower() for z in zone_filter}
        self._zones: list[EnergyZone] = []
        self._primary: EnergyZone | None = None

    def name(self) -> str:
        return "rapl-msr"

    @staticmethod
    def available(device_path: str = "/dev/cpu") -> bool:
        """Any readable MSR device present? (the fallback predicate)"""
        try:
            for entry in os.listdir(device_path):
                if _CPU_DIR_RE.match(entry):
                    msr = os.path.join(device_path, entry, "msr")
                    if os.path.exists(msr) and os.access(msr, os.R_OK):
                        return True
        except OSError:
            pass
        return False

    # -- service lifecycle -------------------------------------------------

    def init(self) -> None:
        log.warning(
            "MSR power meter active: raw MSR reads enable PLATYPUS-class "
            "side channels (CVE-2020-8694/8695) — ensure this node's "
            "threat model allows it (device.msr is opt-in for that reason)")
        self._zones = self._discover()
        if not self._zones:
            raise RuntimeError(
                f"no readable RAPL MSRs under {self._device_path} "
                "(is the msr kernel module loaded and CAP_SYS_RAWIO held?)")
        for z in self._zones:
            z.energy()  # probe readability early
        self._primary = self._select_primary()
        log.info("MSR meter initialized: zones=%s primary=%s",
                 [z.name() for z in self._zones], self._primary.name())

    # -- discovery ---------------------------------------------------------

    def _package_cpus(self) -> dict[int, int]:
        """package id → lowest-numbered CPU with a present MSR device."""
        packages: dict[int, int] = {}
        try:
            entries = sorted((int(e) for e in os.listdir(self._device_path)
                              if _CPU_DIR_RE.match(e)))
        except OSError as err:
            raise RuntimeError(
                f"MSR device tree not found: {self._device_path}") from err
        for cpu in entries:
            if not os.path.exists(os.path.join(self._device_path, str(cpu),
                                               "msr")):
                continue
            pkg = _package_of_cpu(self._topology_path, cpu)
            packages.setdefault(pkg, cpu)
        return packages

    def _discover(self) -> list[EnergyZone]:
        groups: dict[str, list[MsrZone]] = defaultdict(list)
        for pkg, cpu in sorted(self._package_cpus().items()):
            msr_path = os.path.join(self._device_path, str(cpu), "msr")
            try:
                unit_uj = energy_unit_uj(read_msr(msr_path,
                                                  MSR_RAPL_POWER_UNIT))
            except OSError as err:
                log.warning("cannot read power-unit MSR on cpu%d: %s",
                            cpu, err)
                continue
            for register, stem in _ENERGY_MSRS:
                # accept the bare stem OR the exact suffixed spelling for
                # THIS package ("package-0") — matching any "stem-*"
                # would let a 'package-1' filter enable the zone on every
                # socket, diverging from the sysfs meter's
                # canonical_zone_key semantics on multi-socket hosts
                if (self._filter and stem not in self._filter
                        and f"{stem}-{pkg}" not in self._filter):
                    continue
                try:
                    read_msr(msr_path, register)
                except OSError:
                    continue  # register not implemented on this CPU
                groups[stem].append(MsrZone(
                    msr_path, register, f"{stem}-{pkg}", pkg, unit_uj))
        zones: list[EnergyZone] = []
        for stem, members in sorted(groups.items()):
            if len(members) == 1:
                # single socket: drop the -0 suffix like powercap's
                # top-level package naming keeps socket suffixes — keep
                # them for parity with the sysfs meter's aggregation key
                zones.append(members[0])
            else:
                zones.append(AggregatedZone(members))
        return zones

    def _select_primary(self) -> EnergyZone:
        return min(self._zones, key=lambda z: (zone_rank(z.name()), z.name()))

    # -- CPUPowerMeter -----------------------------------------------------

    def zones(self) -> Sequence[EnergyZone]:
        if not self._zones:
            self.init()
        return self._zones

    def primary_energy_zone(self) -> EnergyZone:
        if self._primary is None:
            self.init()
        assert self._primary is not None
        return self._primary
