"""Intel RAPL sysfs power meter.

Reference parity: ``internal/device/rapl_sysfs_power_meter.go`` — dynamic zone
discovery under ``<sysfs>/class/powercap``, optional zone-name filtering
(``rapl.zones`` config), dedup of zones exposing the same name+path shape,
multi-socket aggregation of same-named zones via ``AggregatedZone``, and
primary-zone selection by priority (psys > package > core > dram > uncore).

Layout read (standard Linux powercap):
    /sys/class/powercap/intel-rapl:0/name                → "package-0"
    /sys/class/powercap/intel-rapl:0/energy_uj           → cumulative µJ
    /sys/class/powercap/intel-rapl:0/max_energy_range_uj → wrap point
    /sys/class/powercap/intel-rapl:0:0/...               → subzones (core/dram)
"""

from __future__ import annotations

import logging
import os
import re
from collections import defaultdict
from typing import Sequence

from kepler_tpu.device.aggregated import AggregatedZone
from kepler_tpu.device.energy import Energy
from kepler_tpu.device.meter import EnergyZone, zone_rank

log = logging.getLogger("kepler.device.rapl")

_ZONE_DIR_RE = re.compile(r"^intel-rapl(:\d+)+$")

# sentinel the native batch reader writes for unreadable counter files
_READ_FAILED = 2**64 - 1


class SysfsRaplZone:
    """A single powercap zone directory (reference sysfsRaplZone, :259-287)."""

    def __init__(self, path: str) -> None:
        self._path = path
        with open(os.path.join(path, "name"), encoding="ascii") as f:
            self._name = f.read().strip()
        # index = last numeric component of the dir name (intel-rapl:0:1 → 1)
        base = os.path.basename(path)
        self._index = int(base.rsplit(":", 1)[-1])
        self._max_energy = self._read_int("max_energy_range_uj")

    def _read_int(self, filename: str) -> int:
        with open(os.path.join(self._path, filename), encoding="ascii") as f:
            return int(f.read().strip())

    def name(self) -> str:
        return self._name

    def index(self) -> int:
        return self._index

    def path(self) -> str:
        return self._path

    def energy(self) -> Energy:
        return Energy(self._read_int("energy_uj"))

    def max_energy(self) -> Energy:
        return Energy(self._max_energy)

    # -- batched-read support (native fast path) ---------------------------

    def energy_paths(self) -> list[str]:
        """Counter files backing this zone — lets the monitor batch ALL
        zones' reads into one native call (native.read_counters)."""
        return [os.path.join(self._path, "energy_uj")]

    def energy_from_raw(self, values: Sequence[int]) -> Energy:
        """Interpret raw values batch-read from :meth:`energy_paths`."""
        (v,) = values
        if v == _READ_FAILED:
            raise OSError(f"batched read of {self._path}/energy_uj failed")
        return Energy(int(v))


def canonical_zone_key(name: str) -> str:
    """Normalize multi-socket names: package-0/package-1 → package.

    Grouping key for aggregation (reference groupZonesByName, :157).
    """
    lowered = name.lower()
    return re.sub(r"-\d+$", "", lowered)


class RaplPowerMeter:
    """Reads energy from Intel RAPL via sysfs (reference raplPowerMeter)."""

    def __init__(self, sysfs_path: str = "/sys",
                 zone_filter: Sequence[str] = ()) -> None:
        self._powercap = os.path.join(sysfs_path, "class", "powercap")
        self._filter = {z.lower() for z in zone_filter}
        self._zones: list[EnergyZone] = []
        self._primary: EnergyZone | None = None

    def name(self) -> str:
        return "rapl"

    # -- service lifecycle ------------------------------------------------

    def init(self) -> None:
        """Probe zones and take a first reading (reference Init, :76)."""
        self._zones = self._discover()
        if not self._zones:
            raise RuntimeError(
                f"no RAPL zones found under {self._powercap} "
                "(is intel-rapl available? try dev.fake-cpu-meter for dev)"
            )
        for z in self._zones:
            z.energy()  # probe readability early
        self._primary = self._select_primary()
        log.info("RAPL meter initialized: zones=%s primary=%s",
                 [z.name() for z in self._zones], self._primary.name())

    # -- discovery --------------------------------------------------------

    def _discover(self) -> list[EnergyZone]:
        if not os.path.isdir(self._powercap):
            raise RuntimeError(f"powercap sysfs not found: {self._powercap}")
        raw: list[SysfsRaplZone] = []
        seen_paths: set[str] = set()
        for entry in sorted(os.listdir(self._powercap)):
            if not _ZONE_DIR_RE.match(entry):
                continue
            path = os.path.realpath(os.path.join(self._powercap, entry))
            if path in seen_paths:  # dedup non-standard symlinked paths
                continue
            seen_paths.add(path)
            try:
                raw.append(SysfsRaplZone(path))
            except (OSError, ValueError) as err:
                log.warning("skipping unreadable zone %s: %s", entry, err)
        if self._filter:
            raw = [z for z in raw
                   if canonical_zone_key(z.name()) in self._filter
                   or z.name().lower() in self._filter]
        # multi-socket aggregation: same canonical name → one logical zone
        groups: dict[str, list[SysfsRaplZone]] = defaultdict(list)
        for z in raw:
            groups[canonical_zone_key(z.name())].append(z)
        zones: list[EnergyZone] = []
        for _, members in sorted(groups.items()):
            if len(members) == 1:
                zones.append(members[0])
            else:
                zones.append(AggregatedZone(members))
        return zones

    def _select_primary(self) -> EnergyZone:
        return min(self._zones, key=lambda z: (zone_rank(z.name()), z.name()))

    # -- CPUPowerMeter ----------------------------------------------------

    def zones(self) -> Sequence[EnergyZone]:
        if not self._zones:
            self.init()
        return self._zones

    def primary_energy_zone(self) -> EnergyZone:
        if self._primary is None:
            self.init()
        assert self._primary is not None
        return self._primary
