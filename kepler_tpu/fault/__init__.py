"""Fault injection: seedable, config-wired chaos harness for resilience
tests and soak runs (see docs/developer/resilience.md)."""

from kepler_tpu.fault.plan import (
    KNOWN_SITES,
    SITE_CATALOG,
    FaultPlan,
    FaultSpec,
    active,
    fire,
    install,
    install_from_config,
    installed,
    uninstall,
)

__all__ = [
    "KNOWN_SITES",
    "SITE_CATALOG",
    "FaultPlan",
    "FaultSpec",
    "active",
    "fire",
    "install",
    "install_from_config",
    "installed",
    "uninstall",
]
