"""Seedable fault-injection registry (chaos harness).

The reference hardens every layer against partial failure (skip-on-error
zone reads, rollback-on-init-failure, degrade-gracefully exporters) but
offers no way to *exercise* those paths deterministically. This module is
that way: a ``FaultPlan`` holds a set of ``FaultSpec`` entries — each
scoped by probability, fire count, and a time window — and layers consult
it through cheap injection points (``fault.fire("net.refuse")``).

Design constraints:

- **Zero cost when disarmed.** ``fire()`` with no installed plan is one
  module-global read and a ``None`` check — safe to leave in hot paths
  (the monitor's refresh loop, the agent's send path).
- **Deterministic.** All randomness comes from one seeded ``Random``;
  the same plan replays the same fault sequence, so resilience tests
  never flake (ISSUE acceptance: "deterministic (seeded) tests").
- **Inspectable.** Per-site check/fire counters let tests assert not just
  the outcome but that the fault actually happened (and stopped).

Sites are free-form strings but the canonical set is ``KNOWN_SITES``;
``FaultPlan.from_config`` rejects unknown sites so a typo'd YAML plan
fails at startup instead of silently injecting nothing.
"""

from __future__ import annotations

# keplint: monotonic-only — fault windows (start/duration) use elapsed time

import contextlib
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Mapping

log = logging.getLogger("kepler.fault")

# Canonical injection sites: ``(site, consulting layer, effect)``. The
# catalog is the single source of truth — ``KNOWN_SITES`` (the
# validation set), the resilience.md fault-site table
# (``hack/gen_fault_docs.py``), the dead-site fence test, and the
# kepchaos schedule generator are all derived from it, so a site cannot
# be added without its documentation (or documented without a consumer).
SITE_CATALOG: tuple[tuple[str, str, str], ...] = (
    ("device.read_error", "monitor",
     "zone read fails → zone masked this window"),
    ("device.counter_wrap", "monitor",
     "counter forced to `arg` → wraparound-delta path"),
    ("net.refuse", "agent",
     "connect/send raises `ConnectionRefusedError`"),
    ("net.slow", "agent",
     "send stalls `arg` seconds (≤ timeout)"),
    ("net.corrupt_body", "agent",
     "report body truncated → server-side `WireError`"),
    ("report.clock_skew", "agent",
     "`sent_at` stamped `arg` seconds off"),
    ("disk.write_error", "spool",
     "append fails cleanly (no bytes land) → in-memory fallback"),
    ("disk.fsync_error", "spool",
     "fsync fails; record stays in page cache, counted"),
    ("disk.torn_tail", "spool",
     "partial frame written, append raises — kill -9 mid-write stand-in"),
    ("telemetry.drop", "telemetry",
     "a completed cycle trace is dropped before the ring buffer"),
    # device-plane window leg (aggregator degradation ladder,
    # docs/developer/resilience.md "Device-plane faults")
    ("device.dispatch_error", "window",
     "the XLA dispatch raises → ladder demotion"),
    ("device.compile_error", "window",
     "a cold program/update compile fails (fires before the cache entry "
     "lands)"),
    ("device.oom_on_grow", "window",
     "a bucket-growth recompile OOMs"),
    ("device.stall", "window",
     "the fetch hangs `arg` seconds → dispatch-timeout demotion"),
    # HA ingest tier (consistent-hash replicated aggregators,
    # docs/developer/resilience.md "Ingest hand-off")
    ("net.partition", "agent",
     "one-way partition: report delivered, response dropped → "
     "re-delivery, dedup absorbs"),
    ("replica.down", "aggregator",
     "ingest answers 503 (dying replica) → agent failover + spool"),
    # overload control (admission + shedding,
    # docs/developer/resilience.md "Overload and backpressure")
    ("net.throttle", "agent",
     "send answered 429 with `arg` as Retry-After → throttle path (no "
     "breaker/failover)"),
    ("aggregator.ingest_slow", "aggregator",
     "ingest stalls `arg` seconds → latency EWMA climbs, admission "
     "sheds"),
)

KNOWN_SITES: tuple[str, ...] = tuple(s for s, _, _ in SITE_CATALOG)


@dataclass(frozen=True)
class FaultSpec:
    """One scoped fault.

    ``probability`` gates each eligible check; ``skip`` lets the first N
    eligible checks pass untouched (e.g. "refuse the 3rd connect");
    ``count`` caps total fires (None = unlimited); ``start``/``duration``
    bound the window in seconds since the plan was armed; ``arg`` is a
    site-specific magnitude (seconds of delay for ``net.slow``, seconds
    of skew for ``report.clock_skew``, forced counter value for
    ``device.counter_wrap``).
    """

    site: str
    probability: float = 1.0
    count: int | None = None
    skip: int = 0
    start: float = 0.0
    duration: float | None = None
    arg: float | None = None

    def __post_init__(self) -> None:
        if not self.site or not isinstance(self.site, str):
            raise ValueError("fault spec needs a site")
        # type-check before range-check: a YAML typo like `arg: fast` must
        # be a startup ValueError, never a TypeError escaping validation or
        # a crash inside an injection point at fire time
        def _num(name: str, value: Any, allow_none: bool = False) -> None:
            if value is None and allow_none:
                return
            if isinstance(value, bool) or not isinstance(
                    value, (int, float)):
                raise ValueError(
                    f"{self.site}: {name} must be a number, "
                    f"got {value!r}")

        _num("probability", self.probability)
        _num("count", self.count, allow_none=True)
        _num("skip", self.skip)
        _num("start", self.start)
        _num("duration", self.duration, allow_none=True)
        _num("arg", self.arg, allow_none=True)
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"{self.site}: probability must be in [0, 1], "
                f"got {self.probability}")
        if self.count is not None and self.count < 0:
            raise ValueError(f"{self.site}: count must be >= 0")
        if self.skip < 0:
            raise ValueError(f"{self.site}: skip must be >= 0")
        if self.duration is not None and self.duration < 0:
            raise ValueError(f"{self.site}: duration must be >= 0")


class _SpecState:
    __slots__ = ("spec", "seen", "fired")

    def __init__(self, spec: FaultSpec) -> None:
        self.spec = spec
        self.seen = 0   # eligible checks observed (drives `skip`)
        self.fired = 0  # faults actually injected (drives `count`)


class FaultPlan:
    """A seeded registry of scoped faults, consulted via :meth:`fire`.

    Thread-safe: injection points run on monitor/agent/server threads
    concurrently; all spec state and the RNG live behind one lock (the
    disarmed fast path never takes it — see module-level :func:`fire`).
    """

    def __init__(self, specs: Iterable[FaultSpec] = (), seed: int = 0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._clock = clock
        self._armed_at = clock()
        self._specs: dict[str, list[_SpecState]] = {}
        self.checks: dict[str, int] = {}
        self.fires: dict[str, int] = {}
        for spec in specs:
            self.add(spec)

    def add(self, spec: FaultSpec) -> "FaultPlan":
        with self._lock:
            self._specs.setdefault(spec.site, []).append(_SpecState(spec))
        return self

    def sites(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(self._specs)

    def fire(self, site: str) -> FaultSpec | None:
        """One injection-point check: returns the spec that fires (first
        match in registration order) or None. Never raises."""
        with self._lock:
            self.checks[site] = self.checks.get(site, 0) + 1
            states = self._specs.get(site)
            if not states:
                return None
            elapsed = self._clock() - self._armed_at
            for st in states:
                spec = st.spec
                if elapsed < spec.start:
                    continue
                if (spec.duration is not None
                        and elapsed > spec.start + spec.duration):
                    continue
                if spec.count is not None and st.fired >= spec.count:
                    continue
                st.seen += 1
                if st.seen <= spec.skip:
                    continue
                if spec.probability < 1.0 \
                        and self._rng.random() >= spec.probability:
                    continue
                st.fired += 1
                self.fires[site] = self.fires.get(site, 0) + 1
                return spec
        return None

    def fired(self, site: str) -> int:
        with self._lock:
            return self.fires.get(site, 0)

    def checked(self, site: str) -> int:
        with self._lock:
            return self.checks.get(site, 0)

    def stats(self) -> dict[str, dict[str, int]]:
        """{site: {checks, fires}} — for /healthz details and test asserts."""
        with self._lock:
            sites = set(self.checks) | set(self.fires) | set(self._specs)
            return {s: {"checks": self.checks.get(s, 0),
                        "fires": self.fires.get(s, 0)} for s in sites}

    @classmethod
    def from_config(cls, cfg: Any) -> "FaultPlan":
        """Build from a ``FaultConfig`` (config.py): ``specs`` is a list of
        mappings with a required ``site`` key plus any FaultSpec field.
        Unknown sites/keys fail loudly — a typo'd chaos plan must not
        silently inject nothing."""
        specs: list[FaultSpec] = []
        for i, raw in enumerate(cfg.specs):
            if not isinstance(raw, Mapping):
                raise ValueError(f"fault.specs[{i}] must be a mapping")
            allowed = {"site", "probability", "count", "skip", "start",
                       "duration", "arg"}
            unknown = set(raw) - allowed
            if unknown:
                raise ValueError(
                    f"fault.specs[{i}] has unknown keys {sorted(unknown)}")
            site = raw.get("site", "")
            if site not in KNOWN_SITES:
                raise ValueError(
                    f"fault.specs[{i}]: unknown site {site!r}; known: "
                    f"{', '.join(KNOWN_SITES)}")
            try:
                specs.append(FaultSpec(**raw))
            except TypeError as err:  # e.g. count given as a list
                raise ValueError(f"fault.specs[{i}]: {err}") from err
        return cls(specs, seed=cfg.seed)


# -- module-level active plan (the cheap injection-point surface) -----------

_active: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    """Arm a plan process-wide. Layers' injection points start consulting
    it immediately."""
    global _active
    _active = plan
    return plan


def uninstall() -> None:
    global _active
    _active = None


def active() -> FaultPlan | None:
    return _active


def fire(site: str) -> FaultSpec | None:
    """The injection point. Disarmed cost: one global read + None check."""
    plan = _active
    if plan is None:
        return None
    return plan.fire(site)


def install_from_config(cfg: Any) -> FaultPlan | None:
    """Arm the config's chaos plan (``FaultConfig``) at startup; no-op
    when disabled. Shared by both binaries (cmd/main, cmd/aggregator)."""
    if not cfg.enabled:
        return None
    plan = install(FaultPlan.from_config(cfg))
    log.warning("FAULT INJECTION ARMED (seed=%d): %s — exported data is "
                "not trustworthy while a chaos plan is active",
                cfg.seed, ", ".join(plan.sites()))
    return plan


@contextlib.contextmanager
def installed(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Test helper: arm ``plan`` for the duration of a with-block, always
    disarming on exit (a failed assert must not leak faults into the next
    test)."""
    prev = _active
    install(plan)
    try:
        yield plan
    finally:
        if prev is None:
            uninstall()
        else:
            install(prev)
