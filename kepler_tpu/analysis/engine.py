"""keplint engine: AST lint with a rule registry and a baseline ratchet.

The attribution formula is only correct while a handful of code-level
invariants hold everywhere (wrap-aware counter deltas, monotonic clocks in
timing logic, immutable published snapshots, pure jitted kernels, …).
``ruff``/``mypy`` cannot see those — they are *domain* invariants — so this
module is a small, self-contained AST lint engine that can:

- run a registry of :class:`Rule` objects over a file tree
  (:func:`lint_paths`);
- honor inline suppressions (``# keplint: disable=KTL101`` on the
  offending line or the comment line above it, ``# keplint:
  disable-file=KTL101`` anywhere in the file);
- carry per-file/per-function *markers* that scope rules declaratively
  (``# keplint: monotonic-only``, ``# keplint: hot-loop``,
  ``# keplint: guarded-by=_lock`` — see the ``rules/`` package);
- freeze existing violations in a committed baseline so new ones fail
  while old ones ratchet down (:class:`Baseline`), mirroring the
  strict-typing ratchet in ``pyproject.toml``.

No third-party dependencies: stdlib ``ast`` only, so ``make lint`` works
in every container the tests run in.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Sequence

__all__ = [
    "Baseline",
    "DeviceRule",
    "Diagnostic",
    "FileContext",
    "LintResult",
    "ProjectRule",
    "ProtocolRule",
    "REGISTRY",
    "Rule",
    "build_file_context",
    "find_repo_root",
    "lint_paths",
    "register",
]

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# the trees whose membership means anything for rule scoping; paths
# outside them are always fully linted (see Rule.in_scope)
SCOPED_TREES = ("kepler_tpu", "hack", "benchmarks")

# one directive grammar for suppressions AND rule markers; parsed once per
# file so rules never re-scan source text.  The whole-program vocabulary
# (thread-role, taint-*, sanitizes, …) is consumed by analysis/project.py
# and the KTL111-113 rule family.
_DIRECTIVE = re.compile(
    r"#\s*keplint:\s*"
    r"(?P<kind>disable-file|disable|monotonic-only|hot-loop|"
    r"guarded-by|requires-lock|donates|layout-definition|"
    r"thread-role|role-boundary|role-registrar|forbid-role|allow-role|"
    r"taint-source|taint-sink|sanitizes|protocol-transition)"
    r"(?:=(?P<arg>[A-Za-z0-9_,\- ]+))?")


@dataclass(frozen=True, order=True)
class Diagnostic:
    """One finding, stable-ordered for deterministic output."""

    path: str  # repo-relative, posix separators
    line: int
    col: int
    rule_id: str
    severity: str
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule_id} [{self.severity}] {self.message}")

    @property
    def baseline_key(self) -> str:
        return f"{self.path}::{self.rule_id}"


class FileContext:
    """Everything a rule may inspect about one file.

    ``rel_path`` uses posix separators relative to the lint root so rule
    scoping and baselines are machine-independent.
    """

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.Module, root: str = "") -> None:
        self.path = os.path.abspath(path)
        self.rel_path = rel_path
        self.source = source
        self.tree = tree
        self.root = os.path.abspath(root) if root else os.path.dirname(
            self.path)
        self.lines: list[str] = source.splitlines()
        self._walk_nodes: list[ast.AST] | None = None
        # line (1-based) → [(kind, arg-or-None)]; directives come from
        # real COMMENT tokens only, so a docstring QUOTING a directive
        # (this one included) never arms or disarms anything
        self.directives: dict[int, list[tuple[str, str | None]]] = {}
        self.file_directives: set[tuple[str, str | None]] = set()
        for lineno, comment in _iter_comments(source):
            for m in _DIRECTIVE.finditer(comment):
                kind = m.group("kind")
                arg = m.group("arg")
                arg = arg.strip() if arg else None
                self.directives.setdefault(lineno, []).append((kind, arg))
                if kind in ("disable-file", "monotonic-only"):
                    self.file_directives.add((kind, arg))

    @property
    def walk_nodes(self) -> list[ast.AST]:
        """Every AST node of the file, in ``ast.walk`` order, computed
        once and shared by all rules — the tree is walked once per RUN,
        not once per rule (the dominant cost of the old engine)."""
        if self._walk_nodes is None:
            self._walk_nodes = list(ast.walk(self.tree))
        return self._walk_nodes

    # -- marker helpers (rules call these) ---------------------------------

    def has_file_marker(self, kind: str) -> bool:
        return any(k == kind for k, _ in self.file_directives)

    def marker_on(self, node: ast.AST, kind: str) -> str | None:
        """Directive attached to a statement: on its first line, in the
        contiguous comment block above it, or on any decorator line.
        Returns the directive arg ('' when bare)."""
        lines = {node.lineno}
        for deco in getattr(node, "decorator_list", []):
            lines.add(deco.lineno)
        # walk the comment block directly above the statement (or its
        # first decorator) so several markers can stack one per line
        top = min(lines)
        ln = top - 1
        while 0 < ln <= len(self.lines) and \
                self.lines[ln - 1].strip().startswith("#"):
            lines.add(ln)
            ln -= 1
        for ln in lines:
            for kind_, arg in self.directives.get(ln, []):
                if kind_ == kind:
                    return arg or ""
        return None

    def diag(self, rule: "Rule", node: ast.AST, message: str) -> Diagnostic:
        return Diagnostic(
            path=self.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule_id=rule.id,
            severity=rule.severity,
            message=message,
        )

    # -- suppression -------------------------------------------------------

    def _disabled_rules_at(self, line: int) -> set[str]:
        out: set[str] = set()
        for kind, arg in self.directives.get(line, []):
            if kind == "disable":
                out |= _parse_rule_list(arg)
        return out

    def suppressed(self, diag: Diagnostic) -> bool:
        for kind, arg in self.file_directives:
            if kind == "disable-file":
                ids = _parse_rule_list(arg)
                if "all" in ids or diag.rule_id in ids:
                    return True
        for line in (diag.line, diag.line - 1):
            ids = self._disabled_rules_at(line)
            if not ids:
                continue
            # a same-line directive always applies; a directive on the
            # previous line applies only when that line is comment-only
            if line != diag.line:
                stripped = (self.lines[line - 1].strip()
                            if 0 < line <= len(self.lines) else "")
                if not stripped.startswith("#"):
                    continue
            if "all" in ids or diag.rule_id in ids:
                return True
        return False


def _iter_comments(source: str) -> Iterator[tuple[int, str]]:
    """(lineno, text) for every comment token; tolerant of files whose
    tail fails tokenization (the AST parse already gated syntax)."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError):
        return


def _parse_rule_list(arg: str | None) -> set[str]:
    if not arg:
        return {"all"}
    return {part.strip() for part in arg.split(",") if part.strip()}


class Rule:
    """Base class: subclass, set the class attributes, decorate with
    :func:`register`, implement :meth:`check`."""

    id: str = "KTL000"
    name: str = "unnamed"
    severity: str = SEVERITY_ERROR
    summary: str = ""
    rationale: str = ""
    # top-level tree segments this rule runs over, relative to the lint
    # root.  The attribution invariants live in the package; rules that
    # also police tooling/bench code widen this deliberately (ISSUE 9:
    # KTL101/KTL105 extend to hack/ and benchmarks/).
    tree_scope: tuple[str, ...] = ("kepler_tpu",)

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        raise NotImplementedError

    def in_scope(self, rel_path: str) -> bool:
        head = rel_path.split("/", 1)[0]
        # scoping only partitions the KNOWN trees (hack/ and benchmarks/
        # get a curated rule subset); a path outside all of them — an
        # explicitly linted test file, a scratch script — gets every
        # rule, matching the pre-scoping behavior (a silent all-clear on
        # an explicit path would be a false negative)
        if head not in SCOPED_TREES:
            return True
        return head in self.tree_scope


class ProjectRule(Rule):
    """A rule that needs the whole program: runs once per lint over a
    :class:`~kepler_tpu.analysis.project.ProjectContext` (shared ASTs,
    symbol table, call graph, thread roles) instead of once per file.
    Per-file suppression directives still apply to its diagnostics."""

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        return ()

    def check_project(self, project: "object") -> Iterable[Diagnostic]:
        raise NotImplementedError


class DeviceRule(Rule):
    """A device-tier rule: checks TRACED jaxprs of the registered device
    programs (``kepler_tpu/analysis/device/``), not source files. Runs
    only when the CLI is invoked with ``--device-tier`` (traces cost
    real seconds; the per-file tiers stay instant); registered here so
    the catalog, SARIF driver and ``--list-rules`` stay complete."""

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        return ()

    def check_trace(self, report: "object") -> Iterable[Diagnostic]:
        raise NotImplementedError


class ProtocolRule(Rule):
    """A protocol-tier rule: checks the EXPLORED state space of a
    registered protocol model (``kepler_tpu/analysis/protocol/``), not
    source files. The kepmc explorer walks every interleaving of a
    small fleet through the shipped pure transition code and hands each
    rule the exploration report; a counterexample (minimal event trace)
    becomes the diagnostic body. Runs only when the CLI is invoked with
    ``--protocol-tier`` (exhaustive exploration costs real seconds; the
    per-file tiers stay instant); registered here so the catalog, SARIF
    driver and ``--list-rules`` stay complete."""

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        return ()

    def check_model(self, report: "object") -> Iterable[Diagnostic]:
        raise NotImplementedError


REGISTRY: dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator adding one rule instance to the global registry."""
    rule = cls()
    if rule.id in REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    REGISTRY[rule.id] = rule
    return cls


def all_rules() -> list[Rule]:
    # import deferred so engine/rules have no circular import
    from kepler_tpu.analysis import rules as _rules  # noqa: F401

    return [REGISTRY[rid] for rid in sorted(REGISTRY)]


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    """Outcome of one lint run, before/after baseline application."""

    diagnostics: list[Diagnostic] = field(default_factory=list)
    # violations tolerated by the baseline (reported count only)
    baselined: int = 0
    # baseline entries whose violations have (partly) disappeared —
    # the ratchet: regenerate the baseline to lock in the progress
    stale_entries: list[str] = field(default_factory=list)

    @property
    def failed(self) -> bool:
        return any(d.severity == SEVERITY_ERROR for d in self.diagnostics)


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    seen: set[str] = set()
    for path in paths:
        if os.path.isfile(path):
            real = os.path.realpath(path)
            if real not in seen and path.endswith(".py"):
                seen.add(real)
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if not f.endswith(".py"):
                    continue
                full = os.path.join(root, f)
                real = os.path.realpath(full)
                if real not in seen:
                    seen.add(real)
                    yield full


def find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the directory holding pyproject.toml —
    relative diagnostic paths and the default baseline live there."""
    cur = os.path.abspath(start if os.path.isdir(start)
                          else os.path.dirname(start) or ".")
    start_dir = cur
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return start_dir
        cur = parent


def build_file_context(path: str, root: str) -> "FileContext | Diagnostic":
    """Parse one file into a :class:`FileContext` — the single parse every
    rule (per-file and whole-program) shares for the rest of the run.
    Returns a KTL000 :class:`Diagnostic` when the file cannot be parsed."""
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as err:
        return Diagnostic(path=rel, line=getattr(err, "lineno", 1) or 1,
                          col=1, rule_id="KTL000",
                          severity=SEVERITY_ERROR,
                          message=f"cannot parse: {err}")
    return FileContext(path=path, rel_path=rel, source=source, tree=tree,
                       root=root)


def _check_file(ctx: FileContext, rules: Sequence[Rule]) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for rule in rules:
        if isinstance(rule, ProjectRule) or not rule.in_scope(ctx.rel_path):
            continue
        for diag in rule.check(ctx):
            if not ctx.suppressed(diag):
                out.append(diag)
    return out


def lint_file(path: str, root: str,
              rules: Sequence[Rule] | None = None) -> list[Diagnostic]:
    """All non-suppressed per-file diagnostics for one file (no baseline,
    no whole-program rules — use :func:`lint_paths` for those)."""
    rules = list(rules) if rules is not None else all_rules()
    ctx = build_file_context(path, root)
    if isinstance(ctx, Diagnostic):
        return [ctx]
    return sorted(_check_file(ctx, rules))


def _check_project(ctxs: Sequence[FileContext],
                   rules: Sequence[Rule]) -> list[Diagnostic]:
    """Run the whole-program rules over one ProjectContext spanning
    ``ctxs`` (already-parsed files — nothing is re-read or re-parsed)."""
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    if not project_rules or not ctxs:
        return []
    # deferred import: project.py imports engine types
    from kepler_tpu.analysis.project import ProjectContext

    project = ProjectContext(ctxs)
    by_rel = {ctx.rel_path: ctx for ctx in ctxs}
    out: list[Diagnostic] = []
    for rule in project_rules:
        for diag in rule.check_project(project):
            if not rule.in_scope(diag.path):
                continue
            ctx = by_rel.get(diag.path)
            if ctx is not None and ctx.suppressed(diag):
                continue
            out.append(diag)
    return out


def lint_paths(paths: Sequence[str], root: str | None = None,
               rules: Sequence[Rule] | None = None,
               baseline: "Baseline | None" = None,
               per_file: bool = False) -> LintResult:
    """Lint every .py file under ``paths``; apply ``baseline`` if given.

    Each file is parsed exactly once; the resulting contexts feed both
    the per-file rules and the whole-program (:class:`ProjectRule`)
    analysis.  ``per_file=True`` restricts the whole-program rules to
    one-file ProjectContexts — no cross-module call graph — which is how
    the tests prove the call graph is load-bearing (and what the CLI's
    ``--per-file`` exposes for bisecting findings)."""
    root = root or find_repo_root(paths[0] if paths else ".")
    rules = list(rules) if rules is not None else all_rules()
    diags: list[Diagnostic] = []
    ctxs: list[FileContext] = []
    for path in iter_python_files(paths):
        ctx = build_file_context(path, root)
        if isinstance(ctx, Diagnostic):
            diags.append(ctx)
            continue
        ctxs.append(ctx)
        diags.extend(_check_file(ctx, rules))
    if per_file:
        for ctx in ctxs:
            diags.extend(_check_project([ctx], rules))
    else:
        diags.extend(_check_project(ctxs, rules))
    diags.sort()
    if baseline is None:
        return LintResult(diagnostics=diags)
    return baseline.apply(diags)


# ---------------------------------------------------------------------------
# baseline / ratchet
# ---------------------------------------------------------------------------


class Baseline:
    """Committed violation counts per (file, rule).

    A finding is tolerated while its ``path::rule`` count stays at or
    under the recorded number — so existing debt is frozen, new debt
    fails, and *fixing* debt surfaces the entry as stale (regenerate
    with ``--write-baseline`` to ratchet the ceiling down). Counts, not
    line numbers: unrelated edits that shift lines don't churn the file.
    """

    VERSION = 1

    def __init__(self, counts: dict[str, int] | None = None) -> None:
        self.counts: dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict) or data.get("version") != cls.VERSION:
            raise ValueError(f"unsupported baseline file {path!r}")
        counts = data.get("violations", {})
        if not isinstance(counts, dict) or not all(
                isinstance(k, str) and isinstance(v, int) and v > 0
                for k, v in counts.items()):
            raise ValueError(f"malformed baseline file {path!r}")
        return cls(counts)

    def save(self, path: str) -> None:
        payload = {
            "version": self.VERSION,
            "comment": "keplint ratchet: frozen violation counts per "
                       "path::rule. Fix violations, then regenerate with "
                       "`python -m kepler_tpu.analysis --write-baseline` "
                       "to lower the ceiling. Never raise counts by hand.",
            "violations": {k: self.counts[k] for k in sorted(self.counts)},
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=False)
            f.write("\n")

    @classmethod
    def from_diagnostics(cls, diags: Iterable[Diagnostic]) -> "Baseline":
        counts: dict[str, int] = {}
        for d in diags:
            counts[d.baseline_key] = counts.get(d.baseline_key, 0) + 1
        return cls(counts)

    def apply(self, diags: Sequence[Diagnostic]) -> LintResult:
        by_key: dict[str, list[Diagnostic]] = {}
        for d in diags:
            by_key.setdefault(d.baseline_key, []).append(d)
        new: list[Diagnostic] = []
        baselined = 0
        for key, group in by_key.items():
            allowed = self.counts.get(key, 0)
            group.sort()
            baselined += min(allowed, len(group))
            new.extend(group[allowed:])
        stale = sorted(k for k, allowed in self.counts.items()
                       if len(by_key.get(k, [])) < allowed)
        new.sort()
        return LintResult(diagnostics=new, baselined=baselined,
                          stale_entries=stale)
