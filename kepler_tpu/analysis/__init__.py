"""keplint: project-native static analysis for the attribution stack.

Run as ``python -m kepler_tpu.analysis [paths]`` (wired into ``make
lint``). The engine lives in :mod:`kepler_tpu.analysis.engine`, the
domain rules in :mod:`kepler_tpu.analysis.rules`; the rule catalog is
rendered to ``docs/developer/static-analysis.md`` by
``hack/gen_lint_docs.py`` and checked fresh in CI.
"""

from kepler_tpu.analysis.engine import (
    Baseline,
    Diagnostic,
    FileContext,
    LintResult,
    ProjectRule,
    REGISTRY,
    Rule,
    all_rules,
    find_repo_root,
    lint_paths,
    register,
)
from kepler_tpu.analysis import rules as _rules  # noqa: F401  (registers)

__all__ = [
    "Baseline",
    "Diagnostic",
    "FileContext",
    "LintResult",
    "ProjectRule",
    "REGISTRY",
    "Rule",
    "all_rules",
    "find_repo_root",
    "lint_paths",
    "register",
]
