"""keplint domain rules: the attribution-stack invariants, as AST checks.

Each rule encodes one invariant the attribution formula depends on (see
``docs/developer/static-analysis.md`` for the catalog — generated from
this registry by ``hack/gen_lint_docs.py``). Scoping is declarative where
it can be: files opt into clock discipline with ``# keplint:
monotonic-only``, hot functions are marked ``# keplint: hot-loop``, and
lock contracts are annotated at the attribute (``# keplint:
guarded-by=_lock``) and function (``# keplint: requires-lock=_lock``)
level — so the rules need no hardcoded knowledge of which module does
what, and fixture tests exercise them hermetically.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import (
    Diagnostic,
    FileContext,
    Rule,
    register,
)

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def _qualname(node: ast.AST) -> str | None:
    """Dotted name for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Imports:
    """Per-file import alias map, so ``_time.time()`` and
    ``from time import time as now; now()`` both canonicalize to
    ``time.time``."""

    def __init__(self, tree: ast.Module) -> None:
        self.alias: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.alias[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.alias[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.alias[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def canonical(self, qual: str | None) -> str | None:
        if not qual:
            return None
        head, _, rest = qual.partition(".")
        head = self.alias.get(head, head)
        return f"{head}.{rest}" if rest else head


def _imports_for(ctx: FileContext) -> _Imports:
    """One alias map per file, shared by every rule that needs it."""
    cached = getattr(ctx, "_keplint_imports", None)
    if cached is None:
        cached = _Imports(ctx.tree)
        ctx._keplint_imports = cached  # type: ignore[attr-defined]
    return cached


def _call_canonical(node: ast.Call, imports: _Imports) -> str | None:
    return imports.canonical(_qualname(node.func))


def _terminal(name: str | None) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


# ---------------------------------------------------------------------------
# KTL101 — monotonic clocks in timing logic
# ---------------------------------------------------------------------------

_WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}


@register
class MonotonicClockRule(Rule):
    id = "KTL101"
    name = "monotonic-clock"
    summary = ("no wall-clock calls in modules marked "
               "`# keplint: monotonic-only`")
    rationale = (
        "Backoff, rate-limit, circuit-breaker, and watchdog arithmetic "
        "breaks when NTP steps the wall clock (the exact bug class PR 1 "
        "fixed by hand). Timing modules declare `# keplint: "
        "monotonic-only` and may then only *call* `time.monotonic()` or "
        "an injected clock seam; referencing `time.time` as an injectable "
        "default stays legal because the seam is the point.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if not ctx.has_file_marker("monotonic-only"):
            return
        imports = _imports_for(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = _call_canonical(node, imports)
            if canon in _WALL_CLOCK_CALLS:
                yield ctx.diag(
                    self, node,
                    f"wall-clock call {canon}() in a monotonic-only "
                    "module; use time.monotonic() or the injected "
                    "clock/monotonic seam")


# ---------------------------------------------------------------------------
# KTL102 — wrap-aware energy-counter deltas
# ---------------------------------------------------------------------------

_COUNTERISH = re.compile(r"(^|_)(energy|counter)(_|$)|(^|_)uj$",
                         re.IGNORECASE)
# time.perf_counter / counters of unrelated kinds are not energy counters
_NOT_COUNTERISH = re.compile(r"perf_counter$", re.IGNORECASE)


def _is_counterish(name: str) -> bool:
    return bool(_COUNTERISH.search(name)
                and not _NOT_COUNTERISH.search(name))

# the canonical helper (and the docstring'd inline implementation it
# wraps) are the two places allowed to do raw counter arithmetic
_DELTA_HELPER_SUFFIXES = ("kepler_tpu/ops/deltas.py",)


def _operand_name(node: ast.AST) -> str:
    """Identifier a subtraction operand 'reads from': the terminal
    attribute/name, looking through a call (``zone.energy() - prev``)."""
    if isinstance(node, ast.Call):
        return _terminal(_qualname(node.func))
    return _terminal(_qualname(node))


@register
class WrapAwareDeltaRule(Rule):
    id = "KTL102"
    name = "wrap-aware-delta"
    summary = ("energy-counter subtraction must go through "
               "ops.deltas.energy_delta")
    rationale = (
        "RAPL counters wrap at max_energy_range_uj; a raw `current - "
        "prev` turns every wrap into a huge negative delta that corrupts "
        "cumulative joules and the attribution numerator. All counter "
        "delta math goes through `kepler_tpu.ops.deltas.energy_delta` / "
        "`energy_deltas` (exact wraparound semantics, reference "
        "node.go:87-98).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if ctx.rel_path.endswith(_DELTA_HELPER_SUFFIXES):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            left = _operand_name(node.left)
            right = _operand_name(node.right)
            if not (left and right):
                continue  # literals / nested expressions: not counter math
            if _is_counterish(left) or _is_counterish(right):
                yield ctx.diag(
                    self, node,
                    f"raw subtraction on energy-counter-like operands "
                    f"({left!r} - {right!r}); use "
                    "kepler_tpu.ops.deltas.energy_delta for wrap-aware "
                    "math")


# ---------------------------------------------------------------------------
# KTL103 — published snapshots stay immutable
# ---------------------------------------------------------------------------

# distinctive Snapshot/NodeUsage/WorkloadTable field names; generic ones
# (ids/meta/node/...) are omitted so unrelated objects don't false-positive
_SNAPSHOT_FIELDS = frozenset({
    "energy_uj", "active_uj", "idle_uj",
    "power_uw", "active_power_uw", "idle_power_uw",
    "window_active_uj", "zone_names",
    "terminated_processes", "terminated_containers",
    "terminated_virtual_machines", "terminated_pods",
})

# the monitor build path constructs snapshots before publication
_SNAPSHOT_BUILDER_SUFFIXES = (
    "kepler_tpu/monitor/monitor.py",
    "kepler_tpu/monitor/snapshot.py",
)


@register
class SnapshotImmutableRule(Rule):
    id = "KTL103"
    name = "snapshot-immutable"
    summary = "no mutation of Snapshot fields outside the monitor build path"
    rationale = (
        "`PowerMonitor.snapshot(clone=False)` hands consumers the "
        "published object itself; the exporter's zero-copy scrape render "
        "is only race-free because a published Snapshot is never mutated "
        "— each refresh builds new arrays and swaps the reference. The "
        "dataclasses are frozen, but numpy array *contents* are not, so "
        "`snap.node.energy_uj[0] = x` (or `object.__setattr__`) would "
        "corrupt concurrent scrapes silently.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if ctx.rel_path.endswith(_SNAPSHOT_BUILDER_SUFFIXES):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                canon = _qualname(node.func)
                if canon == "object.__setattr__":
                    yield ctx.diag(
                        self, node,
                        "object.__setattr__ defeats frozen-dataclass "
                        "immutability; build a new Snapshot instead")
                continue
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for target in targets:
                # unwrap element writes: snap.node.energy_uj[...] = v
                inner = target
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if not isinstance(inner, ast.Attribute):
                    continue
                if inner.attr not in _SNAPSHOT_FIELDS:
                    continue
                # only a DIRECT `self.<field>` write is own state (the
                # monitor-style accumulator); a deeper chain rooted at
                # self (`self._snap.node.energy_uj[...]`) is a held
                # published snapshot and exactly the bug class
                if (isinstance(inner.value, ast.Name)
                        and inner.value.id == "self"):
                    continue
                yield ctx.diag(
                    self, node,
                    f"mutation of snapshot field {inner.attr!r} outside "
                    "the monitor build path; published snapshots are "
                    "immutable — build new arrays and swap the reference")


# ---------------------------------------------------------------------------
# KTL104 — config reads must be declared (and documented)
# ---------------------------------------------------------------------------

_CONFIG_PY = "kepler_tpu/config/config.py"
_GEN_CONFIG_DOCS = "hack/gen_config_docs.py"

_schema_cache: dict[str, dict | None] = {}


def _dataclass_classes(tree: ast.Module) -> dict[str, ast.ClassDef]:
    out: dict[str, ast.ClassDef] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for deco in node.decorator_list:
            name = _qualname(deco if not isinstance(deco, ast.Call)
                             else deco.func)
            if name and name.split(".")[-1] == "dataclass":
                out[node.name] = node
                break
    return out


def _class_schema(cls: ast.ClassDef, classes: dict[str, ast.ClassDef],
                  stack: tuple[str, ...] = ()) -> dict:
    """{'fields': {name: sub-schema|None}, 'extras': {methods/classvars}}"""
    fields: dict[str, dict | None] = {}
    extras: set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name):
            sub = None
            ann = _qualname(stmt.annotation) or ""
            target_cls = ann.split(".")[-1]
            if target_cls not in classes and isinstance(
                    stmt.value, ast.Call):
                for kw in stmt.value.keywords:
                    if kw.arg == "default_factory":
                        target_cls = _terminal(_qualname(kw.value))
            if (target_cls in classes and target_cls != cls.name
                    and target_cls not in stack):
                sub = _class_schema(classes[target_cls], classes,
                                    stack + (cls.name,))
            fields[stmt.target.id] = sub
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    extras.add(t.id)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            extras.add(stmt.name)
    return {"fields": fields, "extras": extras}


def _config_schema_for(ctx: FileContext) -> dict | None:
    """Schema of the repo's Config tree, parsed statically from
    kepler_tpu/config/config.py under the lint root (fixture-friendly:
    a tmp tree with its own config.py gets its own schema)."""
    import os

    cache_key = ctx.root
    if cache_key in _schema_cache:
        return _schema_cache[cache_key]
    schema: dict | None = None
    cfg_path = os.path.join(ctx.root, *_CONFIG_PY.split("/"))
    try:
        with open(cfg_path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        classes = _dataclass_classes(tree)
        if "Config" in classes:
            schema = _class_schema(classes["Config"], classes)
    except (OSError, SyntaxError):
        schema = None
    _schema_cache[cache_key] = schema
    return schema


def _documented_config_keys(ctx: FileContext) -> set[str] | None:
    """Keys of DESCRIPTIONS in hack/gen_config_docs.py, or None when the
    generator is absent (fixtures without a hack/ tree)."""
    import os

    gen_path = os.path.join(ctx.root, *_GEN_CONFIG_DOCS.split("/"))
    try:
        with open(gen_path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "DESCRIPTIONS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _schema_leaves(schema: dict, prefix: str = "") -> Iterator[str]:
    for name, sub in schema["fields"].items():
        path = f"{prefix}{name}"
        if sub is None:
            yield path
        else:
            yield from _schema_leaves(sub, f"{path}.")


@register
class ConfigDeclaredRule(Rule):
    id = "KTL104"
    name = "config-declared"
    summary = ("every `cfg.*` attribute read must exist in config.py and "
               "be documented in hack/gen_config_docs.py")
    rationale = (
        "Config is a plain dataclass tree: reading `cfg.monitor.intervall` "
        "raises AttributeError only on the code path that reaches it — in "
        "production, at 3am. Statically resolving every `cfg.`-rooted "
        "attribute chain against the declared schema turns that into a "
        "lint failure; requiring a DESCRIPTIONS entry per leaf keeps "
        "`docs/user/configuration.md` complete (the generator's teeth, "
        "enforced at lint time too).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        schema = _config_schema_for(ctx)
        if schema is None:
            return
        # part 1: cfg.<...> reads anywhere resolve against the schema
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            qual = _qualname(node)
            if not qual:
                continue
            parts = qual.split(".")
            # depth >= 3 (`cfg.section.field`) so a local named `cfg`
            # that is a *section* config (FaultConfig, a dict, …) with
            # depth-1 reads never false-positives; depth-1 reads on the
            # real Config resolve at import time anyway
            if parts[0] != "cfg" or len(parts) < 3:
                continue
            # validate the LONGEST chain only (an Attribute node's value
            # chain is itself an Attribute; skip inner nodes)
            parent = getattr(node, "_keplint_parent_checked", False)
            if parent:
                continue
            cur = schema
            for i, attr in enumerate(parts[1:], start=1):
                if attr in cur["fields"]:
                    sub = cur["fields"][attr]
                    if sub is None:
                        break  # reached a leaf; trailing attrs are on
                        # the leaf value (str/int/...), not config keys
                    cur = sub
                elif attr in cur["extras"]:
                    break  # method / classvar on the section
                else:
                    yield ctx.diag(
                        self, node,
                        f"config attribute {'.'.join(parts[:i + 1])!r} is "
                        "not declared in kepler_tpu/config/config.py")
                    break
            for sub_node in ast.walk(node):
                if isinstance(sub_node, ast.Attribute):
                    sub_node._keplint_parent_checked = True  # type: ignore
        # part 2: on config.py itself, every leaf must be documented
        if ctx.rel_path.endswith(_CONFIG_PY):
            documented = _documented_config_keys(ctx)
            if documented is not None:
                for leaf in _schema_leaves(schema):
                    if leaf not in documented:
                        yield Diagnostic(
                            path=ctx.rel_path, line=1, col=1,
                            rule_id=self.id, severity=self.severity,
                            message=(
                                f"config leaf {leaf!r} has no DESCRIPTIONS "
                                f"entry in {_GEN_CONFIG_DOCS} — document "
                                "the knob"))


# ---------------------------------------------------------------------------
# KTL105 — Prometheus metric naming
# ---------------------------------------------------------------------------

_METRIC_CTORS = {
    "CounterMetricFamily", "GaugeMetricFamily", "HistogramMetricFamily",
    "SummaryMetricFamily", "InfoMetricFamily", "UntypedMetricFamily",
    "Counter", "Gauge", "Histogram", "Summary", "Info", "Enum",
}
_METRIC_NAME = re.compile(r"^kepler_[a-z][a-z0-9_]*$")
# approved final name tokens: units first, then semantic/count forms
_UNIT_TOKENS = frozenset({
    "total", "joules", "watts", "seconds", "ratio", "ms", "bytes",
    "celsius", "info", "healthy", "degraded", "flops", "state",
})
_COUNT_TOKENS = frozenset({"nodes", "workloads", "records", "rows",
                           "shards", "windows"})
# reference-parity names grandfathered in (match the upstream exporter)
_EXACT_ALLOW = frozenset({"kepler_node_cpu_power_meter"})


def _metric_name_literal(arg: ast.expr) -> tuple[str | None, str | None]:
    """(full_constant_name, trailing_literal) for the first ctor arg.

    f-strings return (None, trailing-literal-if-any): the charset of the
    dynamic part can't be checked, but the unit suffix usually can.
    """
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not arg.value.startswith("kepler_"):
            return None, None  # another namespace: out of scope
        return arg.value, arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("kepler_")):
            return None, None
        last = arg.values[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, str):
            return None, last.value
        return None, ""  # dynamic tail: unverifiable
    return None, None


@register
class MetricNameRule(Rule):
    id = "KTL105"
    name = "metric-name"
    summary = ("metric names match `kepler_[a-z0-9_]+` and end with a "
               "unit suffix; counters end `_total`")
    rationale = (
        "Dashboards and recording rules key on metric names; drift "
        "(`kepler_fleet_reports` vs `..._total`) silently splits series "
        "across versions. prometheus_client appends `_total` to counter "
        "samples regardless of the declared family name, so a counter "
        "declared without it exposes a name that exists nowhere in the "
        "source — grep-proofing requires declaring the exposed name.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            ctor = _terminal(_qualname(node.func))
            if ctor not in _METRIC_CTORS:
                continue
            full, tail = _metric_name_literal(node.args[0])
            if full is None and tail is None:
                continue  # not a kepler metric literal
            shown = full if full is not None else f"…{tail}"
            if full is not None:
                if full in _EXACT_ALLOW:
                    continue
                if not _METRIC_NAME.match(full):
                    yield ctx.diag(
                        self, node,
                        f"metric name {full!r} must match "
                        "kepler_[a-z][a-z0-9_]*")
                    continue
            is_counter = ctor.startswith("Counter")
            if is_counter:
                if tail is not None and not tail.endswith("_total"):
                    yield ctx.diag(
                        self, node,
                        f"counter {shown!r} must be declared with the "
                        "exposed `_total` suffix")
                continue
            if tail is None or not tail:
                continue  # dynamic tail: cannot verify the suffix
            token = tail.rsplit("_", 1)[-1]
            if token not in _UNIT_TOKENS and token not in _COUNT_TOKENS:
                yield ctx.diag(
                    self, node,
                    f"metric {shown!r} lacks a recognized unit suffix "
                    f"(one of {', '.join(sorted(_UNIT_TOKENS))} or a "
                    "count noun); name the unit or extend the rule's "
                    "token set deliberately")


# ---------------------------------------------------------------------------
# KTL106 — no blocking I/O in the refresh hot loop
# ---------------------------------------------------------------------------

_BLOCKING_ROOTS = {"subprocess", "socket", "urllib", "requests", "http"}
_BLOCKING_CALLS = {"time.sleep"}
_BLOCKING_BARE = {"open", "input", "print"}


@register
class HotLoopBlockingRule(Rule):
    id = "KTL106"
    name = "hot-loop-blocking"
    summary = ("no sleep / blocking I/O inside functions marked "
               "`# keplint: hot-loop`")
    rationale = (
        "The monitor's refresh loop runs under the snapshot lock on the "
        "interval cadence; one stray sleep or network call inside it "
        "stalls every scrape and window listener and eventually trips "
        "the watchdog. Functions on the refresh path carry `# keplint: "
        "hot-loop`; the check is lexical (direct calls only) — seams "
        "like the meter keep their own contracts.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = _imports_for(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if ctx.marker_on(node, "hot-loop") is None:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                canon = _call_canonical(call, imports) or ""
                root = canon.split(".")[0]
                terminal = _terminal(canon)
                if (canon in _BLOCKING_CALLS
                        or terminal == "sleep"
                        or root in _BLOCKING_ROOTS
                        or canon in _BLOCKING_BARE):
                    yield ctx.diag(
                        self, call,
                        f"blocking call {canon}() inside hot-loop "
                        f"function {node.name}(); the refresh path must "
                        "not sleep or do I/O beyond the meter seam")


# ---------------------------------------------------------------------------
# KTL107 — jitted / Pallas code is side-effect-free
# ---------------------------------------------------------------------------

_IMPURE_ROOTS = {"random", "time", "datetime"}
_IMPURE_BARE = {"print", "open", "input"}


def _jitted_functions(tree: ast.Module,
                      imports: _Imports) -> list[ast.FunctionDef]:
    """Functions decorated with jax.jit (directly or via
    functools.partial) plus kernels passed to pallas_call."""
    out: list[ast.FunctionDef] = []
    kernel_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            canon = _call_canonical(node, imports) or ""
            if _terminal(canon) == "pallas_call" and node.args:
                name = _qualname(node.args[0])
                if name and "." not in name:
                    kernel_names.add(name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name in kernel_names:
            out.append(node)
            continue
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            canon = imports.canonical(_qualname(target)) or ""
            if canon in ("jax.jit", "jit") or canon.endswith(".jit"):
                out.append(node)
                break
            if (isinstance(deco, ast.Call)
                    and _terminal(canon) == "partial" and deco.args):
                inner = imports.canonical(_qualname(deco.args[0])) or ""
                if inner in ("jax.jit", "jit") or inner.endswith(".jit"):
                    out.append(node)
                    break
    return out


@register
class JitPureRule(Rule):
    id = "KTL107"
    name = "jit-pure"
    summary = ("no Python side effects (print, wall clock, host RNG, "
               "global state) inside jitted/Pallas functions")
    rationale = (
        "`jax.jit` traces Python once per shape; side effects run at "
        "trace time only (or not at all after a cache hit), so a print, "
        "`time.time()`, `np.random`, or global mutation inside a kernel "
        "is either dead code or a silent nondeterminism bug. Kernels in "
        "kepler_tpu/ops/ must stay pure functions of their arrays with "
        "static shapes.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = _imports_for(ctx)
        for fn in _jitted_functions(ctx.tree, imports):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield ctx.diag(
                        self, node,
                        f"{type(node).__name__.lower()} statement inside "
                        f"jitted function {fn.name}(); jitted code must "
                        "not mutate enclosing scopes")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                canon = _call_canonical(node, imports) or ""
                root = canon.split(".")[0]
                impure = (
                    canon in _IMPURE_BARE
                    or root in _IMPURE_ROOTS
                    or canon.startswith("numpy.random")
                )
                if impure:
                    yield ctx.diag(
                        self, node,
                        f"impure call {canon}() inside jitted function "
                        f"{fn.name}(); kernels must be side-effect-free "
                        "(use jax.random / jax.debug.print if needed)")


# ---------------------------------------------------------------------------
# KTL108 — lock-guarded attributes
# ---------------------------------------------------------------------------


def _with_locks(node: ast.With) -> set[str]:
    out: set[str] = set()
    for item in node.items:
        qual = _qualname(item.context_expr)
        if qual and qual.startswith("self."):
            out.add(qual[len("self."):])
    return out


@register
class LockGuardedRule(Rule):
    id = "KTL108"
    name = "lock-guarded"
    summary = ("attributes annotated `# keplint: guarded-by=<lock>` are "
               "only written under `with self.<lock>`")
    rationale = (
        "The monitor/aggregator publish data to scrape threads through "
        "attributes whose write side is documented as lock-guarded "
        "(reads are lock-free reference swaps). The contract is machine-"
        "readable: annotate the attribute in __init__ with `# keplint: "
        "guarded-by=_lock`; functions that may only be called with the "
        "lock held carry `# keplint: requires-lock=_lock`, and every "
        "call to them must itself hold the lock (a small lexical effect "
        "system).")

    _EXEMPT_METHODS = frozenset({"__init__", "init"})

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Diagnostic]:
        guarded: dict[str, str] = {}
        requires: dict[str, str] = {}
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for fn in methods:
            lock = ctx.marker_on(fn, "requires-lock")
            if lock:
                requires[fn.name] = lock
            if fn.name not in self._EXEMPT_METHODS:
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                for kind, arg in ctx.directives.get(stmt.lineno, []):
                    if kind != "guarded-by" or not arg:
                        continue
                    targets = (stmt.targets
                               if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            guarded[t.attr] = arg
        if not guarded and not requires:
            return
        for fn in methods:
            if fn.name in self._EXEMPT_METHODS:
                continue
            held: set[str] = set()
            if fn.name in requires:
                held = {requires[fn.name]}
            yield from self._walk(ctx, fn, list(fn.body), held,
                                  guarded, requires)

    def _walk(self, ctx: FileContext, fn: ast.AST, body: list,
              held: set[str], guarded: dict[str, str],
              requires: dict[str, str]) -> Iterator[Diagnostic]:
        for node in body:
            extra: set[str] = set()
            if isinstance(node, ast.With):
                extra = _with_locks(node)
            yield from self._check_stmt(ctx, fn, node, held | extra,
                                        guarded, requires)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure runs later, possibly without the lock held
                yield from self._walk(ctx, fn, node.body, set(),
                                      guarded, requires)
                continue
            for child_body in self._child_bodies(node):
                yield from self._walk(ctx, fn, child_body, held | extra,
                                      guarded, requires)

    @staticmethod
    def _child_bodies(node: ast.AST) -> list[list]:
        out = []
        for attr in ("body", "orelse", "finalbody"):
            val = getattr(node, attr, None)
            if val:
                out.append(val)
        for handler in getattr(node, "handlers", []) or []:
            out.append(handler.body)
        return out

    def _check_stmt(self, ctx: FileContext, fn: ast.AST, node: ast.AST,
                    held: set[str], guarded: dict[str, str],
                    requires: dict[str, str]) -> Iterator[Diagnostic]:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for target in targets:
            inner = target
            while isinstance(inner, ast.Subscript):
                inner = inner.value
            if (isinstance(inner, ast.Attribute)
                    and isinstance(inner.value, ast.Name)
                    and inner.value.id == "self"
                    and inner.attr in guarded
                    and guarded[inner.attr] not in held):
                yield ctx.diag(
                    self, node,
                    f"write to self.{inner.attr} (guarded by "
                    f"self.{guarded[inner.attr]}) outside `with "
                    f"self.{guarded[inner.attr]}` in "
                    f"{getattr(fn, 'name', '?')}()")
        # calls into requires-lock functions need the lock too; examine
        # only the expressions attached to THIS statement (nested
        # statements are visited by _walk, so they are never double-
        # counted)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.expr):
                continue
            for expr in ast.walk(child):
                if not isinstance(expr, ast.Call):
                    continue
                qual = _qualname(expr.func) or ""
                if not qual.startswith("self."):
                    continue
                callee = qual[len("self."):]
                if "." in callee or callee not in requires:
                    continue
                if requires[callee] not in held:
                    yield ctx.diag(
                        self, expr,
                        f"call to self.{callee}() requires holding "
                        f"self.{requires[callee]} (marked requires-lock)"
                        " — wrap the call in `with self."
                        f"{requires[callee]}:`")


# ---------------------------------------------------------------------------
# KTL109 — telemetry span discipline
# ---------------------------------------------------------------------------


def _is_span_call(node: ast.AST, imports: _Imports) -> bool:
    """A call to the telemetry span API: ``telemetry.span(...)`` (module
    import), ``kepler_tpu.telemetry.span`` (canonicalized from-import),
    or a bare ``span(...)`` whose import resolves into the telemetry
    package."""
    if not isinstance(node, ast.Call):
        return False
    canon = _call_canonical(node, imports) or ""
    if _terminal(canon) != "span":
        return False
    return canon == "span" or canon.endswith("telemetry.span")


def _walk_span_body(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a span with-block like ``ast.walk`` but WITHOUT descending
    into nested function/lambda definitions: a callback defined inside
    the body runs after the span closed, so its clock calls are not
    span-body timing."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


@register
class SpanDisciplineRule(Rule):
    id = "KTL109"
    name = "span-discipline"
    summary = ("span bodies use monotonic clocks only, and span() never "
               "appears inside jitted/Pallas kernels")
    rationale = (
        "Telemetry spans time their body with `time.monotonic`; a wall-"
        "clock call (`time.time`, `datetime.now`) inside a `with "
        "span(...)` body means the stage's own logic is deriving "
        "durations from a clock NTP can step — the histogram and the "
        "code would disagree about what was measured. (The injected "
        "`self._clock` seam stays legal: seams are the sanctioned wall-"
        "clock source.) And `jax.jit` traces Python once per shape, so "
        "a span inside a jitted/Pallas kernel times the TRACE, not the "
        "execution — it would record one misleading sample per compile "
        "and nothing afterwards (composes with KTL107's purity rule).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = _imports_for(ctx)
        # part 1: wall-clock calls inside `with span(...)` bodies
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_span_call(item.context_expr, imports)
                       for item in node.items):
                continue
            for call in _walk_span_body(node):
                if not isinstance(call, ast.Call):
                    continue
                canon = _call_canonical(call, imports)
                if canon in _WALL_CLOCK_CALLS:
                    yield ctx.diag(
                        self, call,
                        f"wall-clock call {canon}() inside a telemetry "
                        "span body; spans time with time.monotonic — "
                        "use the monotonic clock or an injected seam")
        # part 2: span() inside jitted / Pallas kernels
        for fn in _jitted_functions(ctx.tree, imports):
            for call in ast.walk(fn):
                if _is_span_call(call, imports):
                    yield ctx.diag(
                        self, call,
                        f"telemetry span inside jitted function "
                        f"{fn.name}(); spans run at trace time only — "
                        "instrument the call site, not the kernel")


# ---------------------------------------------------------------------------
# KTL110 — donated arrays are dead after the donating call
# ---------------------------------------------------------------------------

# the device-resident window plane: everywhere the repo donates buffers
_DONATE_SCOPE = (
    "kepler_tpu/parallel/",
    "kepler_tpu/fleet/aggregator.py",
    "kepler_tpu/fleet/window.py",
)


def _donate_positions(node: ast.expr) -> tuple[int, ...] | None:
    """donate_argnums literal (int or tuple/list of ints) → positions."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _parse_donates_arg(arg: str | None) -> tuple[int, ...] | None:
    if not arg:
        return None
    try:
        return tuple(int(p) for p in arg.split(","))
    except ValueError:
        return None


@register
class DonatedBufferRule(Rule):
    id = "KTL110"
    name = "donated-dead"
    summary = ("arrays passed at a donated position are dead after the "
               "call — rebind (`x = f(x, …)`) or never touch them again")
    rationale = (
        "`jax.jit(..., donate_argnums=…)` aliases the argument's buffer "
        "into the computation: the runtime invalidates the handle, and a "
        "later read either raises (good) or — through a stale alias on a "
        "stream-ordered backend — observes memory the program is "
        "rewriting in place (the resident fleet batch's delta update is "
        "exactly this). The check is LEXICAL, scoped to the window plane "
        "(kepler_tpu/parallel/, fleet/aggregator.py, fleet/window.py): a "
        "callable bound from a `jax.jit(…, donate_argnums=…)` call — or "
        "any callable whose binding carries `# keplint: donates=<pos>` "
        "(for jits built behind a helper) — consumes the variables at "
        "those positions; any later read before a rebinding is flagged. "
        "The canonical legal shape is `resident = update(resident, …)`.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if not ctx.rel_path.startswith(_DONATE_SCOPE):
            return
        donators = self._donating_aliases(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node, donators)

    def _donating_aliases(self, ctx: FileContext) -> dict[str,
                                                          tuple[int, ...]]:
        """qualname (``update`` / ``self._update``) → donated positions,
        from `jax.jit(..., donate_argnums=…)` bindings and `donates=`
        directives anywhere in the file."""
        imports = _imports_for(ctx)
        out: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            positions: tuple[int, ...] | None = None
            value = node.value
            if isinstance(value, ast.Call):
                canon = _call_canonical(value, imports) or ""
                if canon in ("jax.jit", "jit") or canon.endswith(".jit"):
                    for kw in value.keywords:
                        if kw.arg == "donate_argnums":
                            positions = _donate_positions(kw.value)
            for kind, arg in ctx.directives.get(node.lineno, []):
                if kind == "donates":
                    positions = _parse_donates_arg(arg) or positions
            if positions is None:
                continue
            for target in node.targets:
                qual = _qualname(target)
                if qual:
                    out[qual] = positions
        return out

    def _check_function(self, ctx: FileContext, fn: ast.AST,
                        donators: dict) -> Iterator[Diagnostic]:
        # consumed qualname → the line its buffer was donated on
        consumed: dict[str, int] = {}

        def statements(body):
            for stmt in body:
                yield stmt
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # nested defs run later; out of scope
                for child_body in (getattr(stmt, a, None)
                                   for a in ("body", "orelse",
                                             "finalbody")):
                    if child_body:
                        yield from statements(child_body)
                for handler in getattr(stmt, "handlers", []) or []:
                    yield from statements(handler.body)
                for case in getattr(stmt, "cases", []) or []:
                    yield from statements(case.body)

        for stmt in statements(fn.body):
            diags = list(self._check_stmt(ctx, stmt, donators, consumed))
            yield from diags

    @staticmethod
    def _stmt_exprs(stmt: ast.AST) -> Iterator[ast.AST]:
        """The statement's OWN expression nodes (an If's test, a For's
        iter, an Assign's value/targets, a With's items) — nested
        statements are visited separately by the statement walk, so
        descending into them here would double-process their donations
        and falsely flag the rebind pattern inside any compound body."""
        stack = list(ast.iter_child_nodes(stmt))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.stmt):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_stmt(self, ctx: FileContext, stmt: ast.AST, donators: dict,
                    consumed: dict[str, int]) -> Iterator[Diagnostic]:
        # 1) reads of names consumed by an EARLIER statement
        for node in self._stmt_exprs(stmt):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            qual = _qualname(node)
            if qual in consumed:
                line = consumed.pop(qual)  # report once, don't cascade
                yield ctx.diag(
                    self, node,
                    f"{qual!r} was donated on line {line} and its buffer "
                    "is dead; rebind the result (`x = f(x, …)`) or stop "
                    "reading it")
        # 2) donations performed by this statement
        for node in self._stmt_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            qual = _qualname(node.func)
            if qual not in donators:
                continue
            for pos in donators[qual]:
                if pos < len(node.args):
                    arg_qual = _qualname(node.args[pos])
                    if arg_qual:
                        consumed[arg_qual] = node.lineno
        # 3) rebinding clears consumption (the canonical donate pattern
        #    `x = f(x, …)` lands here: consumed in (2), cleared now)
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for target in targets:
            qual = _qualname(target)
            if qual:
                consumed.pop(qual, None)
