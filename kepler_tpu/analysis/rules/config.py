"""KTL104 — config reads must be declared (and documented)."""

from __future__ import annotations

import ast
import os
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import qualname, terminal

_CONFIG_PY = "kepler_tpu/config/config.py"
_GEN_CONFIG_DOCS = "hack/gen_config_docs.py"

_schema_cache: dict[str, dict | None] = {}


def _dataclass_classes(tree: ast.Module) -> dict[str, ast.ClassDef]:
    out: dict[str, ast.ClassDef] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for deco in node.decorator_list:
            name = qualname(deco if not isinstance(deco, ast.Call)
                            else deco.func)
            if name and name.split(".")[-1] == "dataclass":
                out[node.name] = node
                break
    return out


def _class_schema(cls: ast.ClassDef, classes: dict[str, ast.ClassDef],
                  stack: tuple[str, ...] = ()) -> dict:
    """{'fields': {name: sub-schema|None}, 'extras': {methods/classvars}}"""
    fields: dict[str, dict | None] = {}
    extras: set[str] = set()
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name):
            sub = None
            ann = qualname(stmt.annotation) or ""
            target_cls = ann.split(".")[-1]
            if target_cls not in classes and isinstance(
                    stmt.value, ast.Call):
                for kw in stmt.value.keywords:
                    if kw.arg == "default_factory":
                        target_cls = terminal(qualname(kw.value))
            if (target_cls in classes and target_cls != cls.name
                    and target_cls not in stack):
                sub = _class_schema(classes[target_cls], classes,
                                    stack + (cls.name,))
            fields[stmt.target.id] = sub
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    extras.add(t.id)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            extras.add(stmt.name)
    return {"fields": fields, "extras": extras}


def _config_schema_for(ctx: FileContext) -> dict | None:
    """Schema of the repo's Config tree, parsed statically from
    kepler_tpu/config/config.py under the lint root (fixture-friendly:
    a tmp tree with its own config.py gets its own schema)."""
    cache_key = ctx.root
    if cache_key in _schema_cache:
        return _schema_cache[cache_key]
    schema: dict | None = None
    cfg_path = os.path.join(ctx.root, *_CONFIG_PY.split("/"))
    try:
        with open(cfg_path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        classes = _dataclass_classes(tree)
        if "Config" in classes:
            schema = _class_schema(classes["Config"], classes)
    except (OSError, SyntaxError):
        schema = None
    _schema_cache[cache_key] = schema
    return schema


def _documented_config_keys(ctx: FileContext) -> set[str] | None:
    """Keys of DESCRIPTIONS in hack/gen_config_docs.py, or None when the
    generator is absent (fixtures without a hack/ tree)."""
    gen_path = os.path.join(ctx.root, *_GEN_CONFIG_DOCS.split("/"))
    try:
        with open(gen_path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "DESCRIPTIONS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _schema_leaves(schema: dict, prefix: str = "") -> Iterator[str]:
    for name, sub in schema["fields"].items():
        path = f"{prefix}{name}"
        if sub is None:
            yield path
        else:
            yield from _schema_leaves(sub, f"{path}.")


@register
class ConfigDeclaredRule(Rule):
    id = "KTL104"
    name = "config-declared"
    summary = ("every `cfg.*` attribute read must exist in config.py and "
               "be documented in hack/gen_config_docs.py")
    rationale = (
        "Config is a plain dataclass tree: reading `cfg.monitor.intervall` "
        "raises AttributeError only on the code path that reaches it — in "
        "production, at 3am. Statically resolving every `cfg.`-rooted "
        "attribute chain against the declared schema turns that into a "
        "lint failure; requiring a DESCRIPTIONS entry per leaf keeps "
        "`docs/user/configuration.md` complete (the generator's teeth, "
        "enforced at lint time too).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        schema = _config_schema_for(ctx)
        if schema is None:
            return
        # part 1: cfg.<...> reads anywhere resolve against the schema
        for node in ctx.walk_nodes:
            if not isinstance(node, ast.Attribute):
                continue
            qual = qualname(node)
            if not qual:
                continue
            parts = qual.split(".")
            # depth >= 3 (`cfg.section.field`) so a local named `cfg`
            # that is a *section* config (FaultConfig, a dict, …) with
            # depth-1 reads never false-positives; depth-1 reads on the
            # real Config resolve at import time anyway
            if parts[0] != "cfg" or len(parts) < 3:
                continue
            # validate the LONGEST chain only (an Attribute node's value
            # chain is itself an Attribute; skip inner nodes)
            parent = getattr(node, "_keplint_parent_checked", False)
            if parent:
                continue
            cur = schema
            for i, attr in enumerate(parts[1:], start=1):
                if attr in cur["fields"]:
                    sub = cur["fields"][attr]
                    if sub is None:
                        break  # reached a leaf; trailing attrs are on
                        # the leaf value (str/int/...), not config keys
                    cur = sub
                elif attr in cur["extras"]:
                    break  # method / classvar on the section
                else:
                    yield ctx.diag(
                        self, node,
                        f"config attribute {'.'.join(parts[:i + 1])!r} is "
                        "not declared in kepler_tpu/config/config.py")
                    break
            for sub_node in ast.walk(node):
                if isinstance(sub_node, ast.Attribute):
                    sub_node._keplint_parent_checked = True  # type: ignore
        # part 2: on config.py itself, every leaf must be documented
        if ctx.rel_path.endswith(_CONFIG_PY):
            documented = _documented_config_keys(ctx)
            if documented is not None:
                for leaf in _schema_leaves(schema):
                    if leaf not in documented:
                        yield Diagnostic(
                            path=ctx.rel_path, line=1, col=1,
                            rule_id=self.id, severity=self.severity,
                            message=(
                                f"config leaf {leaf!r} has no DESCRIPTIONS "
                                f"entry in {_GEN_CONFIG_DOCS} — document "
                                "the knob"))
