"""keplint domain rules: the attribution-stack invariants, as AST checks.

Each rule encodes one invariant the attribution formula depends on (see
``docs/developer/static-analysis.md`` for the catalog — generated from
this registry by ``hack/gen_lint_docs.py``). Scoping is declarative
where it can be: files opt into clock discipline with ``# keplint:
monotonic-only``, hot functions are marked ``# keplint: hot-loop``, and
lock contracts are annotated at the attribute (``# keplint:
guarded-by=_lock``) and function (``# keplint: requires-lock=_lock``)
level — so the rules need no hardcoded knowledge of which module does
what, and fixture tests exercise them hermetically.

The package splits one module per rule family; importing it populates
the registry. KTL101-110 and KTL114 run per file; KTL111-113 are
:class:`~kepler_tpu.analysis.engine.ProjectRule` families over the
whole-program :class:`~kepler_tpu.analysis.project.ProjectContext`
(call graph, thread roles, lock summaries, taint propagation);
KTL120-123 are :class:`~kepler_tpu.analysis.engine.DeviceRule`
families over traced device-program jaxprs
(:mod:`kepler_tpu.analysis.device`, opt-in via ``--device-tier``);
KTL130-132 are :class:`~kepler_tpu.analysis.engine.ProtocolRule`
families over exhaustively explored protocol state spaces
(:mod:`kepler_tpu.analysis.protocol`, opt-in via ``--protocol-tier``),
with KTL133 as their per-file marker-discipline fence.
"""

from __future__ import annotations

# importing the modules registers the rules (ids keep the catalog order)
from kepler_tpu.analysis.rules import clocks  # noqa: F401  KTL101
from kepler_tpu.analysis.rules import deltas  # noqa: F401  KTL102
from kepler_tpu.analysis.rules import snapshots  # noqa: F401  KTL103
from kepler_tpu.analysis.rules import config  # noqa: F401  KTL104
from kepler_tpu.analysis.rules import metrics  # noqa: F401  KTL105
from kepler_tpu.analysis.rules import hotloop  # noqa: F401  KTL106
from kepler_tpu.analysis.rules import purity  # noqa: F401  KTL107
from kepler_tpu.analysis.rules import locks  # noqa: F401  KTL108+111
from kepler_tpu.analysis.rules import spans  # noqa: F401  KTL109
from kepler_tpu.analysis.rules import donate  # noqa: F401  KTL110
from kepler_tpu.analysis.rules import taint  # noqa: F401  KTL112
from kepler_tpu.analysis.rules import roles  # noqa: F401  KTL113
from kepler_tpu.analysis.rules import layout  # noqa: F401  KTL114
from kepler_tpu.analysis import device as _device  # noqa: F401  KTL120-123
from kepler_tpu.analysis import protocol as _protocol  # noqa: F401  KTL130-132
from kepler_tpu.analysis.rules import protocol  # noqa: F401  KTL133
