"""KTL102 — wrap-aware energy-counter deltas."""

from __future__ import annotations

import ast
import re
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import qualname, terminal

_COUNTERISH = re.compile(r"(^|_)(energy|counter)(_|$)|(^|_)uj$",
                         re.IGNORECASE)
# time.perf_counter / counters of unrelated kinds are not energy counters
_NOT_COUNTERISH = re.compile(r"perf_counter$", re.IGNORECASE)


def _is_counterish(name: str) -> bool:
    return bool(_COUNTERISH.search(name)
                and not _NOT_COUNTERISH.search(name))

# the canonical helper (and the docstring'd inline implementation it
# wraps) are the two places allowed to do raw counter arithmetic
_DELTA_HELPER_SUFFIXES = ("kepler_tpu/ops/deltas.py",)


def _operand_name(node: ast.AST) -> str:
    """Identifier a subtraction operand 'reads from': the terminal
    attribute/name, looking through a call (``zone.energy() - prev``)."""
    if isinstance(node, ast.Call):
        return terminal(qualname(node.func))
    return terminal(qualname(node))


@register
class WrapAwareDeltaRule(Rule):
    id = "KTL102"
    name = "wrap-aware-delta"
    summary = ("energy-counter subtraction must go through "
               "ops.deltas.energy_delta")
    rationale = (
        "RAPL counters wrap at max_energy_range_uj; a raw `current - "
        "prev` turns every wrap into a huge negative delta that corrupts "
        "cumulative joules and the attribution numerator. All counter "
        "delta math goes through `kepler_tpu.ops.deltas.energy_delta` / "
        "`energy_deltas` (exact wraparound semantics, reference "
        "node.go:87-98).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if ctx.rel_path.endswith(_DELTA_HELPER_SUFFIXES):
            return
        for node in ctx.walk_nodes:
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            left = _operand_name(node.left)
            right = _operand_name(node.right)
            if not (left and right):
                continue  # literals / nested expressions: not counter math
            if _is_counterish(left) or _is_counterish(right):
                yield ctx.diag(
                    self, node,
                    f"raw subtraction on energy-counter-like operands "
                    f"({left!r} - {right!r}); use "
                    "kepler_tpu.ops.deltas.energy_delta for wrap-aware "
                    "math")
