"""KTL106 — no blocking I/O in the refresh hot loop (lexical tier).

The call-graph-aware generalization (blocking calls *reachable* from a
hot-loop root through any chain) is KTL113 in ``roles.py``; this rule
stays as the fast intra-file tier that needs no project build.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import imports_for, is_blocking_call


@register
class HotLoopBlockingRule(Rule):
    id = "KTL106"
    name = "hot-loop-blocking"
    summary = ("no sleep / blocking I/O inside functions marked "
               "`# keplint: hot-loop`")
    rationale = (
        "The monitor's refresh loop runs under the snapshot lock on the "
        "interval cadence; one stray sleep or network call inside it "
        "stalls every scrape and window listener and eventually trips "
        "the watchdog. Functions on the refresh path carry `# keplint: "
        "hot-loop`; the check is lexical (direct calls only) — KTL113 "
        "extends it through the call graph, and seams like the meter "
        "keep their own contracts.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = imports_for(ctx)
        for node in ctx.walk_nodes:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if ctx.marker_on(node, "hot-loop") is None:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                canon = is_blocking_call(call, imports)
                if canon:
                    yield ctx.diag(
                        self, call,
                        f"blocking call {canon}() inside hot-loop "
                        f"function {node.name}(); the refresh path must "
                        "not sleep or do I/O beyond the meter seam")
