"""KTL133 — protocol-transition marker discipline (lexical tier).

kepmc (``kepler_tpu/analysis/protocol``) model-checks the fleet's
protocol state machines by driving the SAME pure functions production
runs. That equivalence only holds while every mutation of protocol
state — lease epochs/holders, seq watermarks, spool ack cursors,
wire-v2 base rows — goes through a function declared as a transition.
KTL133 is the fence: inside ``kepler_tpu/fleet/``, an assignment to a
protected protocol attribute is only legal inside a function marked
``# keplint: protocol-transition`` (on the def line, a decorator line,
or the contiguous comment block above — markers stack with
requires-lock and friends). ``__init__`` is not exempt: birth states
are transitions too, and the shipped ones carry the marker.

An unmarked write site is exactly a transition the model checker does
not know about — the KTL130-132 all-clear would silently stop covering
it.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import (
    Diagnostic,
    FileContext,
    Rule,
    SCOPED_TREES,
    register,
)

MARKER = "protocol-transition"

#: the protocol-state attribute surface kepmc models. An attribute
#: lands here when a KTL130-132 model's transition rules read or move
#: it; renaming one in fleet code must update this set AND the model.
PROTECTED_ATTRS = frozenset({
    # lease / membership (lease.succession, lease.partitioned)
    "_epoch", "_holder", "_ring_epoch",
    # seq tracker watermarks (seq.delivery)
    "max_seen", "ring_epoch",
    # spool durability cursor (spool.cursor)
    "_cursor_seg", "_cursor_off", "_acked_through",
    # wire-v2 base-row machine (keyframe.delta)
    "_kf_base", "_needs_keyframe", "_since_keyframe", "_base_rows",
})


def _target_attrs(target: ast.expr) -> Iterator[ast.Attribute]:
    """Attribute nodes a store-target actually writes: unwraps tuple/
    list unpacking, starred targets and subscript chains (``x.a[k] =``
    writes through ``x.a``), without descending into index/value
    expressions (those are reads)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _target_attrs(el)
        return
    if isinstance(target, ast.Starred):
        yield from _target_attrs(target.value)
        return
    node: ast.expr = target
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        yield node


@register
class ProtocolTransitionMarkerRule(Rule):
    id = "KTL133"
    name = "protocol-transition-marker"
    summary = ("inside kepler_tpu/fleet/, protocol state (epoch/seq/"
               "ack/base-row attributes) is only written inside "
               "functions marked `# keplint: protocol-transition`")
    rationale = (
        "The kepmc protocol tier (KTL130-132) proves safety by "
        "exhaustively exploring models built from the fleet's pure "
        "transition functions — and that proof covers production "
        "exactly as long as production state only moves THROUGH those "
        "functions. This rule makes the boundary machine-checkable: "
        "every assignment to a protected protocol attribute (lease "
        "epoch/holder, ring epoch, seq watermark, spool cursor, "
        "keyframe base state) must sit inside a function carrying the "
        "`# keplint: protocol-transition` marker. A write outside a "
        "marked function is a transition the model checker cannot "
        "see: the KTL130-132 all-clear would silently stop meaning "
        "anything for that code path. Birth states (__init__) are "
        "marked, not exempted — initialization chooses the protocol's "
        "initial state, and the models start from it.")

    def in_scope(self, rel_path: str) -> bool:
        head = rel_path.split("/", 1)[0]
        if head not in SCOPED_TREES:
            return True  # explicitly linted fixtures get the rule
        return rel_path.startswith("kepler_tpu/fleet/")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        yield from self._walk(ctx, ctx.tree.body, marked=False,
                              where="module level")

    def _walk(self, ctx: FileContext, body: list, marked: bool,
              where: str) -> Iterator[Diagnostic]:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs later, outside the enclosing
                # transition — it needs its own marker
                fn_marked = ctx.marker_on(node, MARKER) is not None
                yield from self._walk(ctx, node.body, fn_marked,
                                      f"{node.name}()")
                continue
            if isinstance(node, ast.ClassDef):
                yield from self._walk(ctx, node.body, False, where)
                continue
            yield from self._check_stmt(ctx, node, marked, where)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(node, attr, None)
                if child:
                    yield from self._walk(ctx, child, marked, where)
            for handler in getattr(node, "handlers", []) or []:
                yield from self._walk(ctx, handler.body, marked, where)

    def _check_stmt(self, ctx: FileContext, node: ast.AST, marked: bool,
                    where: str) -> Iterator[Diagnostic]:
        if marked:
            return
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for target in targets:
            for attr_node in _target_attrs(target):
                if attr_node.attr not in PROTECTED_ATTRS:
                    continue
                yield ctx.diag(
                    self, node,
                    f"write to protocol state `.{attr_node.attr}` in "
                    f"{where} outside a `# keplint: {MARKER}`-marked "
                    f"function — kepmc (KTL130-132) only proves "
                    f"schedules over declared transitions; mark the "
                    f"function (and cover it in the model) or move the "
                    f"write into an existing transition")
