"""KTL107 — jitted / Pallas code is side-effect-free."""

from __future__ import annotations

import ast
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import (
    call_canonical,
    imports_for,
    jitted_functions,
)

_IMPURE_ROOTS = {"random", "time", "datetime"}
_IMPURE_BARE = {"print", "open", "input"}


@register
class JitPureRule(Rule):
    id = "KTL107"
    name = "jit-pure"
    summary = ("no Python side effects (print, wall clock, host RNG, "
               "global state) inside jitted/Pallas functions")
    rationale = (
        "`jax.jit` traces Python once per shape; side effects run at "
        "trace time only (or not at all after a cache hit), so a print, "
        "`time.time()`, `np.random`, or global mutation inside a kernel "
        "is either dead code or a silent nondeterminism bug. Kernels in "
        "kepler_tpu/ops/ must stay pure functions of their arrays with "
        "static shapes.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = imports_for(ctx)
        for fn in jitted_functions(ctx):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    yield ctx.diag(
                        self, node,
                        f"{type(node).__name__.lower()} statement inside "
                        f"jitted function {fn.name}(); jitted code must "
                        "not mutate enclosing scopes")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                canon = call_canonical(node, imports) or ""
                root = canon.split(".")[0]
                impure = (
                    canon in _IMPURE_BARE
                    or root in _IMPURE_ROOTS
                    or canon.startswith("numpy.random")
                )
                if impure:
                    yield ctx.diag(
                        self, node,
                        f"impure call {canon}() inside jitted function "
                        f"{fn.name}(); kernels must be side-effect-free "
                        "(use jax.random / jax.debug.print if needed)")
