"""KTL114 — packed row-layout offsets live in one place.

The packed fleet wire format (one f32 row: ``cpu[W] | zone[Z] |
zone_valid[Z] | ratio, denom, dt, mode``) is consumed by THREE
independent implementations that must agree bit-for-bit: the jitted
device programs, the window engines' staging path, and the pure-NumPy
rung-3 mirror (``numpy_fleet_window``). The contract is
:class:`kepler_tpu.parallel.packed.PackedLayout`; this rule forbids the
signature forms of raw layout-offset arithmetic (``w + 2 * z + 1`` and
friends) in subscripts anywhere in the packed/window modules outside
the one ``# keplint: layout-definition``-marked scope, so a hand-typed
offset can never silently diverge from the contract.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register

# the modules that read/write packed rows or wire-frame offsets;
# everything else never sees a layout and stays out of scope
_LAYOUT_SCOPE = (
    "kepler_tpu/parallel/packed.py",
    "kepler_tpu/fleet/window.py",
    "kepler_tpu/fleet/wire.py",
)


def _is_int_const(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool))


def _add_chain_terms(node: ast.expr) -> Iterator[ast.expr]:
    """Flatten a top-level ``a + b - c`` chain into its terms."""
    if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                  (ast.Add, ast.Sub)):
        yield from _add_chain_terms(node.left)
        yield from _add_chain_terms(node.right)
    else:
        yield node


def _is_layout_arith(node: ast.expr) -> bool:
    """True for additive index arithmetic carrying a literal offset —
    ``w + 2 * z``, ``w + 2 * z + 1``, ``2 * z`` — the forms a packed
    column offset takes. Pure name arithmetic (``base + sb``,
    ``k * mb + len(lk)``) is row/shard indexing and stays legal."""
    terms = list(_add_chain_terms(node))
    if len(terms) < 2 and not (terms and isinstance(terms[0], ast.BinOp)):
        return False
    for term in terms:
        if _is_int_const(term):
            return True
        if isinstance(term, ast.BinOp) and isinstance(term.op, ast.Mult):
            if _is_int_const(term.left) or _is_int_const(term.right):
                return True
    return False


def _index_exprs(sl: ast.expr) -> Iterator[ast.expr]:
    """Every scalar index / slice bound inside a subscript's slice."""
    parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
    for part in parts:
        if isinstance(part, ast.Slice):
            for bound in (part.lower, part.upper, part.step):
                if bound is not None:
                    yield bound
        else:
            yield part


@register
class PackedLayoutRule(Rule):
    id = "KTL114"
    name = "packed-layout"
    summary = ("packed row/wire-frame offsets come from a "
               "`layout-definition` scope (PackedLayout, WireLayoutV2); "
               "raw additive-literal index arithmetic is forbidden "
               "outside it")
    rationale = (
        "The packed fleet row is one wire format with three independent "
        "consumers: the jitted device programs (`parallel/packed.py`), "
        "the window engines' delta-staging path (`fleet/window.py`), and "
        "the pure-NumPy rung-3 mirror (`numpy_fleet_window`) that keeps "
        "publishing when the device plane is dead. A hand-typed offset "
        "(`packed[:, w + 2 * z + 1]`) that drifts from the others is a "
        "silent mis-attribution, not a crash — the mirror would read dt "
        "where denom lives and publish plausible wrong watts. All offset "
        "arithmetic therefore lives in `PackedLayout` (the one scope "
        "marked `# keplint: layout-definition`); everywhere else in the "
        "packed/window modules, subscripts carrying additive literal "
        "offsets (`name + 2 * name + const` forms) are findings. Row and "
        "shard indexing (`base + sb`, `k * mb + len(...)`) carries no "
        "literal offsets and stays legal. The wire v2 binary frame "
        "(`fleet/wire.py`) is the same hazard one layer down — its "
        "struct offsets live in the `WireLayoutV2` "
        "`layout-definition` scope, and the encoder/decoder/restamp "
        "paths slice only through names derived from it.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if not ctx.rel_path.startswith(_LAYOUT_SCOPE):
            return
        exempt: list[tuple[int, int]] = []
        for node in ctx.walk_nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if ctx.marker_on(node, "layout-definition") is not None:
                    exempt.append((node.lineno,
                                   node.end_lineno or node.lineno))
        for node in ctx.walk_nodes:
            if not isinstance(node, ast.Subscript):
                continue
            if any(lo <= node.lineno <= hi for lo, hi in exempt):
                continue
            for expr in _index_exprs(node.slice):
                if _is_layout_arith(expr):
                    yield ctx.diag(
                        self, node,
                        "raw packed-layout offset arithmetic in a "
                        "subscript; use PackedLayout fields (the "
                        "`layout-definition` scope in parallel/packed.py) "
                        "so the device program, the window engine and "
                        "the NumPy mirror cannot drift")
                    break
