"""KTL103 — published snapshots stay immutable."""

from __future__ import annotations

import ast
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import qualname

# distinctive Snapshot/NodeUsage/WorkloadTable field names; generic ones
# (ids/meta/node/...) are omitted so unrelated objects don't false-positive
_SNAPSHOT_FIELDS = frozenset({
    "energy_uj", "active_uj", "idle_uj",
    "power_uw", "active_power_uw", "idle_power_uw",
    "window_active_uj", "zone_names",
    "terminated_processes", "terminated_containers",
    "terminated_virtual_machines", "terminated_pods",
})

# the monitor build path constructs snapshots before publication
_SNAPSHOT_BUILDER_SUFFIXES = (
    "kepler_tpu/monitor/monitor.py",
    "kepler_tpu/monitor/snapshot.py",
)


@register
class SnapshotImmutableRule(Rule):
    id = "KTL103"
    name = "snapshot-immutable"
    summary = "no mutation of Snapshot fields outside the monitor build path"
    rationale = (
        "`PowerMonitor.snapshot(clone=False)` hands consumers the "
        "published object itself; the exporter's zero-copy scrape render "
        "is only race-free because a published Snapshot is never mutated "
        "— each refresh builds new arrays and swaps the reference. The "
        "dataclasses are frozen, but numpy array *contents* are not, so "
        "`snap.node.energy_uj[0] = x` (or `object.__setattr__`) would "
        "corrupt concurrent scrapes silently.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if ctx.rel_path.endswith(_SNAPSHOT_BUILDER_SUFFIXES):
            return
        for node in ctx.walk_nodes:
            if isinstance(node, ast.Call):
                canon = qualname(node.func)
                if canon == "object.__setattr__":
                    yield ctx.diag(
                        self, node,
                        "object.__setattr__ defeats frozen-dataclass "
                        "immutability; build a new Snapshot instead")
                continue
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for target in targets:
                # unwrap element writes: snap.node.energy_uj[...] = v
                inner = target
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if not isinstance(inner, ast.Attribute):
                    continue
                if inner.attr not in _SNAPSHOT_FIELDS:
                    continue
                # only a DIRECT `self.<field>` write is own state (the
                # monitor-style accumulator); a deeper chain rooted at
                # self (`self._snap.node.energy_uj[...]`) is a held
                # published snapshot and exactly the bug class
                if (isinstance(inner.value, ast.Name)
                        and inner.value.id == "self"):
                    continue
                yield ctx.diag(
                    self, node,
                    f"mutation of snapshot field {inner.attr!r} outside "
                    "the monitor build path; published snapshots are "
                    "immutable — build new arrays and swap the reference")
