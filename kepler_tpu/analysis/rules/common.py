"""Shared AST helpers for the keplint rule modules.

Everything here is pure lookup over one :class:`FileContext`; the
whole-program analogs (cross-module resolution, call graph) live in
``kepler_tpu.analysis.project``.
"""

from __future__ import annotations

import ast

from kepler_tpu.analysis.engine import FileContext

__all__ = [
    "BLOCKING_BARE",
    "BLOCKING_CALLS",
    "BLOCKING_ROOTS",
    "WALL_CLOCK_CALLS",
    "call_canonical",
    "child_bodies",
    "imports_for",
    "is_blocking_call",
    "jitted_functions",
    "qualname",
    "stmt_exprs",
    "terminal",
    "Imports",
]


def qualname(node: ast.AST) -> str | None:
    """Dotted name for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Imports:
    """Per-file import alias map, so ``_time.time()`` and
    ``from time import time as now; now()`` both canonicalize to
    ``time.time``."""

    def __init__(self, tree: ast.Module) -> None:
        self.alias: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.alias[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.alias[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.alias[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def canonical(self, qual: str | None) -> str | None:
        if not qual:
            return None
        head, _, rest = qual.partition(".")
        head = self.alias.get(head, head)
        return f"{head}.{rest}" if rest else head


def imports_for(ctx: FileContext) -> Imports:
    """One alias map per file, shared by every rule that needs it."""
    cached = getattr(ctx, "_keplint_imports", None)
    if cached is None:
        cached = Imports(ctx.tree)
        ctx._keplint_imports = cached  # type: ignore[attr-defined]
    return cached


def call_canonical(node: ast.Call, imports: Imports) -> str | None:
    return imports.canonical(qualname(node.func))


def terminal(name: str | None) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def stmt_exprs(stmt: ast.AST):
    """A statement's OWN expression nodes (an If's test, a For's iter, an
    Assign's value/targets) — nested statements and function/lambda
    bodies are excluded; statement walks visit those separately."""
    stack = list(ast.iter_child_nodes(stmt))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.stmt, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def child_bodies(node: ast.AST) -> list[list]:
    """Every nested statement list of a compound statement."""
    out = []
    for attr in ("body", "orelse", "finalbody"):
        val = getattr(node, attr, None)
        if val:
            out.append(val)
    for handler in getattr(node, "handlers", []) or []:
        out.append(handler.body)
    for case in getattr(node, "cases", []) or []:
        out.append(case.body)
    return out


WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

# blocking-call vocabulary shared by the lexical KTL106 and the
# call-graph-aware KTL113
BLOCKING_ROOTS = {"subprocess", "socket", "urllib", "requests", "http"}
BLOCKING_CALLS = {"time.sleep"}
BLOCKING_BARE = {"open", "input", "print"}


def is_blocking_call(call: ast.Call, imports: Imports) -> str | None:
    """Canonical name when ``call`` is a blocking/IO call, else None.
    Includes the ``…lower(…).compile(…)`` XLA-compile shape (a
    multi-second stall), matched structurally so ``re.compile`` stays
    out."""
    canon = call_canonical(call, imports) or ""
    root = canon.split(".")[0]
    term = terminal(canon)
    if (canon in BLOCKING_CALLS or term == "sleep"
            or root in BLOCKING_ROOTS or canon in BLOCKING_BARE):
        return canon or term
    func = call.func
    if (isinstance(func, ast.Attribute) and func.attr == "compile"
            and isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Attribute)
            and func.value.func.attr == "lower"):
        return "lower().compile"
    return None


def jitted_functions(ctx: FileContext) -> list[ast.FunctionDef]:
    """Functions decorated with jax.jit (directly or via
    functools.partial) plus kernels passed to pallas_call. Computed once
    per file per run (shared by KTL107 and KTL109) over the cached node
    list."""
    cached = getattr(ctx, "_keplint_jitted", None)
    if cached is not None:
        return cached
    imports = imports_for(ctx)
    out: list[ast.FunctionDef] = []
    kernel_names: set[str] = set()
    fns: list[ast.FunctionDef] = []
    for node in ctx.walk_nodes:
        if isinstance(node, ast.FunctionDef):
            fns.append(node)
        elif isinstance(node, ast.Call):
            canon = call_canonical(node, imports) or ""
            if terminal(canon) == "pallas_call" and node.args:
                name = qualname(node.args[0])
                if name and "." not in name:
                    kernel_names.add(name)
    for node in fns:
        if node.name in kernel_names:
            out.append(node)
            continue
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            canon = imports.canonical(qualname(target)) or ""
            if canon in ("jax.jit", "jit") or canon.endswith(".jit"):
                out.append(node)
                break
            if (isinstance(deco, ast.Call)
                    and terminal(canon) == "partial" and deco.args):
                inner = imports.canonical(qualname(deco.args[0])) or ""
                if inner in ("jax.jit", "jit") or inner.endswith(".jit"):
                    out.append(node)
                    break
    ctx._keplint_jitted = out  # type: ignore[attr-defined]
    return out
