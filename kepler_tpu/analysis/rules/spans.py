"""KTL109 — telemetry span discipline."""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import (
    Imports,
    WALL_CLOCK_CALLS,
    call_canonical,
    imports_for,
    jitted_functions,
    terminal,
)


def _is_span_call(node: ast.AST, imports: Imports) -> bool:
    """A call to the telemetry span API: ``telemetry.span(...)`` (module
    import), ``kepler_tpu.telemetry.span`` (canonicalized from-import),
    or a bare ``span(...)`` whose import resolves into the telemetry
    package."""
    if not isinstance(node, ast.Call):
        return False
    canon = call_canonical(node, imports) or ""
    if terminal(canon) != "span":
        return False
    return canon == "span" or canon.endswith("telemetry.span")


def _walk_span_body(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a span with-block like ``ast.walk`` but WITHOUT descending
    into nested function/lambda definitions: a callback defined inside
    the body runs after the span closed, so its clock calls are not
    span-body timing."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


@register
class SpanDisciplineRule(Rule):
    id = "KTL109"
    name = "span-discipline"
    summary = ("span bodies use monotonic clocks only, and span() never "
               "appears inside jitted/Pallas kernels")
    rationale = (
        "Telemetry spans time their body with `time.monotonic`; a wall-"
        "clock call (`time.time`, `datetime.now`) inside a `with "
        "span(...)` body means the stage's own logic is deriving "
        "durations from a clock NTP can step — the histogram and the "
        "code would disagree about what was measured. (The injected "
        "`self._clock` seam stays legal: seams are the sanctioned wall-"
        "clock source.) And `jax.jit` traces Python once per shape, so "
        "a span inside a jitted/Pallas kernel times the TRACE, not the "
        "execution — it would record one misleading sample per compile "
        "and nothing afterwards (composes with KTL107's purity rule).")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        imports = imports_for(ctx)
        # part 1: wall-clock calls inside `with span(...)` bodies
        for node in ctx.walk_nodes:
            if not isinstance(node, ast.With):
                continue
            if not any(_is_span_call(item.context_expr, imports)
                       for item in node.items):
                continue
            for call in _walk_span_body(node):
                if not isinstance(call, ast.Call):
                    continue
                canon = call_canonical(call, imports)
                if canon in WALL_CLOCK_CALLS:
                    yield ctx.diag(
                        self, call,
                        f"wall-clock call {canon}() inside a telemetry "
                        "span body; spans time with time.monotonic — "
                        "use the monotonic clock or an injected seam")
        # part 2: span() inside jitted / Pallas kernels
        for fn in jitted_functions(ctx):
            for call in ast.walk(fn):
                if _is_span_call(call, imports):
                    yield ctx.diag(
                        self, call,
                        f"telemetry span inside jitted function "
                        f"{fn.name}(); spans run at trace time only — "
                        "instrument the call site, not the kernel")
