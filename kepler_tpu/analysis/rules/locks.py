"""KTL108 (lexical) + KTL111 (whole-program) — lock discipline.

KTL108 is the fast intra-file tier: guarded attribute writes and
requires-lock calls checked within one class, one file. KTL111 runs on
the :class:`~kepler_tpu.analysis.project.ProjectContext` and sees what
the lexical tier structurally cannot:

- the **lock-acquisition order graph** across call edges — cycles are
  potential deadlocks (RacerD-style, PAPERS.md precedent), and
  acquiring a known non-reentrant lock that is already held (directly
  or through a helper call chain) is a guaranteed one;
- ``requires-lock`` calls and ``guarded-by`` writes **through receiver
  objects of other classes/modules** (``self._spool._append_locked()``
  from the agent; a subclass writing a base-guarded attribute), which
  KTL108's ``self.``-only view cannot resolve.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import (
    Diagnostic,
    FileContext,
    ProjectRule,
    Rule,
    register,
)
from kepler_tpu.analysis.rules.common import qualname

# ---------------------------------------------------------------------------
# KTL108 — lock-guarded attributes (lexical tier)
# ---------------------------------------------------------------------------


def _with_locks(node: ast.With) -> set[str]:
    out: set[str] = set()
    for item in node.items:
        qual = qualname(item.context_expr)
        if qual and qual.startswith("self."):
            out.add(qual[len("self."):])
    return out


@register
class LockGuardedRule(Rule):
    id = "KTL108"
    name = "lock-guarded"
    summary = ("attributes annotated `# keplint: guarded-by=<lock>` are "
               "only written under `with self.<lock>`")
    rationale = (
        "The monitor/aggregator publish data to scrape threads through "
        "attributes whose write side is documented as lock-guarded "
        "(reads are lock-free reference swaps). The contract is machine-"
        "readable: annotate the attribute in __init__ with `# keplint: "
        "guarded-by=_lock`; functions that may only be called with the "
        "lock held carry `# keplint: requires-lock=_lock`, and every "
        "call to them must itself hold the lock (a small lexical effect "
        "system — KTL111 extends it across call edges and modules).")

    _EXEMPT_METHODS = frozenset({"__init__", "init"})

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        for cls in ctx.walk_nodes:
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Diagnostic]:
        guarded: dict[str, str] = {}
        requires: dict[str, str] = {}
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for fn in methods:
            lock = ctx.marker_on(fn, "requires-lock")
            if lock:
                requires[fn.name] = lock
            if fn.name not in self._EXEMPT_METHODS:
                continue
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                for kind, arg in ctx.directives.get(stmt.lineno, []):
                    if kind != "guarded-by" or not arg:
                        continue
                    targets = (stmt.targets
                               if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            guarded[t.attr] = arg
        if not guarded and not requires:
            return
        for fn in methods:
            if fn.name in self._EXEMPT_METHODS:
                continue
            held: set[str] = set()
            if fn.name in requires:
                held = {requires[fn.name]}
            yield from self._walk(ctx, fn, list(fn.body), held,
                                  guarded, requires)

    def _walk(self, ctx: FileContext, fn: ast.AST, body: list,
              held: set[str], guarded: dict[str, str],
              requires: dict[str, str]) -> Iterator[Diagnostic]:
        for node in body:
            extra: set[str] = set()
            if isinstance(node, ast.With):
                extra = _with_locks(node)
            yield from self._check_stmt(ctx, fn, node, held | extra,
                                        guarded, requires)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure runs later, possibly without the lock held
                yield from self._walk(ctx, fn, node.body, set(),
                                      guarded, requires)
                continue
            for child_body in self._child_bodies(node):
                yield from self._walk(ctx, fn, child_body, held | extra,
                                      guarded, requires)

    @staticmethod
    def _child_bodies(node: ast.AST) -> list[list]:
        out = []
        for attr in ("body", "orelse", "finalbody"):
            val = getattr(node, attr, None)
            if val:
                out.append(val)
        for handler in getattr(node, "handlers", []) or []:
            out.append(handler.body)
        return out

    def _check_stmt(self, ctx: FileContext, fn: ast.AST, node: ast.AST,
                    held: set[str], guarded: dict[str, str],
                    requires: dict[str, str]) -> Iterator[Diagnostic]:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for target in targets:
            inner = target
            while isinstance(inner, ast.Subscript):
                inner = inner.value
            if (isinstance(inner, ast.Attribute)
                    and isinstance(inner.value, ast.Name)
                    and inner.value.id == "self"
                    and inner.attr in guarded
                    and guarded[inner.attr] not in held):
                yield ctx.diag(
                    self, node,
                    f"write to self.{inner.attr} (guarded by "
                    f"self.{guarded[inner.attr]}) outside `with "
                    f"self.{guarded[inner.attr]}` in "
                    f"{getattr(fn, 'name', '?')}()")
        # calls into requires-lock functions need the lock too; examine
        # only the expressions attached to THIS statement (nested
        # statements are visited by _walk, so they are never double-
        # counted)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.expr):
                continue
            for expr in ast.walk(child):
                if not isinstance(expr, ast.Call):
                    continue
                qual = qualname(expr.func) or ""
                if not qual.startswith("self."):
                    continue
                callee = qual[len("self."):]
                if "." in callee or callee not in requires:
                    continue
                if requires[callee] not in held:
                    yield ctx.diag(
                        self, expr,
                        f"call to self.{callee}() requires holding "
                        f"self.{requires[callee]} (marked requires-lock)"
                        " — wrap the call in `with self."
                        f"{requires[callee]}:`")


# ---------------------------------------------------------------------------
# KTL111 — lock order + interprocedural lock contracts (whole-program)
# ---------------------------------------------------------------------------

# lock kinds that are NOT re-entrant: acquiring one that is already held
# on the same thread deadlocks immediately
_NON_REENTRANT = frozenset({"Lock", "Semaphore"})


@register
class LockOrderRule(ProjectRule):
    id = "KTL111"
    name = "lock-order"
    summary = ("no cycles in the cross-module lock-acquisition graph, no "
               "re-acquisition of held non-reentrant locks, and "
               "`requires-lock`/`guarded-by` contracts hold through "
               "helper calls and across modules")
    rationale = (
        "The device plane is genuinely concurrent (ingest HTTP threads, "
        "the pipelined window thread, the monitor refresh loop, the "
        "_FetchWorker), and KTL108's lexical view stops at the first "
        "helper-function hop. KTL111 derives the lock-acquisition graph "
        "from `with`-lock regions across resolved call edges: a cycle "
        "between two locks is a potential deadlock the moment two "
        "threads interleave; acquiring a known `threading.Lock` that is "
        "already held (even two frames up, through helpers) is a "
        "guaranteed one; and a call to a `requires-lock` method of "
        "ANOTHER object/class (`self._spool._append_locked()`) or a "
        "write to another class's `guarded-by` attribute must hold that "
        "receiver's lock — contracts the per-file tier cannot resolve.")

    def check_project(self, project) -> Iterable[Diagnostic]:
        yield from self._check_reacquire(project)
        yield from self._check_cycles(project)
        yield from self._check_cross_requires(project)
        yield from self._check_cross_guarded(project)

    # -- self-deadlock ----------------------------------------------------

    def _check_reacquire(self, project) -> Iterator[Diagnostic]:
        # lexical: `with self._lock` while self._lock already held
        for info in project.functions.values():
            for lid, qual, node, held in info.acquires:
                if lid in held:
                    kind = project.lock_kind(lid) or "unknown kind"
                    if kind in ("RLock", "Condition"):
                        continue  # re-entrant by construction
                    yield info.ctx.diag(
                        self, node,
                        f"acquisition of {qual} while already held in "
                        f"{info.qual}() ({kind}); a non-reentrant lock "
                        "self-deadlocks — split the locked section or "
                        "mark the callee requires-lock")
        # call-mediated: calling a function whose closure re-acquires a
        # lock held at the site (known non-reentrant kinds only: an
        # unknown lock reached conditionally is too speculative to fail)
        for sites in project.calls.values():
            for site in sites:
                callee = project.functions[site.callee]
                req = callee.marker("requires-lock")
                for lid in site.held_ids:
                    if project.lock_kind(lid) not in _NON_REENTRANT:
                        continue
                    if lid not in callee.closure_acquires:
                        continue
                    # a requires-lock callee legitimately expects the
                    # lock; its own `with` would be flagged above
                    if req and lid.endswith(f".{req}"):
                        continue
                    yield site.ctx.diag(
                        self, site.node,
                        f"call to {callee.qual}() while holding "
                        f"{self._short(lid)}; the callee (or something "
                        "it calls) re-acquires that non-reentrant lock "
                        "— deadlock")

    # -- cycles ------------------------------------------------------------

    def _check_cycles(self, project) -> Iterator[Diagnostic]:
        edges: dict[str, dict[str, tuple]] = {}  # a → b → (ctx, node, via)

        def add(a: str, b: str, ctx, node, via: str) -> None:
            if a == b:
                return  # self-deadlock handled above
            edges.setdefault(a, {}).setdefault(b, (ctx, node, via))

        for info in project.functions.values():
            for lid, qual, node, held in info.acquires:
                for h in held:
                    add(h, lid, info.ctx, node, info.qual)
        for sites in project.calls.values():
            for site in sites:
                callee = project.functions[site.callee]
                for lid in callee.closure_acquires:
                    for h in site.held_ids:
                        add(h, lid, site.ctx, site.node,
                            f"{project.functions[site.caller].qual} → "
                            f"{callee.qual}")
        # DFS cycle detection, reporting each cycle once at its smallest
        # participating edge
        seen_cycles: set[frozenset] = set()
        color: dict[str, int] = {}
        stack: list[str] = []

        def dfs(n: str) -> Iterator[tuple]:
            color[n] = 1
            stack.append(n)
            for m in sorted(edges.get(n, {})):
                if color.get(m, 0) == 1:
                    cycle = stack[stack.index(m):] + [m]
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        yield tuple(cycle)
                elif color.get(m, 0) == 0:
                    yield from dfs(m)
            stack.pop()
            color[n] = 2

        for n in sorted(edges):
            if color.get(n, 0) == 0:
                for cycle in dfs(n):
                    a, b = cycle[0], cycle[1]
                    ctx, node, via = edges[a][b]
                    order = " → ".join(self._short(x) for x in cycle)
                    yield ctx.diag(
                        self, node,
                        f"lock-order cycle {order} (this edge acquired "
                        f"via {via}); two threads taking the locks in "
                        "opposite order deadlock — impose one global "
                        "acquisition order")

    # -- cross-class requires-lock ----------------------------------------

    def _check_cross_requires(self, project) -> Iterator[Diagnostic]:
        for sites in project.calls.values():
            for site in sites:
                callee = project.functions[site.callee]
                req = callee.marker("requires-lock")
                if not req or not site.receiver:
                    continue
                caller = project.functions[site.caller]
                if (site.receiver == "self"
                        and callee.class_key == caller.class_key):
                    continue  # same class, same file: KTL108's tier
                if caller.name in ("__init__", "init"):
                    continue
                needed = f"{site.receiver}.{req}"
                if needed in site.held_raw:
                    continue
                yield site.ctx.diag(
                    self, site.node,
                    f"call to {callee.qual}() requires holding "
                    f"{needed} (marked requires-lock={req}) — the "
                    "lexical tier cannot see this contract from "
                    f"{caller.qual}(); wrap the call in `with {needed}:`")

    # -- cross-class guarded-by writes ------------------------------------

    def _check_cross_guarded(self, project) -> Iterator[Diagnostic]:
        for info in project.functions.values():
            if info.name in ("__init__", "init"):
                continue
            ltypes = None
            for qual, node, held_raw in info.writes:
                parts = qual.split(".")
                recv, attr = ".".join(parts[:-1]), parts[-1]
                owner_key = None
                if recv == "self" and info.class_key:
                    # inherited guarded attrs only: own-class ones are
                    # KTL108's (and would double-report)
                    own = project.classes.get(info.class_key)
                    if own is not None and attr in own.guarded:
                        continue
                    owner_key = info.class_key
                elif parts[0] == "self" and len(parts) == 3:
                    owner_key = project._attr_type_on(
                        info.class_key, parts[1])
                elif len(parts) == 2:
                    if ltypes is None:
                        ltypes = project.local_types(info)
                    owner_key = ltypes.get(parts[0])
                if owner_key is None:
                    continue
                lock = project.guarded_on(owner_key, attr)
                if not lock:
                    continue
                needed = f"{recv}.{lock}"
                if needed in held_raw:
                    continue
                yield info.ctx.diag(
                    self, node,
                    f"write to {qual} (guarded by {lock} on "
                    f"{self._short(owner_key)}) outside `with {needed}` "
                    f"in {info.qual}() — cross-class guarded-by "
                    "violation the lexical tier cannot see")

    @staticmethod
    def _short(lock_or_class_id: str) -> str:
        """Strip the module prefix for readable messages:
        ``kepler_tpu.fleet.aggregator:Aggregator._lock`` →
        ``Aggregator._lock``."""
        _, _, tail = lock_or_class_id.rpartition(":")
        return tail or lock_or_class_id
