"""KTL112 — untrusted-input taint tracking (whole-program).

Values originating from the wire (``# keplint: taint-source`` functions
such as ``peek_node_name``) or from HTTP request surfaces (``.headers``
/ ``.path`` / ``.body`` / query reads inside ``http-handler``-role
functions) are **tainted** until they pass a sanitizer — a function
marked ``# keplint: sanitizes`` (validate/clamp/coerce helpers, or
``decode_report`` itself, which rejects malformed input) or a built-in
coercion (``int``/``float``/…). Taint propagates through assignments,
string operations, and **resolved call edges** (a tainted argument
taints the callee's parameter; a function returning tainted data taints
its call sites), so a wire name laundered through two helper frames is
still caught at the sink.

Sinks — where hostile bytes become unbounded metric cardinality, store
churn, or log forgery:

- Prometheus label values (``.labels(...)`` args, ``add_metric([...])``
  label lists);
- keys inserted into object-attached stores (``self._nodes[name] = …``:
  the scoreboard/tracker/dedup bounded-LRU class);
- sequence indexing with a tainted index;
- arguments of logging calls (newline injection forges log lines);
- any argument to a function marked ``# keplint: taint-sink``.

A membership guard (``if x in allowed:``) clears taint in its body, and
functions marked ``sanitizes``/``taint-source`` are themselves exempt
from sink checks — they ARE the validation boundary.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, ProjectRule, register
from kepler_tpu.analysis.rules.common import (
    call_canonical,
    child_bodies,
    imports_for,
    qualname,
    stmt_exprs,
    terminal,
)

# request-object surfaces that carry raw network bytes
_REQUEST_ATTRS = frozenset({
    "headers", "path", "body", "rfile", "requestline", "query",
})
_HANDLER_ROLE = "http-handler"

# built-in coercions whose result cannot carry hostile bytes
_COERCERS = frozenset({
    "int", "float", "bool", "len", "abs", "round", "min", "max",
    "hash", "ord", "html.escape",
})

_LOG_METHODS = frozenset({"debug", "info", "warning", "error",
                          "exception", "critical"})
_LOG_RECEIVERS = frozenset({"log", "logger", "logging"})

_MAX_ITERS = 12


class _FnTaint:
    """Mutable interprocedural summary for one function."""

    __slots__ = ("params", "returns")

    def __init__(self) -> None:
        self.params: dict[str, str] = {}   # param name → origin
        self.returns: str | None = None    # origin when return is tainted


@register
class TaintRule(ProjectRule):
    id = "KTL112"
    name = "untrusted-taint"
    summary = ("wire/HTTP-derived values must pass a registered "
               "sanitizer before reaching label values, store keys, "
               "sequence indexes, or log calls")
    rationale = (
        "Node names and header fields come off an untrusted network; PR "
        "8 found by hand that junk wire names were evicting real "
        "scoreboard rows, and every Prometheus label minted from such a "
        "value is unbounded series cardinality. The fix discipline is a "
        "visible chokepoint: sources (`taint-source`, HTTP request "
        "surfaces) mark data hostile, sanitizers (`sanitizes` — "
        "validate/clamp/coerce) launder it, and the call-graph "
        "propagation means a helper hop (ingest → degradation "
        "accounting → scoreboard insert) cannot silently drop the "
        "obligation the way a per-file check would.")

    def check_project(self, project) -> Iterable[Diagnostic]:
        summaries: dict[str, _FnTaint] = {
            fid: _FnTaint() for fid in project.functions}
        # fixpoint: propagate param/return taint over the call graph
        for _ in range(_MAX_ITERS):
            changed = False
            for fid, info in project.functions.items():
                if not self._seeded(project, info, summaries):
                    continue
                changed |= self._analyze(project, info, summaries,
                                         sinks=None)
            if not changed:
                break
        diags: list[Diagnostic] = []
        for fid, info in project.functions.items():
            if not self._seeded(project, info, summaries):
                continue
            if info.marker("sanitizes") is not None \
                    or info.marker("taint-source") is not None:
                continue  # the validation boundary works on raw bytes
            self._analyze(project, info, summaries, sinks=diags)
        # loop bodies are walked twice for loop-carried taint, which can
        # duplicate a sink finding — diagnostics are frozen/hashable
        return sorted(set(diags))

    @staticmethod
    def _seeded(project, info, summaries: dict) -> bool:
        """Only functions that can possibly see taint are analyzed: they
        have tainted params, run under the http-handler role, ARE a
        source, or call a source / a function whose return is (so far
        known to be) tainted — everything else is skipped, which is what
        keeps the whole-program pass inside the wall-clock budget.
        Re-evaluated every fixpoint iteration, so return-taint
        discovered mid-pass seeds its callers on the next one."""
        if summaries[info.func_id].params \
                or _HANDLER_ROLE in info.roles \
                or info.marker("taint-source") is not None:
            return True
        for site in project.calls.get(info.func_id, []):
            callee = project.functions[site.callee]
            if callee.marker("taint-source") is not None \
                    or summaries[callee.func_id].returns:
                return True
        return False

    # -- one function ------------------------------------------------------

    def _analyze(self, project, info, summaries,
                 sinks: list | None) -> bool:
        """Walk ``info`` propagating taint; update interprocedural
        summaries (returns True when they grew). With ``sinks`` set,
        emit sink diagnostics instead."""
        my = summaries[info.func_id]
        env: dict[str, str] = dict(my.params)
        imports = imports_for(info.ctx)
        http_role = _HANDLER_ROLE in info.roles
        changed = False

        def taint_of(node: ast.AST) -> str | None:
            if isinstance(node, ast.Name):
                return env.get(node.id)
            if isinstance(node, ast.Attribute):
                # `.path`/`.headers`/… on anything a handler holds is
                # request surface — EXCEPT attributes of imported
                # modules (`os.path`, `urllib.parse`), which are code,
                # not data off the wire
                if http_role and node.attr in _REQUEST_ATTRS \
                        and not (isinstance(node.value, ast.Name)
                                 and node.value.id in imports.alias):
                    return f"HTTP request surface .{node.attr}"
                return taint_of(node.value)
            if isinstance(node, ast.Subscript):
                return taint_of(node.value) or (
                    taint_of(node.slice)
                    if not isinstance(node.slice, ast.Slice) else None)
            if isinstance(node, ast.Call):
                return call_taint(node)
            if isinstance(node, (ast.BinOp,)):
                return taint_of(node.left) or taint_of(node.right)
            if isinstance(node, ast.BoolOp):
                for v in node.values:
                    t = taint_of(v)
                    if t:
                        return t
            if isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.FormattedValue):
                        t = taint_of(v.value)
                        if t:
                            return t
                return None
            if isinstance(node, ast.FormattedValue):
                return taint_of(node.value)
            if isinstance(node, ast.IfExp):
                return taint_of(node.body) or taint_of(node.orelse)
            if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                for elt in node.elts:
                    t = taint_of(elt)
                    if t:
                        return t
                return None
            if isinstance(node, ast.Starred):
                return taint_of(node.value)
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for gen in node.generators:
                    t = taint_of(gen.iter)
                    if t:
                        return t
                return None
            if isinstance(node, ast.Await):
                return taint_of(node.value)
            # Compare / Constant / Lambda / comprehension vars: clean
            return None

        def call_taint(call: ast.Call) -> str | None:
            nonlocal changed
            canon = call_canonical(call, imports) or ""
            callee_id, _recv = project.resolve_call(
                info, call, local_types)
            callee = project.functions.get(callee_id) \
                if callee_id else None
            arg_taints = [taint_of(a) for a in call.args]
            kw_taints = {kw.arg: taint_of(kw.value)
                         for kw in call.keywords if kw.arg}
            if callee is not None:
                # propagate into the callee's parameters
                csum = summaries[callee.func_id]
                params = self._param_names(callee)
                for i, t in enumerate(arg_taints):
                    if t and i < len(params) \
                            and params[i] not in csum.params:
                        csum.params[params[i]] = (
                            f"{t}, via {info.qual}()")
                        changed = True
                for name, t in kw_taints.items():
                    if t and name in params and name not in csum.params:
                        csum.params[name] = f"{t}, via {info.qual}()"
                        changed = True
                if callee.marker("sanitizes") is not None:
                    return None
                if callee.marker("taint-source") is not None:
                    return f"{callee.name}() [taint-source]"
                if csum.returns:
                    return f"{callee.name}() → {csum.returns}"
            if canon in _COERCERS or terminal(canon) in ("isoformat",):
                return None
            # method on a tainted receiver (str ops etc.) or any
            # tainted argument: conservatively tainted result
            recv_taint = None
            if isinstance(call.func, ast.Attribute):
                recv_taint = taint_of(call.func.value)
            for t in [recv_taint] + arg_taints + list(kw_taints.values()):
                if t:
                    return t
            return None

        def check_sinks(stmt: ast.AST) -> None:
            if sinks is None:
                return
            for node in self._stmt_exprs(stmt):
                if isinstance(node, ast.Subscript) \
                        and isinstance(node.ctx, ast.Load) \
                        and not isinstance(node.slice, ast.Slice) \
                        and not isinstance(node.slice, ast.Constant):
                    t = taint_of(node.slice)
                    if t:
                        sinks.append(info.ctx.diag(
                            self, node,
                            f"tainted value ({t}) used as a sequence/"
                            f"mapping index in {info.qual}(); validate "
                            "or clamp it through a registered sanitizer "
                            "first"))
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) \
                    else None
                if attr == "labels":
                    for arg in list(node.args) + [kw.value for kw in
                                                  node.keywords]:
                        t = taint_of(arg)
                        if t:
                            sinks.append(info.ctx.diag(
                                self, node,
                                f"tainted value ({t}) used as a "
                                f"Prometheus label in {info.qual}(); "
                                "unbounded hostile cardinality — "
                                "sanitize first"))
                elif attr == "add_metric" and node.args:
                    first = node.args[0]
                    elts = first.elts if isinstance(
                        first, (ast.List, ast.Tuple)) else [first]
                    for elt in elts:
                        t = taint_of(elt)
                        if t:
                            sinks.append(info.ctx.diag(
                                self, node,
                                f"tainted value ({t}) used as a "
                                f"Prometheus label in {info.qual}(); "
                                "unbounded hostile cardinality — "
                                "sanitize first"))
                elif attr in _LOG_METHODS and isinstance(
                        func, ast.Attribute):
                    recv = terminal(qualname(func.value) or "")
                    if recv in _LOG_RECEIVERS:
                        for arg in node.args:
                            t = taint_of(arg)
                            if t:
                                sinks.append(info.ctx.diag(
                                    self, node,
                                    f"tainted value ({t}) in a log "
                                    f"call in {info.qual}(); newline "
                                    "injection forges log lines — "
                                    "sanitize first"))
                                break
                callee_id, _ = project.resolve_call(
                    info, node, local_types)
                callee = project.functions.get(callee_id) \
                    if callee_id else None
                if callee is not None and \
                        callee.marker("taint-sink") is not None:
                    what = callee.marker("taint-sink") or "sink"
                    for arg in list(node.args) + [kw.value for kw in
                                                  node.keywords]:
                        t = taint_of(arg)
                        if t:
                            sinks.append(info.ctx.diag(
                                self, node,
                                f"tainted value ({t}) passed to "
                                f"{callee.name}() (taint-sink"
                                f"{'=' + what if what else ''}) in "
                                f"{info.qual}(); sanitize first"))
                            break

        def assign_target(target: ast.AST, t: str | None,
                          stmt: ast.AST) -> None:
            if isinstance(target, ast.Name):
                if t:
                    env[target.id] = t
                else:
                    env.pop(target.id, None)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    assign_target(elt, t, stmt)
            elif isinstance(target, ast.Starred):
                assign_target(target.value, t, stmt)
            elif isinstance(target, ast.Subscript) and sinks is not None:
                # store-key sink: obj.attr[tainted_key] = …
                inner = target.value
                if isinstance(inner, ast.Attribute) \
                        and not isinstance(target.slice, ast.Slice):
                    kt = taint_of(target.slice)
                    if kt:
                        sinks.append(info.ctx.diag(
                            self, stmt,
                            f"tainted value ({kt}) inserted as a key "
                            f"into {qualname(inner) or 'a store'} in "
                            f"{info.qual}(); hostile names churn/evict "
                            "bounded stores — sanitize first"))

        def walk(stmts: list) -> None:
            nonlocal changed
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                check_sinks(stmt)
                # taint_of on call expressions also drives propagation
                for expr in self._stmt_exprs(stmt):
                    if isinstance(expr, ast.Call):
                        taint_of(expr)
                if isinstance(stmt, ast.Assign):
                    t = taint_of(stmt.value)
                    for target in stmt.targets:
                        assign_target(target, t, stmt)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    assign_target(stmt.target, taint_of(stmt.value),
                                  stmt)
                elif isinstance(stmt, ast.AugAssign):
                    t = taint_of(stmt.value) or taint_of(stmt.target)
                    assign_target(stmt.target, t, stmt)
                elif isinstance(stmt, ast.Return) and stmt.value:
                    t = taint_of(stmt.value)
                    if t and my.returns is None:
                        my.returns = t
                        changed = True
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    assign_target(stmt.target, taint_of(stmt.iter),
                                  stmt)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        if item.optional_vars is not None:
                            assign_target(item.optional_vars,
                                          taint_of(item.context_expr),
                                          stmt)
                if isinstance(stmt, ast.If):
                    cleared = self._membership_guard(stmt.test)
                    saved = {n: env[n] for n in cleared if n in env}
                    for n in cleared:
                        env.pop(n, None)
                    walk(stmt.body)
                    env.update(saved)
                    walk(stmt.orelse)
                    continue
                for body in self._child_bodies(stmt):
                    walk(body)
                    if isinstance(stmt, (ast.For, ast.AsyncFor,
                                         ast.While)):
                        walk(body)  # second pass: loop-carried taint

        local_types = project.local_types(info)
        walk(list(info.node.body))
        return changed

    @staticmethod
    def _membership_guard(test: ast.AST) -> set[str]:
        """``if x in allowed:`` validates ``x`` for the guarded body."""
        out: set[str] = set()
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], ast.In) \
                and isinstance(test.left, ast.Name):
            out.add(test.left.id)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for v in test.values:
                out |= TaintRule._membership_guard(v)
        return out

    @staticmethod
    def _param_names(info) -> list[str]:
        args = info.node.args
        names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        return names

    _stmt_exprs = staticmethod(stmt_exprs)
    _child_bodies = staticmethod(child_bodies)
