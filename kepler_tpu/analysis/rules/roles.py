"""KTL113 — thread-role discipline (whole-program).

Thread roles are declared at the roots (``# keplint: thread-role=<role>``
on a def or class; the ``hot-loop`` marker roots the ``hot-loop`` role;
callables registered through a ``# keplint: role-registrar=<role>``
function — ``APIServer.register`` — root the ``http-handler`` role) and
propagate along resolved call edges, stopping at ``# keplint:
role-boundary`` seams. Two disciplines are enforced on top:

- **hot-loop reachability**: a blocking call any number of frames below
  a hot-loop root stalls the refresh cadence exactly like a lexical one
  (KTL106 generalized through the call graph);
- **handler isolation**: classes marked ``# keplint:
  forbid-role=http-handler`` (the live window engines) may not be
  called from HTTP-handler-role code except through methods marked
  ``# keplint: allow-role=http-handler`` — pinning PR 8's invariant
  that handlers read *published snapshots*, never live engine state.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import Diagnostic, ProjectRule, register
from kepler_tpu.analysis.rules.common import imports_for, is_blocking_call

_HOT_ROLE = "hot-loop"


def _roles_in(arg: str | None) -> set[str]:
    if not arg:
        return set()
    return {p.strip() for p in arg.split(",") if p.strip()}


@register
class ThreadRoleRule(ProjectRule):
    id = "KTL113"
    name = "thread-role"
    summary = ("no blocking calls reachable from hot-loop roots through "
               "any call chain, and HTTP-handler-role code stays off "
               "classes marked `forbid-role=http-handler` except via "
               "`allow-role` accessors")
    rationale = (
        "KTL106 sees a sleep inside a marked function; it is blind to "
        "the same sleep one helper call away — and the refresh loop "
        "stalls identically either way. KTL113 propagates thread roles "
        "from declared roots (refresh loop, agent thread, ingest and "
        "debug HTTP handlers, _FetchWorker, shutdown paths) along the "
        "project call graph, stopping at `role-boundary` seams (the "
        "meter does I/O by design), and flags blocking calls reachable "
        "under the hot-loop role with the full call chain. It also pins "
        "the PR 8 introspection invariant: HTTP handler threads serve "
        "PUBLISHED snapshots; one refactor that reaches live engine "
        "state (classes marked forbid-role=http-handler) is a data race "
        "with the pipelined window thread, caught here at the call edge.")

    def check_project(self, project) -> Iterable[Diagnostic]:
        yield from self._check_hot_reachability(project)
        yield from self._check_forbidden(project)

    def _check_hot_reachability(self, project) -> Iterator[Diagnostic]:
        for info in project.functions.values():
            if _HOT_ROLE not in info.roles:
                continue
            if info.marker("hot-loop") is not None:
                continue  # a root: KTL106's lexical tier owns it
            imports = imports_for(info.ctx)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                canon = is_blocking_call(node, imports)
                if not canon:
                    continue
                chain = project.role_chain(info.func_id, _HOT_ROLE)
                yield info.ctx.diag(
                    self, node,
                    f"blocking call {canon}() in {info.qual}() is "
                    "reachable from a hot-loop root via "
                    f"{' → '.join(chain)}; the refresh path must not "
                    "sleep or do I/O beyond the meter seam "
                    "(role-boundary)")

    def _check_forbidden(self, project) -> Iterator[Diagnostic]:
        for sites in project.calls.values():
            for site in sites:
                callee = project.functions[site.callee]
                forbidden = _roles_in(project.class_marker(
                    callee.class_key, "forbid-role"))
                if not forbidden:
                    continue
                caller = project.functions[site.caller]
                hit = forbidden & set(caller.roles)
                if not hit:
                    continue
                allowed = _roles_in(callee.marker("allow-role"))
                hit -= allowed
                # a constructor call is wiring, not state access
                if callee.name == "__init__":
                    continue
                for role in sorted(hit):
                    chain = project.role_chain(caller.func_id, role)
                    yield site.ctx.diag(
                        self, site.node,
                        f"{role}-role code ({' → '.join(chain)}) calls "
                        f"{callee.qual}() on a class marked "
                        f"forbid-role={role}; reach this state only "
                        "through its published-snapshot accessors "
                        "(mark the method allow-role to sanction)")
