"""KTL101 — monotonic clocks in timing logic."""

from __future__ import annotations

import ast
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import (
    WALL_CLOCK_CALLS,
    call_canonical,
    imports_for,
)


@register
class MonotonicClockRule(Rule):
    id = "KTL101"
    name = "monotonic-clock"
    summary = ("no wall-clock calls in modules marked "
               "`# keplint: monotonic-only`")
    rationale = (
        "Backoff, rate-limit, circuit-breaker, and watchdog arithmetic "
        "breaks when NTP steps the wall clock (the exact bug class PR 1 "
        "fixed by hand). Timing modules declare `# keplint: "
        "monotonic-only` and may then only *call* `time.monotonic()` or "
        "an injected clock seam; referencing `time.time` as an injectable "
        "default stays legal because the seam is the point. Scope "
        "includes hack/ and benchmarks/: bench timing math breaks the "
        "same way production timing math does.")
    tree_scope = ("kepler_tpu", "hack", "benchmarks")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if not ctx.has_file_marker("monotonic-only"):
            return
        imports = imports_for(ctx)
        for node in ctx.walk_nodes:
            if not isinstance(node, ast.Call):
                continue
            canon = call_canonical(node, imports)
            if canon in WALL_CLOCK_CALLS:
                yield ctx.diag(
                    self, node,
                    f"wall-clock call {canon}() in a monotonic-only "
                    "module; use time.monotonic() or the injected "
                    "clock/monotonic seam")
