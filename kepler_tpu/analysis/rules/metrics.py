"""KTL105 — Prometheus metric naming."""

from __future__ import annotations

import ast
import re
from typing import Iterable

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import qualname, terminal

_METRIC_CTORS = {
    "CounterMetricFamily", "GaugeMetricFamily", "HistogramMetricFamily",
    "SummaryMetricFamily", "InfoMetricFamily", "UntypedMetricFamily",
    "Counter", "Gauge", "Histogram", "Summary", "Info", "Enum",
}
_METRIC_NAME = re.compile(r"^kepler_[a-z][a-z0-9_]*$")
# approved final name tokens: units first, then semantic/count forms
_UNIT_TOKENS = frozenset({
    "total", "joules", "watts", "seconds", "ratio", "ms", "bytes",
    "celsius", "info", "healthy", "degraded", "flops", "state",
    "epoch", "version",
})
_COUNT_TOKENS = frozenset({"nodes", "workloads", "records", "rows",
                           "shards", "windows", "inflight",
                           # elastic membership (ISSUE 16): ring
                           # replicas are counted, not measured
                           "peers", "replicas"})
# reference-parity names grandfathered in (match the upstream exporter)
_EXACT_ALLOW = frozenset({"kepler_node_cpu_power_meter"})


def _metric_name_literal(arg: ast.expr) -> tuple[str | None, str | None]:
    """(full_constant_name, trailing_literal) for the first ctor arg.

    f-strings return (None, trailing-literal-if-any): the charset of the
    dynamic part can't be checked, but the unit suffix usually can.
    """
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not arg.value.startswith("kepler_"):
            return None, None  # another namespace: out of scope
        return arg.value, arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("kepler_")):
            return None, None
        last = arg.values[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, str):
            return None, last.value
        return None, ""  # dynamic tail: unverifiable
    return None, None


@register
class MetricNameRule(Rule):
    id = "KTL105"
    name = "metric-name"
    summary = ("metric names match `kepler_[a-z0-9_]+` and end with a "
               "unit suffix; counters end `_total`")
    rationale = (
        "Dashboards and recording rules key on metric names; drift "
        "(`kepler_fleet_reports` vs `..._total`) silently splits series "
        "across versions. prometheus_client appends `_total` to counter "
        "samples regardless of the declared family name, so a counter "
        "declared without it exposes a name that exists nowhere in the "
        "source — grep-proofing requires declaring the exposed name. "
        "Scope includes hack/ and benchmarks/: bench rows and tooling "
        "emit `kepler_*` names that dashboards join against the "
        "production series, so they obey the same grammar.")
    tree_scope = ("kepler_tpu", "hack", "benchmarks")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        for node in ctx.walk_nodes:
            if not (isinstance(node, ast.Call) and node.args):
                continue
            ctor = terminal(qualname(node.func))
            if ctor not in _METRIC_CTORS:
                continue
            full, tail = _metric_name_literal(node.args[0])
            if full is None and tail is None:
                continue  # not a kepler metric literal
            shown = full if full is not None else f"…{tail}"
            if full is not None:
                if full in _EXACT_ALLOW:
                    continue
                if not _METRIC_NAME.match(full):
                    yield ctx.diag(
                        self, node,
                        f"metric name {full!r} must match "
                        "kepler_[a-z][a-z0-9_]*")
                    continue
            is_counter = ctor.startswith("Counter")
            if is_counter:
                if tail is not None and not tail.endswith("_total"):
                    yield ctx.diag(
                        self, node,
                        f"counter {shown!r} must be declared with the "
                        "exposed `_total` suffix")
                continue
            if tail is None or not tail:
                continue  # dynamic tail: cannot verify the suffix
            token = tail.rsplit("_", 1)[-1]
            if token not in _UNIT_TOKENS and token not in _COUNT_TOKENS:
                yield ctx.diag(
                    self, node,
                    f"metric {shown!r} lacks a recognized unit suffix "
                    f"(one of {', '.join(sorted(_UNIT_TOKENS))} or a "
                    "count noun); name the unit or extend the rule's "
                    "token set deliberately")
