"""KTL110 — donated arrays are dead after the donating call."""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from kepler_tpu.analysis.engine import Diagnostic, FileContext, Rule, register
from kepler_tpu.analysis.rules.common import (
    call_canonical,
    imports_for,
    qualname,
)

# the device-resident window plane: everywhere the repo donates buffers
_DONATE_SCOPE = (
    "kepler_tpu/parallel/",
    "kepler_tpu/fleet/aggregator.py",
    "kepler_tpu/fleet/window.py",
)


def _donate_positions(node: ast.expr) -> tuple[int, ...] | None:
    """donate_argnums literal (int or tuple/list of ints) → positions."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _parse_donates_arg(arg: str | None) -> tuple[int, ...] | None:
    if not arg:
        return None
    try:
        return tuple(int(p) for p in arg.split(","))
    except ValueError:
        return None


@register
class DonatedBufferRule(Rule):
    id = "KTL110"
    name = "donated-dead"
    summary = ("arrays passed at a donated position are dead after the "
               "call — rebind (`x = f(x, …)`) or never touch them again")
    rationale = (
        "`jax.jit(..., donate_argnums=…)` aliases the argument's buffer "
        "into the computation: the runtime invalidates the handle, and a "
        "later read either raises (good) or — through a stale alias on a "
        "stream-ordered backend — observes memory the program is "
        "rewriting in place (the resident fleet batch's delta update is "
        "exactly this). The check is LEXICAL, scoped to the window plane "
        "(kepler_tpu/parallel/, fleet/aggregator.py, fleet/window.py): a "
        "callable bound from a `jax.jit(…, donate_argnums=…)` call — or "
        "any callable whose binding carries `# keplint: donates=<pos>` "
        "(for jits built behind a helper) — consumes the variables at "
        "those positions; any later read before a rebinding is flagged. "
        "The canonical legal shape is `resident = update(resident, …)`.")

    def check(self, ctx: FileContext) -> Iterable[Diagnostic]:
        if not ctx.rel_path.startswith(_DONATE_SCOPE):
            return
        donators = self._donating_aliases(ctx)
        for node in ctx.walk_nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node, donators)

    def _donating_aliases(self, ctx: FileContext) -> dict[str,
                                                          tuple[int, ...]]:
        """qualname (``update`` / ``self._update``) → donated positions,
        from `jax.jit(..., donate_argnums=…)` bindings and `donates=`
        directives anywhere in the file."""
        imports = imports_for(ctx)
        out: dict[str, tuple[int, ...]] = {}
        for node in ctx.walk_nodes:
            if not isinstance(node, ast.Assign):
                continue
            positions: tuple[int, ...] | None = None
            value = node.value
            if isinstance(value, ast.Call):
                canon = call_canonical(value, imports) or ""
                if canon in ("jax.jit", "jit") or canon.endswith(".jit"):
                    for kw in value.keywords:
                        if kw.arg == "donate_argnums":
                            positions = _donate_positions(kw.value)
            for kind, arg in ctx.directives.get(node.lineno, []):
                if kind == "donates":
                    positions = _parse_donates_arg(arg) or positions
            if positions is None:
                continue
            for target in node.targets:
                qual = qualname(target)
                if qual:
                    out[qual] = positions
        return out

    def _check_function(self, ctx: FileContext, fn: ast.AST,
                        donators: dict) -> Iterator[Diagnostic]:
        # consumed qualname → the line its buffer was donated on
        consumed: dict[str, int] = {}

        def statements(body):
            for stmt in body:
                yield stmt
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # nested defs run later; out of scope
                for child_body in (getattr(stmt, a, None)
                                   for a in ("body", "orelse",
                                             "finalbody")):
                    if child_body:
                        yield from statements(child_body)
                for handler in getattr(stmt, "handlers", []) or []:
                    yield from statements(handler.body)
                for case in getattr(stmt, "cases", []) or []:
                    yield from statements(case.body)

        for stmt in statements(fn.body):
            diags = list(self._check_stmt(ctx, stmt, donators, consumed))
            yield from diags

    @staticmethod
    def _stmt_exprs(stmt: ast.AST) -> Iterator[ast.AST]:
        """The statement's OWN expression nodes (an If's test, a For's
        iter, an Assign's value/targets, a With's items) — nested
        statements are visited separately by the statement walk, so
        descending into them here would double-process their donations
        and falsely flag the rebind pattern inside any compound body."""
        stack = list(ast.iter_child_nodes(stmt))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.stmt):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_stmt(self, ctx: FileContext, stmt: ast.AST, donators: dict,
                    consumed: dict[str, int]) -> Iterator[Diagnostic]:
        # 1) reads of names consumed by an EARLIER statement
        for node in self._stmt_exprs(stmt):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            qual = qualname(node)
            if qual in consumed:
                line = consumed.pop(qual)  # report once, don't cascade
                yield ctx.diag(
                    self, node,
                    f"{qual!r} was donated on line {line} and its buffer "
                    "is dead; rebind the result (`x = f(x, …)`) or stop "
                    "reading it")
        # 2) donations performed by this statement
        for node in self._stmt_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            qual = qualname(node.func)
            if qual not in donators:
                continue
            for pos in donators[qual]:
                if pos < len(node.args):
                    arg_qual = qualname(node.args[pos])
                    if arg_qual:
                        consumed[arg_qual] = node.lineno
        # 3) rebinding clears consumption (the canonical donate pattern
        #    `x = f(x, …)` lands here: consumed in (2), cleared now)
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for target in targets:
            qual = qualname(target)
            if qual:
                consumed.pop(qual, None)
