"""Whole-program analysis context for keplint (ISSUE 9 tentpole).

Per-file AST rules (KTL101-110) stop seeing an invariant the moment it
crosses a call edge: a helper hop hides a lock contract, a wire-decoded
name loses its taint, a ``time.sleep`` two frames below the refresh
loop is invisible to the lexical hot-loop check.  :class:`ProjectContext`
closes that gap without leaving stdlib ``ast``:

- every file is parsed **once** per run (the contexts are shared with
  the per-file rules — see ``engine.lint_paths``);
- a module-level symbol table maps imports/classes/functions to global
  ids (``module:Class.method``);
- light type inference (constructor assignments, parameter annotations,
  ``self.attr = ClassName(...)`` in ``__init__``) resolves receiver
  classes so ``self._scoreboard.observe_report(...)`` becomes a real
  call edge into another module;
- a call graph links every resolved call site, carrying the set of
  locks lexically held at the site;
- **thread roles** propagate from declared roots (``# keplint:
  thread-role=<role>`` on a def or class, ``hot-loop`` markers, and
  callables passed to a ``# keplint: role-registrar=<role>`` function
  such as ``APIServer.register``) along call edges, stopping at
  ``# keplint: role-boundary`` seams (the meter keeps its own
  contract);
- per-function **lock summaries** (which locks a function acquires,
  directly and through its call closure) feed the KTL111 lock-order
  graph.

The KTL111/112/113 rule families in ``analysis/rules/`` consume this
context; everything here is pure construction, no diagnostics.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Sequence

from kepler_tpu.analysis.engine import FileContext
from kepler_tpu.analysis.rules.common import (
    Imports as _Imports,
    child_bodies as _shared_child_bodies,
    qualname as _qualname,
    stmt_exprs as _shared_stmt_exprs,
)

__all__ = [
    "CallSite",
    "ClassInfo",
    "FunctionInfo",
    "ProjectContext",
]

# attribute names treated as lock acquisitions inside a `with` even when
# the constructor was not seen (over-approximation shared with KTL108)
_LOCKISH = ("lock", "mutex", "cv", "cond")

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}


def module_name_for(rel_path: str) -> str:
    """``kepler_tpu/fleet/wire.py`` → ``kepler_tpu.fleet.wire``;
    ``pkg/__init__.py`` → ``pkg``."""
    parts = rel_path[:-3].split("/") if rel_path.endswith(".py") \
        else rel_path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p)


@dataclass
class FunctionInfo:
    """One function/method with everything the project rules inspect."""

    func_id: str                     # "module:Class.method" / "module:func"
    module: str
    qual: str                        # dotted path inside the module
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    ctx: FileContext
    class_key: str | None = None     # enclosing ClassInfo key
    # locks this function acquires itself: (lock_id, raw_qual, node,
    # frozenset of lock_ids already held at the acquisition)
    acquires: list = field(default_factory=list)
    # attribute-chain assignment targets: (raw_qual, node, held_raw) —
    # KTL111 checks cross-class guarded-attribute writes against these
    writes: list = field(default_factory=list)
    # lock_ids acquired by this function OR anything it calls (fixpoint)
    closure_acquires: frozenset = frozenset()
    # thread roles this function runs under: role → CallSite | None
    # (None = this function is itself a root for the role)
    roles: dict = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    def marker(self, kind: str) -> str | None:
        return self.ctx.marker_on(self.node, kind)


@dataclass
class ClassInfo:
    key: str                         # "module:Outer.Inner"
    name: str
    module: str
    node: ast.ClassDef
    ctx: FileContext
    bases: list = field(default_factory=list)         # resolved class keys
    methods: dict = field(default_factory=dict)       # name → func_id
    guarded: dict = field(default_factory=dict)       # attr → lock attr
    attr_types: dict = field(default_factory=dict)    # attr → class key
    lock_kinds: dict = field(default_factory=dict)    # attr → Lock/RLock/…

    def marker(self, kind: str) -> str | None:
        return self.ctx.marker_on(self.node, kind)


@dataclass
class CallSite:
    """One resolved call edge."""

    caller: str                      # func_id
    callee: str                      # func_id
    node: ast.Call
    ctx: FileContext
    # raw receiver qualnames of locks lexically held at the site
    # ("self._lock", "self._agg._lock", …) plus entry-held requires-lock
    held_raw: frozenset = frozenset()
    held_ids: frozenset = frozenset()        # same, as global lock ids
    receiver: str | None = None              # "self._spool" for attr calls


class ProjectContext:
    """Symbol table + call graph + roles over a set of parsed files."""

    def __init__(self, ctxs: Sequence[FileContext]) -> None:
        self.files: dict[str, FileContext] = {c.rel_path: c for c in ctxs}
        self.modules: dict[str, FileContext] = {}
        self.imports: dict[str, _Imports] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.calls: dict[str, list[CallSite]] = {}
        self.callers: dict[str, list[CallSite]] = {}
        # containing function of each module: "module:" pseudo-function
        # is NOT modeled; module-level calls are ignored (import-time)
        for ctx in ctxs:
            mod = module_name_for(ctx.rel_path)
            self.modules[mod] = ctx
            self.imports[ctx.rel_path] = _Imports(ctx.tree)
        for ctx in ctxs:
            self._collect_symbols(ctx)
        for ctx in ctxs:
            self._infer_types(ctx)
        for info in list(self.functions.values()):
            self._link_calls(info)
        self._close_lock_acquires()
        self._propagate_roles()

    # -- symbol collection -------------------------------------------------

    def _collect_symbols(self, ctx: FileContext) -> None:
        mod = module_name_for(ctx.rel_path)

        def visit(node: ast.AST, path: tuple[str, ...],
                  class_key: str | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    key = f"{mod}:{'.'.join(path + (child.name,))}"
                    info = ClassInfo(key=key, name=child.name, module=mod,
                                     node=child, ctx=ctx)
                    self.classes[key] = info
                    visit(child, path + (child.name,), key)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = ".".join(path + (child.name,))
                    fid = f"{mod}:{qual}"
                    self.functions[fid] = FunctionInfo(
                        func_id=fid, module=mod, qual=qual, node=child,
                        ctx=ctx, class_key=class_key)
                    if class_key is not None:
                        self.classes[class_key].methods.setdefault(
                            child.name, fid)
                    # nested defs: new scope, not a method of class_key
                    visit(child, path + (child.name,), None)

        visit(ctx.tree, (), None)

    # -- type inference ----------------------------------------------------

    def resolve_class(self, ctx: FileContext, name: str | None) -> str | None:
        """Class key for a (possibly dotted / imported / aliased) name
        as seen from ``ctx``."""
        if not name:
            return None
        mod = module_name_for(ctx.rel_path)
        # local (top-level or nested) class of this module
        for key in (f"{mod}:{name}",):
            if key in self.classes:
                return key
        canon = self.imports[ctx.rel_path].canonical(name)
        if canon and "." in canon:
            owner, _, cls = canon.rpartition(".")
            key = f"{owner}:{cls}"
            if key in self.classes:
                return key
        return None

    def _annotation_class(self, ctx: FileContext,
                          ann: ast.AST | None) -> str | None:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        # unwrap Optional[X] / "X | None"
        if isinstance(ann, ast.Subscript):
            base = _qualname(ann.value) or ""
            if base.rsplit(".", 1)[-1] == "Optional":
                ann = ann.slice
            else:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            left = self._annotation_class(ctx, ann.left)
            return left or self._annotation_class(ctx, ann.right)
        qual = _qualname(ann)
        if qual in ("None", "NoneType"):
            return None
        return self.resolve_class(ctx, qual)

    def _infer_types(self, ctx: FileContext) -> None:
        """Fill ClassInfo.attr_types / lock_kinds / guarded / bases."""
        for cls in self.classes.values():
            if cls.ctx is not ctx:
                continue
            for base in cls.node.bases:
                key = self.resolve_class(ctx, _qualname(base))
                if key:
                    cls.bases.append(key)
            for fid in cls.methods.values():
                fn = self.functions[fid].node
                for stmt in ast.walk(fn):
                    if isinstance(stmt, ast.AnnAssign):
                        target, value = stmt.target, stmt.value
                        tkey = self._annotation_class(ctx, stmt.annotation)
                    elif isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1:
                        target, value = stmt.targets[0], stmt.value
                        tkey = None
                    else:
                        continue
                    if not (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        continue
                    attr = target.attr
                    if isinstance(value, ast.Call):
                        canon = self.imports[ctx.rel_path].canonical(
                            _qualname(value.func))
                        if canon in _LOCK_CTORS:
                            cls.lock_kinds.setdefault(
                                attr, _LOCK_CTORS[canon])
                        tkey = tkey or self.resolve_class(
                            ctx, _qualname(value.func))
                    elif isinstance(value, ast.Name):
                        tkey = tkey or self._param_type(fid, value.id)
                    if tkey:
                        cls.attr_types.setdefault(attr, tkey)
                    # guarded-by directives attach to the assignment line
                    for kind, arg in ctx.directives.get(stmt.lineno, []):
                        if kind == "guarded-by" and arg:
                            cls.guarded.setdefault(attr, arg)

    def _param_type(self, fid: str, name: str) -> str | None:
        info = self.functions.get(fid)
        if info is None:
            return None
        args = info.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.arg == name:
                return self._annotation_class(info.ctx, a.annotation)
        return None

    # -- lookup helpers ----------------------------------------------------

    def class_of(self, info: FunctionInfo) -> ClassInfo | None:
        return self.classes.get(info.class_key) if info.class_key else None

    def method_on(self, class_key: str | None,
                  name: str, _seen: frozenset = frozenset()) -> str | None:
        """Method resolution through project-visible single inheritance."""
        if not class_key or class_key in _seen:
            return None
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for base in cls.bases:
            fid = self.method_on(base, name, _seen | {class_key})
            if fid:
                return fid
        return None

    def guarded_on(self, class_key: str | None, attr: str,
                   _seen: frozenset = frozenset()) -> str | None:
        """guarded-by lock attr for ``attr`` looked up through bases."""
        if not class_key or class_key in _seen:
            return None
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        if attr in cls.guarded:
            return cls.guarded[attr]
        for base in cls.bases:
            lock = self.guarded_on(base, attr, _seen | {class_key})
            if lock:
                return lock
        return None

    def class_marker(self, class_key: str | None, kind: str,
                     _seen: frozenset = frozenset()) -> str | None:
        if not class_key or class_key in _seen:
            return None
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        val = cls.marker(kind)
        if val is not None:
            return val
        for base in cls.bases:
            val = self.class_marker(base, kind, _seen | {class_key})
            if val is not None:
                return val
        return None

    def local_types(self, info: FunctionInfo) -> dict[str, str]:
        """name → class key for annotated params and constructor-assigned
        locals of one function."""
        out: dict[str, str] = {}
        args = info.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            key = self._annotation_class(info.ctx, a.annotation)
            if key:
                out[a.arg] = key
        for stmt in ast.walk(info.node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                key = self.resolve_class(info.ctx,
                                         _qualname(stmt.value.func))
                if key:
                    out.setdefault(stmt.targets[0].id, key)
        return out

    def resolve_call(self, info: FunctionInfo, call: ast.Call,
                     local_types: dict[str, str]) -> tuple[str | None,
                                                           str | None]:
        """(callee func_id, receiver qual) for one call, or (None, None).

        Resolution order: ``self.m()`` through the enclosing class (and
        bases), ``self.attr.m()`` / ``local.m()`` through inferred
        types, ``Class(...)`` to ``__init__``, plain/imported names to
        module functions, ``mod.func()`` through the alias map.
        """
        func = call.func
        mod = info.module
        if isinstance(func, ast.Name):
            name = func.id
            key = self.resolve_class(info.ctx, name)
            if key:
                return self.method_on(key, "__init__"), None
            fid = f"{mod}:{name}"
            if fid in self.functions:
                return fid, None
            canon = self.imports[info.ctx.rel_path].canonical(name)
            if canon and "." in canon:
                owner, _, fn_name = canon.rpartition(".")
                fid = f"{owner}:{fn_name}"
                if fid in self.functions:
                    return fid, None
            return None, None
        if not isinstance(func, ast.Attribute):
            return None, None
        attr = func.attr
        recv_qual = _qualname(func.value)
        if recv_qual == "self" and info.class_key:
            return self.method_on(info.class_key, attr), "self"
        if recv_qual:
            parts = recv_qual.split(".")
            # self.attr chains: resolve the attribute's inferred type
            if parts[0] == "self" and len(parts) == 2 and info.class_key:
                cls = self.class_of(info)
                tkey = self._attr_type_on(info.class_key, parts[1]) \
                    if cls else None
                if tkey:
                    return self.method_on(tkey, attr), recv_qual
                return None, recv_qual
            if len(parts) == 1:
                tkey = local_types.get(parts[0])
                if tkey:
                    return self.method_on(tkey, attr), recv_qual
                # ClassName.method / module.func / imported alias
                key = self.resolve_class(info.ctx, parts[0])
                if key:
                    return self.method_on(key, attr), None
            canon = self.imports[info.ctx.rel_path].canonical(recv_qual)
            if canon:
                fid = f"{canon}:{attr}"
                if fid in self.functions:
                    return fid, None
                key = self.resolve_class(info.ctx, recv_qual)
                if key:
                    return self.method_on(key, attr), None
        return None, recv_qual

    def _attr_type_on(self, class_key: str | None, attr: str,
                      _seen: frozenset = frozenset()) -> str | None:
        if not class_key or class_key in _seen:
            return None
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        for base in cls.bases:
            tkey = self._attr_type_on(base, attr, _seen | {class_key})
            if tkey:
                return tkey
        return None

    # -- lock identity -----------------------------------------------------

    def lock_id(self, info: FunctionInfo, raw_qual: str) -> str:
        """Global identity of a lock named by ``raw_qual`` in ``info``:
        ``self._lock`` keys on the (attribute-typed) owning class so the
        same lock has one node in the order graph regardless of which
        method or module acquires it."""
        parts = raw_qual.split(".")
        if parts[0] == "self" and info.class_key:
            if len(parts) == 2:
                owner = self._lock_owner(info.class_key, parts[1])
                return f"{owner}.{parts[1]}"
            if len(parts) == 3:
                tkey = self._attr_type_on(info.class_key, parts[1])
                if tkey:
                    owner = self._lock_owner(tkey, parts[2])
                    return f"{owner}.{parts[2]}"
            return f"{info.class_key}.{'.'.join(parts[1:])}"
        if len(parts) == 1:
            # module-level lock, or a local variable (function-scoped id)
            ctx = info.ctx
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == parts[0]
                        for t in node.targets):
                    return f"{info.module}:{parts[0]}"
            return f"{info.func_id}:{parts[0]}"
        return f"{info.module}:{raw_qual}"

    def _lock_owner(self, class_key: str, lock_attr: str) -> str:
        """Hoist a lock's identity to the base class that creates it, so
        subclass acquisitions alias correctly."""
        cls = self.classes.get(class_key)
        if cls is None:
            return class_key
        if lock_attr in cls.lock_kinds:
            return class_key
        for base in cls.bases:
            owner = self._lock_owner(base, lock_attr)
            owner_cls = self.classes.get(owner)
            if owner_cls is not None and lock_attr in owner_cls.lock_kinds:
                return owner
        return class_key

    def lock_kind(self, lock_id: str) -> str | None:
        """Lock/RLock/Condition/… when the constructor was seen."""
        owner, _, attr = lock_id.rpartition(".")
        cls = self.classes.get(owner)
        if cls is not None:
            return cls.lock_kinds.get(attr)
        return None

    @staticmethod
    def is_lockish(info_or_none: "ProjectContext | None",
                   raw_qual: str) -> bool:
        term = raw_qual.rsplit(".", 1)[-1].lower()
        return any(t in term for t in _LOCKISH)

    def _with_lock_quals(self, info: FunctionInfo,
                         node: ast.With) -> list[tuple[str, ast.AST]]:
        out: list[tuple[str, ast.AST]] = []
        for item in node.items:
            qual = _qualname(item.context_expr)
            if not qual:
                continue
            term = qual.rsplit(".", 1)[-1].lower()
            known = False
            parts = qual.split(".")
            if parts[0] == "self" and info.class_key:
                if len(parts) == 2 and self._lock_kind_on(
                        info.class_key, parts[1]):
                    known = True
                elif len(parts) == 3:
                    tkey = self._attr_type_on(info.class_key, parts[1])
                    if tkey and self._lock_kind_on(tkey, parts[2]):
                        known = True
            if known or any(t in term for t in _LOCKISH):
                out.append((qual, item.context_expr))
        return out

    def _lock_kind_on(self, class_key: str, attr: str,
                      _seen: frozenset = frozenset()) -> str | None:
        if class_key in _seen:
            return None
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        if attr in cls.lock_kinds:
            return cls.lock_kinds[attr]
        for base in cls.bases:
            kind = self._lock_kind_on(base, attr, _seen | {class_key})
            if kind:
                return kind
        return None

    # -- call graph + lock walk --------------------------------------------

    def _link_calls(self, info: FunctionInfo) -> None:
        local_types = self.local_types(info)
        sites: list[CallSite] = []
        entry_raw: set[str] = set()
        req = info.marker("requires-lock")
        if req:
            entry_raw.add(f"self.{req}")

        def walk(stmts: list, held_raw: frozenset,
                 held_ids: frozenset) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # separate functions; no lexical lock carry
                add_raw: set[str] = set()
                add_ids: set[str] = set()
                if isinstance(stmt, ast.With):
                    for qual, expr in self._with_lock_quals(info, stmt):
                        lid = self.lock_id(info, qual)
                        info.acquires.append((lid, qual, expr, held_ids))
                        add_raw.add(qual)
                        add_ids.add(lid)
                # attribute writes (guarded-by enforcement feeds on these)
                targets: list = []
                if isinstance(stmt, ast.Assign):
                    targets = list(stmt.targets)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    targets = [stmt.target]
                for target in targets:
                    inner = target
                    while isinstance(inner, ast.Subscript):
                        inner = inner.value
                    qual = _qualname(inner)
                    if qual and "." in qual:
                        info.writes.append(
                            (qual, stmt,
                             held_raw | frozenset(add_raw)))
                # calls in THIS statement's own expressions
                for expr in self._stmt_exprs(stmt):
                    if isinstance(expr, ast.Call):
                        callee, recv = self.resolve_call(
                            info, expr, local_types)
                        if callee and callee in self.functions:
                            sites.append(CallSite(
                                caller=info.func_id, callee=callee,
                                node=expr, ctx=info.ctx,
                                held_raw=held_raw | frozenset(add_raw),
                                held_ids=held_ids | frozenset(add_ids),
                                receiver=recv))
                for body in self._child_bodies(stmt):
                    walk(body, held_raw | frozenset(add_raw),
                         held_ids | frozenset(add_ids))

        entry_ids = frozenset(self.lock_id(info, q) for q in entry_raw)
        walk(list(info.node.body), frozenset(entry_raw), entry_ids)
        self.calls[info.func_id] = sites
        for site in sites:
            self.callers.setdefault(site.callee, []).append(site)

    _stmt_exprs = staticmethod(_shared_stmt_exprs)
    _child_bodies = staticmethod(_shared_child_bodies)

    def _close_lock_acquires(self) -> None:
        """closure_acquires: lock ids acquired by a function or anything
        reachable from it (worklist fixpoint, cycle-safe)."""
        own = {fid: frozenset(a[0] for a in info.acquires)
               for fid, info in self.functions.items()}
        closure = dict(own)
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for fid, sites in self.calls.items():
                acc = closure[fid]
                for site in sites:
                    acc = acc | closure.get(site.callee, frozenset())
                if acc != closure[fid]:
                    closure[fid] = acc
                    changed = True
        for fid, info in self.functions.items():
            info.closure_acquires = closure[fid]

    # -- thread roles ------------------------------------------------------

    def _propagate_roles(self) -> None:
        roots: list[tuple[str, str]] = []  # (func_id, role)
        for fid, info in self.functions.items():
            if info.marker("hot-loop") is not None:
                roots.append((fid, "hot-loop"))
            role = info.marker("thread-role")
            if role:
                roots.append((fid, role))
            crole = self.class_marker(info.class_key, "thread-role")
            if crole and info.name != "__init__":
                roots.append((fid, crole))
        # role-registrar: callables passed to a registrar become roots
        for fid, info in self.functions.items():
            role = info.marker("role-registrar")
            if not role:
                continue
            for site in self.callers.get(fid, []):
                caller = self.functions[site.caller]
                ltypes = self.local_types(caller)
                for arg in list(site.node.args) + [
                        kw.value for kw in site.node.keywords]:
                    target = self._callable_arg(caller, arg, ltypes)
                    if target:
                        roots.append((target, role))
        # BFS per role with parent pointers for chain reconstruction
        queue: list[str] = []
        for fid, role in roots:
            info = self.functions[fid]
            if role not in info.roles:
                info.roles[role] = None
                queue.append(fid)
        while queue:
            fid = queue.pop()
            info = self.functions[fid]
            for site in self.calls.get(fid, []):
                callee = self.functions[site.callee]
                if callee.marker("role-boundary") is not None:
                    continue  # the seam keeps its own contract
                grew = False
                for role in info.roles:
                    if role not in callee.roles:
                        callee.roles[role] = site
                        grew = True
                if grew:
                    queue.append(site.callee)

    def _callable_arg(self, caller: FunctionInfo, arg: ast.AST,
                      local_types: dict[str, str]) -> str | None:
        """func_id of a function-valued argument (``self._handle`` or a
        plain function name)."""
        qual = _qualname(arg)
        if not qual:
            return None
        parts = qual.split(".")
        if parts[0] == "self" and len(parts) == 2 and caller.class_key:
            return self.method_on(caller.class_key, parts[1])
        if len(parts) == 1:
            fid = f"{caller.module}:{parts[0]}"
            if fid in self.functions:
                return fid
            canon = self.imports[caller.ctx.rel_path].canonical(parts[0])
            if canon and "." in canon:
                owner, _, name = canon.rpartition(".")
                fid = f"{owner}:{name}"
                if fid in self.functions:
                    return fid
        if len(parts) == 2:
            tkey = local_types.get(parts[0])
            if tkey:
                return self.method_on(tkey, parts[1])
        return None

    def role_chain(self, fid: str, role: str, limit: int = 12) -> list[str]:
        """Human-readable call chain from the role root down to ``fid``."""
        chain: list[str] = []
        cur: str | None = fid
        while cur is not None and len(chain) < limit:
            info = self.functions[cur]
            chain.append(info.qual)
            site = info.roles.get(role)
            cur = site.caller if site is not None else None
        chain.reverse()
        return chain
