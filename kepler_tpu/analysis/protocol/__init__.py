"""kepmc: exhaustive-interleaving model checking of the fleet's pure
decision layer.

The host-plane tiers read source text; the device tier reads jaxprs;
this tier reads REACHABLE STATE SPACES. Every distributed-protocol
decision the fleet makes — lease adopt/succession, membership
apply/replay, seq dedup/watermark seeding, spool ack-cursor math, the
wire-v2 keyframe/delta/409 machine — lives in pure functions
(``fleet/membership.py``, ``fleet/delivery.py``), and kepmc drives
those SAME functions through every event interleaving at small scope
(2-3 replicas, a handful of windows/epochs) via an explicit-state BFS
explorer. Three families ride each exploration:

- **KTL130 protocol-epoch-safety** — no split-brain, holders inside
  their membership, contiguous epochs, no awaiting-forever wedge.
- **KTL131 protocol-loss-accounting** — no fabricated loss, no spool
  record skipped or stale-acked, rewinds bounded.
- **KTL132 protocol-replay-idempotence** — replays are no-ops,
  duplicate keyframes still plant the base, 409s converge in one
  round trip.

(The companion per-file rule KTL133 — epoch/seq/ack/base-row state
writes only inside ``# keplint: protocol-transition``-marked functions
— lives with the other AST rules in ``rules/protocol.py``; it is what
keeps the modeled surface and the production surface the same code.)

Counterexamples print as minimal event traces (BFS order = shortest
schedule). Run via ``python -m kepler_tpu.analysis --protocol-tier``
(wired into ``make lint``; ``make protocheck`` runs the tier alone).
Importing this package registers the rules but explores nothing.
"""

from kepler_tpu.analysis.protocol.checks import (  # noqa: F401
    INVARIANT_RULE,
    ModelReport,
    PROTOCOL_RULE_IDS,
    analyze_protocol_specs,
    clear_exploration_cache,
    explore_case,
)
from kepler_tpu.analysis.protocol.explorer import (  # noqa: F401
    Counterexample,
    ExplorationResult,
    ProtocolModel,
    StateExplosionError,
    explore,
)
from kepler_tpu.analysis.protocol.models import (  # noqa: F401
    MODEL_BUILDERS,
    build_model,
)
from kepler_tpu.analysis.protocol.registry import (  # noqa: F401
    PROTOCOL_SPECS,
    ProtocolCase,
    ProtocolSpec,
    spec_by_name,
)

__all__ = [
    "Counterexample",
    "ExplorationResult",
    "INVARIANT_RULE",
    "MODEL_BUILDERS",
    "ModelReport",
    "PROTOCOL_RULE_IDS",
    "PROTOCOL_SPECS",
    "ProtocolCase",
    "ProtocolModel",
    "ProtocolSpec",
    "StateExplosionError",
    "analyze_protocol_specs",
    "build_model",
    "clear_exploration_cache",
    "explore",
    "explore_case",
    "spec_by_name",
]
