"""kepmc protocol models: the fleet's transition rules as explorable
state machines, built on the REAL pure code.

Fidelity is the whole point: every transition a model takes calls the
SAME function production runs — :func:`plan_succession` /
:func:`plan_membership_apply` / :class:`CoordinatorLease` for
membership, :class:`SeqTracker` + the watermark seeding rules for the
delivery plane, :func:`plan_ack_cursor` / :func:`plan_rewind_tail` for
the spool cursor, :func:`keyframe_wanted` / :func:`delta_base_matches`
for the wire-v2 keyframe/delta machine. The model layer contributes
only the EVENT VOCABULARY (deliver / duplicate / reorder /
drop-response / crash / restart / partition-probe / scale-op) and the
state packing; when an invariant fires, the counterexample is a real
schedule the shipped functions mishandle, not a modeling artifact.

Each model also carries its PR 16 bug fixture as a ``variant``: with
``variant="shipped"`` (the registry default) the model drives the
fixed code; the named bug variants re-introduce one pre-fix behavior
so the test suite can prove the checker would have caught it
(``skip_demote_early_return`` — the broadcast-lands-before-demote
wedge; ``hardcoded_issuer`` — the holder-leave handoff break;
``skip_ownership_reseed`` — fabricated loss on ownership return).
Variants exist ONLY for fixtures: the lint registry never explores
them.

States are canonical hashable tuples; every model is deterministic
(no clocks, no randomness) so an exploration is reproducible
state-for-state. A state that already violates an invariant is
ABSORBING (no successors): exploration past a violation only buries
the minimal trace.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

from kepler_tpu.fleet.delivery import (
    SeqTracker,
    delta_base_matches,
    keyframe_wanted,
    plan_ack_cursor,
    plan_rewind_tail,
    reseed_on_ownership_return,
    seed_fresh_tracker,
)
from kepler_tpu.fleet.membership import (
    CoordinatorLease,
    MembershipError,
    elect_successor,
    plan_membership_apply,
    plan_succession,
)

__all__ = [
    "KeyframeDeltaModel",
    "LeaseSuccessionModel",
    "MODEL_BUILDERS",
    "SeqDeliveryModel",
    "SpoolCursorModel",
    "build_model",
]


# ---------------------------------------------------------------------------
# coordinator lease / succession (KTL130)
# ---------------------------------------------------------------------------

# replica: (alive, epoch, holder, peers, awaiting)
# state:   (replicas, messages) — messages: frozenset of
#          (epoch, peers, issuer) broadcasts, never consumed (so every
#          delivery can also happen as a duplicate)


class LeaseSuccessionModel:
    """Succession/lease safety over N replicas: crash, true-death
    notice, graceful leave, restart-join, broadcast delivery (with
    inherent duplication/reorder — messages persist), and optionally a
    partitioned prober that falsely suspects its holder.

    Every membership adoption runs the real
    :func:`plan_membership_apply` + :class:`CoordinatorLease.adopt`;
    every issuer election runs the real :func:`plan_succession`.
    """

    def __init__(self, replicas: int = 3, epoch_cap: int = 5,
                 msg_cap: int = 3, suspects: bool = False,
                 variant: str = "shipped") -> None:
        if not 2 <= replicas <= 3:
            raise ValueError("lease model scope is 2-3 replicas")
        self.names = tuple("abc"[:replicas])
        self.epoch_cap = epoch_cap
        # broadcasts persist forever (that is what makes every deliver
        # also a duplicate), so the DISTINCT-message count needs a cap
        # or the frozenset lattice explodes; issuance events gate on it
        self.msg_cap = msg_cap
        self.suspects = suspects
        self.variant = variant

    def initial(self) -> Any:
        holder = elect_successor(self.names)
        reps = tuple((True, 1, holder, self.names, False)
                     for _ in self.names)
        return reps, frozenset()

    # -- transition helpers (REAL code under the hood) ---------------------

    def _alive_names(self, reps: tuple[Any, ...]) -> tuple[str, ...]:
        return tuple(n for n, r in zip(self.names, reps) if r[0])

    def _deliver(self, name: str, rep: Any, msg: Any) -> Any:
        """One replica applies one broadcast — the production
        ``apply_membership`` decision, including replay-does-not-clear-
        awaiting and equal-epoch-conflict rejection."""
        alive, epoch, holder, peers, awaiting = rep
        m_epoch, m_peers, m_issuer = msg
        try:
            decision = plan_membership_apply(
                epoch, list(peers), "kepmc", m_epoch, list(m_peers),
                name, "peer")
        except MembershipError:
            return rep  # stale/conflict: rejected loudly, no change
        if decision.action == "replay":
            return rep  # production parity: awaiting is NOT cleared
        lease = CoordinatorLease(holder, epoch)
        lease.adopt(m_issuer, decision.epoch)
        return (alive, decision.epoch, lease.holder,
                tuple(sorted(decision.peers)), False)

    def _mint_ok(self, messages: frozenset[Any], epoch: int,
                 survivors: tuple[str, ...], issuer: str) -> bool:
        msg = (epoch, tuple(sorted(survivors)), issuer)
        return msg in messages or len(messages) < self.msg_cap

    def _issue(self, reps: tuple[Any, ...], messages: frozenset[Any],
               idx: int, survivors: tuple[str, ...],
               issuer: str) -> Any:
        """Replica ``idx`` issues a membership at its epoch+1 and
        applies it locally (the production issue path)."""
        name = self.names[idx]
        alive, epoch, holder, peers, awaiting = reps[idx]
        msg = (epoch + 1, tuple(sorted(survivors)), issuer)
        new_rep = self._deliver(name, reps[idx], msg)
        out = list(reps)
        out[idx] = new_rep
        return tuple(out), messages | {msg}

    # -- event enumeration --------------------------------------------------

    def successors(self, state: Any) -> Iterable[tuple[str, Any]]:
        reps, messages = state
        if any(True for _ in self.violations(state)):
            return  # absorbing: keep the minimal trace minimal
        alive = self._alive_names(reps)
        for i, name in enumerate(self.names):
            rep = reps[i]
            if rep[0]:
                if len(alive) > 1:
                    out = list(reps)
                    out[i] = (False,) + rep[1:]
                    yield f"crash({name})", (tuple(out), messages)
                yield from self._leave_events(reps, messages, i)
                yield from self._notice_events(reps, messages, i, alive)
                if self.suspects:
                    yield from self._suspect_events(reps, messages, i,
                                                    alive)
                for msg in sorted(messages):
                    new_rep = self._deliver(name, rep, msg)
                    if new_rep != rep:
                        out = list(reps)
                        out[i] = new_rep
                        yield (f"deliver(epoch={msg[0]},"
                               f"peers={{{','.join(msg[1])}}},"
                               f"issuer={msg[2]} -> {name})",
                               (tuple(out), messages))
            else:
                yield from self._restart_events(reps, messages, i)

    def _notice_events(self, reps: tuple[Any, ...],
                       messages: frozenset[Any], i: int,
                       alive: tuple[str, ...]
                       ) -> Iterable[tuple[str, Any]]:
        """Replica ``i`` notices the TRUE dead set and runs the demote
        decision (``_demote_mesh``'s shape)."""
        name = self.names[i]
        _alive, epoch, holder, peers, _awaiting = reps[i]
        if len(alive) == len(self.names):
            return  # nobody is dead; nothing to notice
        survivors = alive
        if set(survivors) == set(peers):
            if self.variant != "skip_demote_early_return":
                return  # FIXED: membership already reflects survivors
            # pre-fix wedge: fall through and await an apply that can
            # never come
        issuer = plan_succession(holder, survivors)
        if issuer == name:
            if (epoch + 1 <= self.epoch_cap
                    and self._mint_ok(messages, epoch + 1, survivors,
                                      issuer)):
                yield (f"notice({name}:issues)",
                       self._issue(reps, messages, i, survivors,
                                   issuer))
        else:
            out = list(reps)
            out[i] = reps[i][:4] + (True,)
            yield f"notice({name}:awaits {issuer})", (tuple(out),
                                                      messages)

    def _suspect_events(self, reps: tuple[Any, ...],
                        messages: frozenset[Any], i: int,
                        alive: tuple[str, ...]
                        ) -> Iterable[tuple[str, Any]]:
        """Partitioned prober: ``i`` falsely suspects its (live)
        holder dead and runs succession over the rest."""
        name = self.names[i]
        _alive, epoch, holder, peers, _awaiting = reps[i]
        if holder == name or holder not in alive:
            return  # self-suspicion is meaningless; true death is notice
        survivors = tuple(n for n in alive if n != holder)
        if not survivors or set(survivors) == set(peers):
            return
        issuer = plan_succession(holder, survivors)
        if issuer == name:
            if (epoch + 1 <= self.epoch_cap
                    and self._mint_ok(messages, epoch + 1, survivors,
                                      issuer)):
                yield (f"suspect({name}:issues over -{holder})",
                       self._issue(reps, messages, i, survivors,
                                   issuer))
        else:
            out = list(reps)
            out[i] = reps[i][:4] + (True,)
            yield (f"suspect({name}:awaits {issuer})",
                   (tuple(out), messages))

    def _leave_events(self, reps: tuple[Any, ...],
                      messages: frozenset[Any],
                      i: int) -> Iterable[tuple[str, Any]]:
        """Graceful leave: ``i`` broadcasts the membership without
        itself. FIXED code names the succession-planned holder as the
        lease issuer; the ``hardcoded_issuer`` variant re-introduces
        the pre-fix bug (issuer = the sender itself)."""
        name = self.names[i]
        _alive, epoch, holder, peers, _awaiting = reps[i]
        survivors = tuple(sorted(set(peers) - {name}))
        if not survivors or epoch + 1 >= self.epoch_cap + 1:
            return
        if self.variant == "hardcoded_issuer":
            issuer = name  # pre-fix: broke the holder-leave handoff
        else:
            issuer = plan_succession(holder, survivors)
        if not self._mint_ok(messages, epoch + 1, survivors, issuer):
            return
        msg = (epoch + 1, survivors, issuer)
        out = list(reps)
        out[i] = (False,) + reps[i][1:]
        yield f"leave({name})", (tuple(out), messages | {msg})

    def _restart_events(self, reps: tuple[Any, ...],
                        messages: frozenset[Any],
                        i: int) -> Iterable[tuple[str, Any]]:
        """Dead replica rejoins via the join handshake: the lease
        holder folds it in at epoch+1 and the joiner adopts the
        incumbent from the reply (it never self-elects)."""
        name = self.names[i]
        for j, hname in enumerate(self.names):
            h = reps[j]
            if not h[0] or h[2] != hname:
                continue  # only a replica believing itself holder folds
            _alive, h_epoch, _holder, h_peers, _awaiting = h
            if h_epoch + 1 > self.epoch_cap:
                continue
            new_peers = tuple(sorted(set(h_peers) | {name}))
            if not self._mint_ok(messages, h_epoch + 1, new_peers,
                                 hname):
                continue
            new_state, new_msgs = self._issue(
                reps, messages, j, new_peers, hname)
            out = list(new_state)
            folded = out[j]
            out[i] = (True, folded[1], folded[2], folded[3], False)
            yield (f"restart({name} joins via {hname})",
                   (tuple(out), new_msgs))

    # -- invariants ----------------------------------------------------------

    def violations(self, state: Any) -> Iterable[tuple[str, str]]:
        reps, messages = state
        alive = self._alive_names(reps)
        # no split-brain: two LIVE self-believing holders never share
        # an epoch. Only meaningful without partitioned probers — a
        # partition can mint transient dual holders by design; there
        # the protection is the conflict rejection below.
        if not self.suspects:
            holders = [(n, r[1]) for n, r in zip(self.names, reps)
                       if r[0] and r[2] == n]
            for a in range(len(holders)):
                for b in range(a + 1, len(holders)):
                    if holders[a][1] == holders[b][1]:
                        yield ("no-split-brain",
                               f"replicas {holders[a][0]!r} and "
                               f"{holders[b][0]!r} both hold the lease "
                               f"at epoch {holders[a][1]}")
        # the lease holder governs a membership it belongs to
        for n, r in zip(self.names, reps):
            if r[0] and r[2] not in r[3]:
                yield ("holder-in-peers",
                       f"replica {n!r} adopted lease holder {r[2]!r} "
                       f"outside its membership {list(r[3])!r}")
        # at most one epoch bump per succession: the epochs ever minted
        # form a contiguous range from the initial epoch
        epochs = {1} | {m[0] for m in messages} | {r[1] for r in reps}
        if sorted(epochs) != list(range(1, max(epochs) + 1)):
            yield ("contiguous-epochs",
                   f"epoch set {sorted(epochs)} has a gap: some "
                   f"succession bumped by more than one")
        # the PR 16 wedge: a replica awaiting a membership that is
        # already fully reflected (its peers == the live set) with no
        # newer broadcast in flight will wait forever
        if not self.suspects:
            max_msg = max((m[0] for m in messages), default=0)
            for n, r in zip(self.names, reps):
                if (r[0] and r[4] and set(r[3]) == set(alive)
                        and max_msg <= r[1]):
                    yield ("no-await-wedge",
                           f"replica {n!r} awaits a membership apply "
                           f"at epoch {r[1]} but its peer set already "
                           f"matches the survivors and no newer "
                           f"broadcast exists — it waits forever")

    def describe_state(self, state: Any) -> str:
        reps, messages = state
        parts = []
        for n, (alive, epoch, holder, peers, awaiting) in zip(
                self.names, reps):
            parts.append(
                f"{n}[{'up' if alive else 'DOWN'} e{epoch} "
                f"holder={holder} peers={{{','.join(peers)}}}"
                f"{' AWAITING' if awaiting else ''}]")
        msgs = ", ".join(f"(e{e},{{{','.join(p)}}},{i})"
                         for e, p, i in sorted(messages)) or "none"
        return " ".join(parts) + f" inflight: {msgs}"


# ---------------------------------------------------------------------------
# seq dedup / watermark seeding (KTL131 + KTL132)
# ---------------------------------------------------------------------------

# tracker: None | (max_seen, order, epoch, lost)
# state: (owner, ring_epoch, emitted, acked, next_send, trackers,
#         replay_loss)

_SEQ_RUN = "kepmc-run"


class SeqDeliveryModel:
    """One agent's window stream against two aggregator replicas under
    elastic ownership: emit, deliver, drop-response (server ingested,
    2xx lost — the agent re-sends), spool-tail rewind (the send cursor
    steps BACK and the tail re-delivers in order: the wire is one FIFO
    drain loop, so replays never skip a seq), scale ops (ownership
    moves + ring-epoch bump), replica restarts (trackers are memory).
    Every observation runs the real :class:`SeqTracker` with the real
    watermark seeding rules."""

    def __init__(self, windows: int = 6, dedup_window: int = 2,
                 epoch_cap: int = 4, replicas: int = 2,
                 variant: str = "shipped") -> None:
        self.windows = windows
        self.dedup_window = dedup_window
        self.epoch_cap = epoch_cap
        self.replicas = replicas
        self.variant = variant

    def initial(self) -> Any:
        return 0, 1, 0, 0, 1, (None,) * self.replicas, False

    def _ingest(self, trackers: tuple[Any, ...], owner: int,
                ring_epoch: int, acked: int,
                seq: int) -> tuple[tuple[Any, ...], bool, int]:
        """The aggregator ``_ingest_payload`` seq accounting, driven
        through the real pure functions → (trackers', dup, lost)."""
        entry = trackers[owner]
        t = SeqTracker(_SEQ_RUN, self.dedup_window)
        prior_lost = 0
        if entry is None:
            seed_fresh_tracker(t, acked, seq)
        else:
            max_seen, order, tepoch, prior_lost = entry
            t.max_seen = max_seen
            for s in order:
                t.seen.add(s)
                t.order.append(s)
            t.ring_epoch = tepoch
        if self.variant != "skip_ownership_reseed":
            reseed_on_ownership_return(t, ring_epoch, acked, seq)
        dup, lost = t.observe(seq)
        out = list(trackers)
        out[owner] = (t.max_seen, tuple(t.order), t.ring_epoch,
                      prior_lost + lost)
        return tuple(out), dup, lost

    def successors(self, state: Any) -> Iterable[tuple[str, Any]]:
        (owner, epoch, emitted, acked, next_send, trackers,
         replay_loss) = state
        if any(True for _ in self.violations(state)):
            return  # absorbing
        if emitted < self.windows:
            yield "emit", (owner, epoch, emitted + 1, acked, next_send,
                           trackers, replay_loss)
        if next_send <= emitted:
            seq = next_send
            tr, _dup, lost = self._ingest(trackers, owner, epoch,
                                          acked, seq)
            # a re-sent concluded seq that still counts loss breaks
            # replay idempotence (it can never be a real gap: FIFO)
            bad_replay = replay_loss or (seq <= acked and lost > 0)
            kind = "replay" if seq <= acked else "deliver"
            yield (f"{kind}(seq={seq} -> r{owner})",
                   (owner, epoch, emitted, max(acked, seq), seq + 1,
                    tr, bad_replay))
            yield (f"drop_response(seq={seq} -> r{owner})",
                   (owner, epoch, emitted, acked, next_send, tr,
                    bad_replay))
        # spool rewind: the send cursor steps back over concluded
        # records (bounded tail); the drain loop then re-delivers them
        # IN ORDER before any fresh window
        for back in (1, 2):
            tgt = acked + 1 - back
            if 1 <= tgt < next_send:
                yield (f"rewind(to seq={tgt})",
                       (owner, epoch, emitted, acked, tgt, trackers,
                        replay_loss))
        if epoch < self.epoch_cap and self.replicas > 1:
            yield (f"scale(owner -> r{(owner + 1) % self.replicas})",
                   ((owner + 1) % self.replicas, epoch + 1, emitted,
                    acked, next_send, trackers, replay_loss))
        for r in range(self.replicas):
            if trackers[r] is not None:
                out = list(trackers)
                out[r] = None
                yield (f"restart(r{r})",
                       (owner, epoch, emitted, acked, next_send,
                        tuple(out), replay_loss))

    def violations(self, state: Any) -> Iterable[tuple[str, str]]:
        (_owner, _epoch, _emitted, _acked, _next_send, trackers,
         replay_loss) = state
        # every window reaches SOME owner in this model (the spool is
        # durable and sends are FIFO), so ANY counted loss is fabricated
        for r, entry in enumerate(trackers):
            if entry is not None and entry[3] > 0:
                yield ("no-fabricated-loss",
                       f"replica r{r} counted {entry[3]} lost "
                       f"window(s) although every window was delivered "
                       f"to its then-owner")
        if replay_loss:
            yield ("replay-idempotent",
                   "a spool-tail replay of an already-concluded seq "
                   "was counted as loss instead of being absorbed")

    def describe_state(self, state: Any) -> str:
        (owner, epoch, emitted, acked, next_send, trackers,
         replay_loss) = state
        ts = []
        for r, entry in enumerate(trackers):
            if entry is None:
                ts.append(f"r{r}[-]")
            else:
                ms, order, tepoch, lost = entry
                ts.append(f"r{r}[max={ms} seen={list(order)} "
                          f"e{tepoch} lost={lost}]")
        return (f"owner=r{owner} ring_epoch={epoch} emitted={emitted} "
                f"acked={acked} next_send={next_send} "
                + " ".join(ts)
                + (" REPLAY-LOSS" if replay_loss else ""))


# ---------------------------------------------------------------------------
# spool ack cursor / rewind (KTL131)
# ---------------------------------------------------------------------------

# record ledger status: "p" pending | "a" acked | "e" evicted
# state: (sealed, active, cursor, ledger, stale_flag, rewind_flag)
#   sealed: tuple[(idx, count), ...]   active: (idx, count)
#   ledger: tuple[(seg, off, status), ...] in append order


class SpoolCursorModel:
    """The spool's durability cursor under append/rotate, in-order and
    batched (segment-hop) acks, STALE acks racing cap eviction, peek
    hops, and bounded rewind — every cursor move computed by the real
    :func:`plan_ack_cursor` / :func:`plan_rewind_tail` (unit-sized
    records: offset == record ordinal, record_end == offset+1)."""

    def __init__(self, max_records: int = 5, segment_records: int = 2,
                 rewind_max: int = 2, variant: str = "shipped") -> None:
        self.max_records = max_records
        self.segment_records = segment_records
        self.rewind_max = rewind_max
        self.variant = variant

    def initial(self) -> Any:
        return (), (1, 0), (1, 0), (), False, False

    @staticmethod
    def _count(sealed: tuple[Any, ...], active: Any, seg: int) -> int:
        if seg == active[0]:
            return int(active[1])
        for idx, count in sealed:
            if idx == seg:
                return int(count)
        return 0

    def _next_seg(self, sealed: tuple[Any, ...], active: Any,
                  seg: int) -> int | None:
        later = [idx for idx, _ in sealed if idx > seg]
        if active[0] > seg:
            later.append(active[0])
        return min(later) if later else None

    def successors(self, state: Any) -> Iterable[tuple[str, Any]]:
        sealed, active, cursor, ledger, stale, rew = state
        if any(True for _ in self.violations(state)):
            return  # absorbing
        if len(ledger) < self.max_records:
            rec = (active[0], active[1], "p")
            new_active = (active[0], active[1] + 1)
            new_sealed = sealed
            if new_active[1] == self.segment_records:
                new_sealed = sealed + ((active[0],
                                        self.segment_records),)
                new_active = (active[0] + 1, 0)
            yield (f"append(seg={rec[0]},off={rec[1]})",
                   (new_sealed, new_active, cursor, ledger + (rec,),
                    stale, rew))
        yield from self._ack_events(state)
        # peek hop: the cursor parked at a sealed segment's end hops to
        # the next segment's first frame (spool.peek's shape)
        seg, off = cursor
        if (seg != active[0]
                and off >= self._count(sealed, active, seg)):
            nxt = self._next_seg(sealed, active, seg)
            if nxt is not None:
                yield (f"peek_hop(-> seg={nxt})",
                       (sealed, active, (nxt, 0), ledger, stale, rew))
        if sealed:
            yield from self._evict_event(state)
        yield from self._rewind_event(state)

    def _ack_events(self, state: Any) -> Iterable[tuple[str, Any]]:
        sealed, active, cursor, ledger, stale, rew = state
        seg, off = cursor
        end = self._count(sealed, active, seg)
        nxt = self._next_seg(sealed, active, seg)
        for rseg, roff, status in ledger:
            if status != "p":
                continue
            new_cursor = plan_ack_cursor(cursor, (rseg, roff),
                                         roff + 1, end, nxt)
            legit = (rseg, roff) == cursor or (
                off >= end and nxt is not None and rseg == nxt
                and roff == 0)
            if new_cursor is None:
                continue  # stale ack correctly refused: a no-op
            new_ledger = tuple(
                (s, o, "a" if (s, o) == (rseg, roff) else st)
                for s, o, st in ledger)
            yield (f"ack(seg={rseg},off={roff})",
                   (sealed, active, new_cursor, new_ledger,
                    stale or not legit, rew))

    def _evict_event(self, state: Any) -> Iterable[tuple[str, Any]]:
        sealed, active, cursor, ledger, stale, rew = state
        oldest = min(idx for idx, _ in sealed)
        new_sealed = tuple((i, c) for i, c in sealed if i != oldest)
        new_ledger = tuple(
            (s, o, "e" if s == oldest and st == "p" else st)
            for s, o, st in ledger)
        new_cursor = cursor
        if cursor[0] <= oldest:
            new_cursor = (oldest + 1, 0)  # spool._evict_for_locked
        yield (f"evict(seg={oldest})",
               (sealed and new_sealed or (), active, new_cursor,
                new_ledger, stale, rew))

    def _rewind_event(self, state: Any) -> Iterable[tuple[str, Any]]:
        sealed, active, cursor, ledger, stale, rew = state
        seg, off = cursor
        starts = tuple(range(self._count(sealed, active, seg)))
        tail = plan_rewind_tail(starts, off, self.rewind_max)
        if not tail:
            return
        bad = any(st != "a"
                  for s, o, st in ledger
                  if s == seg and o in tail)
        new_ledger = tuple(
            (s, o, "p" if s == seg and o in tail else st)
            for s, o, st in ledger)
        yield (f"rewind({len(tail)} record(s))",
               (sealed, active, (seg, tail[0]), new_ledger, stale,
                rew or bad))

    def violations(self, state: Any) -> Iterable[tuple[str, str]]:
        _sealed, _active, cursor, ledger, stale, rew = state
        for seg, off, status in ledger:
            before = (seg, off) < cursor
            if before and status == "p":
                yield ("cursor-no-skip",
                       f"cursor {cursor} passed record "
                       f"(seg={seg},off={off}) whose delivery never "
                       f"concluded — it is silently lost")
            if not before and status == "a":
                yield ("cursor-no-skip",
                       f"record (seg={seg},off={off}) is concluded but "
                       f"sits at/after cursor {cursor} — it would "
                       f"re-deliver as fresh")
        if stale:
            yield ("stale-ack-rejected",
                   "an ack for a record the cursor does not point at "
                   "(nor the one legitimate segment hop) was honored")
        if rew:
            yield ("rewind-bounded",
                   "a rewind re-opened a record that was never "
                   "concluded, or reached outside the cursor segment")

    def describe_state(self, state: Any) -> str:
        sealed, active, cursor, ledger, stale, rew = state
        recs = " ".join(f"{s}.{o}:{st}" for s, o, st in ledger) or "none"
        return (f"cursor={cursor} active=seg{active[0]}"
                f"({active[1]} rec) sealed={list(sealed)} "
                f"records: {recs}")


# ---------------------------------------------------------------------------
# wire-v2 keyframe / delta / 409 (KTL132)
# ---------------------------------------------------------------------------

# state: (seq, needs_kf, kf_base, since_kf, disrupted, owner, bases,
#         w409, dup_flag)

_KF_RUN = "kepmc-run"


class KeyframeDeltaModel:
    """The wire-v2 base-row machine: an agent streaming windows to two
    replicas through keyframe/delta selection (the real
    :func:`keyframe_wanted`), server-side base matching (the real
    :func:`delta_base_matches`), 409 needs-keyframe recovery, response
    loss, owner hand-off, base eviction, and duplicate keyframe
    replays. ``keyframe_every`` cadence and window count stay tiny —
    the machine has no long-range state."""

    def __init__(self, windows: int = 4, keyframe_every: int = 2,
                 replicas: int = 2, variant: str = "shipped") -> None:
        self.windows = windows
        self.keyframe_every = keyframe_every
        self.replicas = replicas
        self.variant = variant

    def initial(self) -> Any:
        return 1, False, None, 0, False, 0, (None,) * self.replicas, 0, False

    def _want_kf(self, needs: bool, disrupted: bool, kf_base: Any,
                 since: int) -> bool:
        needs_in = False if self.variant == "ignore_needs_flag" else needs
        return keyframe_wanted(
            needs_keyframe=needs_in,
            delivery_path="replay" if disrupted else "fresh",
            has_base=kf_base is not None, run_matches=True,
            since_keyframe=since, keyframe_every=self.keyframe_every)

    def _base_ok(self, bases: tuple[Any, ...], owner: int,
                 kf_base: Any) -> bool:
        if bases[owner] is None or kf_base is None:
            return False
        return delta_base_matches(_KF_RUN, int(bases[owner]), _KF_RUN,
                                  int(kf_base))

    def successors(self, state: Any) -> Iterable[tuple[str, Any]]:
        (seq, needs, kf_base, since, disrupted, owner, bases, w409,
         dup_flag) = state
        if any(True for _ in self.violations(state)):
            return  # absorbing
        if seq <= self.windows:
            wk = self._want_kf(needs, disrupted, kf_base, since)
            if wk:
                nb = list(bases)
                nb[owner] = seq  # keyframe plants the base (dup-safe)
                yield (f"send_kf_ok(seq={seq} -> r{owner})",
                       (seq + 1, False, seq, 0, False, owner,
                        tuple(nb), 0, dup_flag))
                yield (f"send_kf_lost(seq={seq} -> r{owner})",
                       (seq, needs, kf_base, since, True, owner,
                        tuple(nb), w409, dup_flag))
            elif self._base_ok(bases, owner, kf_base):
                yield (f"send_delta_ok(seq={seq} -> r{owner})",
                       (seq + 1, needs, kf_base, since + 1, False,
                        owner, bases, 0, dup_flag))
                yield (f"send_delta_lost(seq={seq} -> r{owner})",
                       (seq, needs, kf_base, since, True, owner,
                        bases, w409, dup_flag))
            else:
                # the structured 409: base missing/mismatched after a
                # hand-off, eviction or run change
                yield (f"recv_409(seq={seq} from r{owner})",
                       (seq, True, kf_base, since, disrupted, owner,
                        bases, min(w409 + 1, 3), dup_flag))
        if kf_base is not None:
            # spool-tail replay re-delivers the acked keyframe: the
            # duplicate MUST still plant the base (hand-off recovery)
            nb = list(bases)
            planted = kf_base
            if self.variant == "dup_kf_skips_base":
                planted = bases[owner]  # pre-hardening: dup judged, dropped
            nb[owner] = planted
            yield (f"dup_kf(seq={kf_base} -> r{owner})",
                   (seq, needs, kf_base, since, disrupted, owner,
                    tuple(nb), w409,
                    dup_flag or nb[owner] != kf_base))
        if self.replicas > 1:
            yield (f"handoff(-> r{(owner + 1) % self.replicas})",
                   (seq, needs, kf_base, since, True,
                    (owner + 1) % self.replicas, bases, w409,
                    dup_flag))
        if bases[owner] is not None:
            nb = list(bases)
            nb[owner] = None
            yield (f"evict_base(r{owner})",
                   (seq, needs, kf_base, since, disrupted, owner,
                    tuple(nb), w409, dup_flag))

    def violations(self, state: Any) -> Iterable[tuple[str, str]]:
        (_seq, _needs, _kf_base, _since, _disrupted, _owner, _bases,
         w409, dup_flag) = state
        # a 409 latches needs_keyframe, and keyframe_wanted() makes the
        # very next send a keyframe — which can never 409. So one
        # window sees at most ONE 409: the loop converges in a single
        # round-trip.
        if w409 > 1:
            yield ("409-converges",
                   f"the same window drew {w409} needs-keyframe "
                   f"answers: the 409 recovery loop is not converging")
        if dup_flag:
            yield ("dup-keyframe-plants-base",
                   "a duplicate keyframe was dedup-dropped WITHOUT "
                   "planting the delta base — the hand-off replay "
                   "cannot re-arm deltas")

    def describe_state(self, state: Any) -> str:
        (seq, needs, kf_base, since, disrupted, owner, bases, w409,
         dup_flag) = state
        bs = " ".join(f"r{r}[base={b}]" for r, b in enumerate(bases))
        return (f"window={seq} needs_kf={needs} agent_base={kf_base} "
                f"since_kf={since} path="
                f"{'replay' if disrupted else 'fresh'} owner=r{owner} "
                f"{bs} window_409s={w409}")


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------

MODEL_BUILDERS: dict[str, type] = {
    "lease": LeaseSuccessionModel,
    "seq": SeqDeliveryModel,
    "spool": SpoolCursorModel,
    "keyframe": KeyframeDeltaModel,
}


def build_model(model: str, params: Mapping[str, Any] | None = None,
                variant: str = "shipped") -> Any:
    """Instantiate a registered model with a case's params/variant."""
    try:
        cls = MODEL_BUILDERS[model]
    except KeyError:
        raise ValueError(f"unknown protocol model {model!r}; "
                         f"registered: {sorted(MODEL_BUILDERS)}")
    return cls(**dict(params or {}), variant=variant)
