"""kepmc explicit-state explorer: exhaustive BFS over a protocol model.

The fleet's chaos tests sample a few dozen interleavings per run; every
PR 16 bug hid in a schedule they happened not to draw. This explorer
closes that gap at small scope: a model exposes an initial state, a
successor relation (every event any component could take next), and
safety invariants — the explorer walks EVERY reachable state
breadth-first, so the first state violating an invariant yields a
MINIMAL event trace (BFS discovery order is shortest-path order).

Design points, in the TLC tradition:

- **Canonical hashable states.** A state is a plain tuple the model
  builds; hashing dedupes revisits, so duplicate/reorder events (which
  loop back to seen states) terminate naturally.
- **Bounded scope.** Models cap epochs/windows/records; the explorer
  additionally hard-caps the state count (``max_states``) and raises
  :class:`StateExplosionError` instead of silently truncating — a
  truncated "all clear" would be a false negative.
- **Possibility goals.** Pure safety misses wedges ("awaiting forever"
  is a liveness failure). A model may declare a ``goal`` predicate and
  a ``goal_event_ok`` label filter; after the forward sweep the
  explorer computes backward reachability from the goal states over
  the permitted edges — any reachable state that can NEVER reach a
  goal state is reported with its (minimal) discovery trace. This is
  TLA+'s "eventually possible" weakening of liveness, which is exactly
  what a wedge violates.

No clocks, no randomness, no I/O: same model → same exploration,
state-for-state.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Hashable, Iterable, Protocol

__all__ = [
    "Counterexample",
    "ExplorationResult",
    "ProtocolModel",
    "StateExplosionError",
    "explore",
]

State = Hashable


class StateExplosionError(RuntimeError):
    """The model's reachable space outgrew the declared scope cap —
    the SPEC is wrong (unbounded epoch/seq growth), not the fleet."""


class ProtocolModel(Protocol):
    """What the explorer needs from a model (duck-typed; the concrete
    models in :mod:`.models` drive the real fleet transition code)."""

    def initial(self) -> State: ...

    def successors(self, state: State) -> Iterable[tuple[str, State]]: ...

    def violations(self, state: State) -> Iterable[tuple[str, str]]: ...

    def describe_state(self, state: State) -> str: ...


@dataclasses.dataclass(frozen=True)
class Counterexample:
    """One invariant violation, with the shortest event schedule that
    reaches it from the initial state — the review surface."""

    invariant: str
    detail: str
    trace: tuple[str, ...]
    state_repr: str

    def format(self) -> str:
        steps = "\n".join(f"  {i + 1}. {ev}"
                          for i, ev in enumerate(self.trace)) or "  (initial state)"
        return (f"invariant `{self.invariant}` violated: {self.detail}\n"
                f"minimal trace ({len(self.trace)} event(s)):\n{steps}\n"
                f"  => {self.state_repr}")


@dataclasses.dataclass(frozen=True)
class ExplorationResult:
    """Outcome of one exhaustive exploration."""

    states: int
    transitions: int
    depth: int
    counterexamples: tuple[Counterexample, ...]

    @property
    def ok(self) -> bool:
        return not self.counterexamples


def _trace_of(state: State,
              parents: dict[State, tuple[State, str] | None]
              ) -> tuple[str, ...]:
    labels: list[str] = []
    cur: State = state
    while True:
        link = parents[cur]
        if link is None:
            break
        cur, label = link
        labels.append(label)
    labels.reverse()
    return tuple(labels)


def explore(model: ProtocolModel,
            max_states: int = 250_000) -> ExplorationResult:
    """Exhaustively explore ``model``; first (= minimal-trace)
    counterexample per invariant. Deterministic: successor order is the
    model's, discovery is FIFO."""
    init = model.initial()
    parents: dict[State, tuple[State, str] | None] = {init: None}
    depth: dict[State, int] = {init: 0}
    order: list[State] = [init]
    edges: dict[State, tuple[tuple[str, State], ...]] = {}
    found: dict[str, Counterexample] = {}
    transitions = 0
    max_depth = 0
    i = 0
    while i < len(order):
        state = order[i]
        i += 1
        for invariant, detail in model.violations(state):
            if invariant not in found:
                found[invariant] = Counterexample(
                    invariant=invariant, detail=detail,
                    trace=_trace_of(state, parents),
                    state_repr=model.describe_state(state))
        succ: list[tuple[str, State]] = []
        for label, nxt in model.successors(state):
            transitions += 1
            succ.append((label, nxt))
            if nxt not in parents:
                parents[nxt] = (state, label)
                depth[nxt] = depth[state] + 1
                max_depth = max(max_depth, depth[nxt])
                order.append(nxt)
                if len(order) > max_states:
                    raise StateExplosionError(
                        f"model exceeded the {max_states}-state scope "
                        f"cap at depth {depth[nxt]}; tighten the case "
                        f"bounds (epoch/window/record caps)")
        edges[state] = tuple(succ)

    goal: Callable[[State], bool] | None = getattr(model, "goal", None)
    if goal is not None:
        found.update(_check_goal(model, goal, order, edges, parents,
                                 depth, found))
    ranked = sorted(found.values(),
                    key=lambda c: (len(c.trace), c.invariant))
    return ExplorationResult(states=len(order), transitions=transitions,
                             depth=max_depth,
                             counterexamples=tuple(ranked))


def _check_goal(model: ProtocolModel, goal: Callable[[State], bool],
                order: list[State],
                edges: dict[State, tuple[tuple[str, State], ...]],
                parents: dict[State, tuple[State, str] | None],
                depth: dict[State, int],
                found: dict[str, Counterexample],
                ) -> dict[str, Counterexample]:
    """Possibility check: every reachable state must be able to reach a
    goal state via permitted events (wedge detection — see module
    docstring)."""
    goal_name: str = getattr(model, "goal_name", "goal-reachable")
    if goal_name in found:
        return {}
    event_ok: Callable[[str], bool] = getattr(
        model, "goal_event_ok", lambda _label: True)
    preds: dict[State, list[State]] = {}
    for src, succ in edges.items():
        for label, dst in succ:
            if event_ok(label):
                preds.setdefault(dst, []).append(src)
    can_reach = {s for s in order if goal(s)}
    stack = list(can_reach)
    while stack:
        dst = stack.pop()
        for src in preds.get(dst, ()):
            if src not in can_reach:
                can_reach.add(src)
                stack.append(src)
    stuck = [s for s in order if s not in can_reach]
    if not stuck:
        return {}
    worst = min(stuck, key=lambda s: depth[s])
    return {goal_name: Counterexample(
        invariant=goal_name,
        detail=(f"{len(stuck)} reachable state(s) can NEVER reach the "
                f"goal again (a wedge): no schedule of permitted "
                f"events recovers"),
        trace=_trace_of(worst, parents),
        state_repr=model.describe_state(worst))}
