"""kepmc check families KTL130-132 + the protocol-tier runner.

Each rule consumes the :class:`ModelReport` of one registry case — the
exhaustive exploration of the shipped transition code at that case's
scope — and yields engine :class:`~kepler_tpu.analysis.engine
.Diagnostic`\\ s anchored at the protocol's home module, so
protocol-tier findings ride the same severity, baseline-ratchet and
text/json/SARIF machinery as every other keplint rule. Explorations
are cached per (spec, case) for the life of the process.

A counterexample's diagnostic carries the FULL minimal event trace:
the finding is a schedule, and the schedule is the review surface.
The baseline stays EMPTY for this tier by policy — a reachable
protocol violation is a bug to fix, never a debt to grandfather.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from kepler_tpu.analysis.engine import (
    Diagnostic,
    ProtocolRule,
    SEVERITY_ERROR,
    register,
)
from kepler_tpu.analysis.protocol.explorer import (
    ExplorationResult,
    explore,
)
from kepler_tpu.analysis.protocol.registry import (
    PROTOCOL_SPECS,
    ProtocolCase,
    ProtocolSpec,
)

__all__ = [
    "INVARIANT_RULE",
    "ModelReport",
    "PROTOCOL_RULE_IDS",
    "analyze_protocol_specs",
    "clear_exploration_cache",
    "explore_case",
]

PROTOCOL_RULE_IDS = ("KTL130", "KTL131", "KTL132")

#: invariant name (Counterexample.invariant) → owning rule id. Every
#: invariant a registered model can emit MUST appear here — an unmapped
#: counterexample reports under KTL000 so it cannot vanish silently.
INVARIANT_RULE: dict[str, str] = {
    # epoch safety (KTL130)
    "no-split-brain": "KTL130",
    "holder-in-peers": "KTL130",
    "contiguous-epochs": "KTL130",
    "no-await-wedge": "KTL130",
    # loss accounting (KTL131)
    "no-fabricated-loss": "KTL131",
    "cursor-no-skip": "KTL131",
    "stale-ack-rejected": "KTL131",
    "rewind-bounded": "KTL131",
    # replay idempotence (KTL132)
    "replay-idempotent": "KTL132",
    "409-converges": "KTL132",
    "dup-keyframe-plants-base": "KTL132",
}


@dataclasses.dataclass(frozen=True)
class ModelReport:
    """One registry case's exhaustive exploration of the SHIPPED code."""

    spec: ProtocolSpec
    case: ProtocolCase
    result: ExplorationResult

    @property
    def key(self) -> str:
        return f"{self.spec.name}/{self.case.name}"


# process-lifetime exploration cache: (spec.name, case.name) → report
_EXPLORE_CACHE: dict[tuple[str, str], ModelReport] = {}


def clear_exploration_cache() -> None:
    _EXPLORE_CACHE.clear()


def explore_case(spec: ProtocolSpec, case: ProtocolCase) -> ModelReport:
    """Explore one registry case (shipped variant), cached."""
    from kepler_tpu.analysis.protocol.models import build_model

    key = (spec.name, case.name)
    report = _EXPLORE_CACHE.get(key)
    if report is None:
        model = build_model(spec.model, case.params)
        result = explore(model, max_states=case.max_states)
        report = ModelReport(spec=spec, case=case, result=result)
        _EXPLORE_CACHE[key] = report
    return report


def _diag(rule_id: str, severity: str, report: ModelReport,
          message: str) -> Diagnostic:
    return Diagnostic(
        path=report.spec.source, line=1, col=1, rule_id=rule_id,
        severity=severity, message=f"[{report.key}] {message}")


class _InvariantRule(ProtocolRule):
    """Shared shape: report every counterexample whose invariant this
    rule owns, with its minimal event trace inline."""

    def check_model(self, report: ModelReport) -> Iterable[Diagnostic]:
        for cex in report.result.counterexamples:
            if INVARIANT_RULE.get(cex.invariant) != self.id:
                continue
            yield _diag(self.id, self.severity, report, cex.format())


@register
class EpochSafetyRule(_InvariantRule):
    id = "KTL130"
    name = "protocol-epoch-safety"
    summary = ("exhaustive exploration of the lease/succession model "
               "finds no reachable epoch-safety violation: no two live "
               "holders at one epoch, every adopted holder inside its "
               "membership, epochs contiguous (at most one bump per "
               "succession), no awaiting-forever wedge")
    rationale = (
        "The coordinator lease is the fleet's only writer-election "
        "mechanism: a split-brain (two live replicas believing they "
        "hold the lease at the SAME epoch) double-drives autoscale and "
        "membership, and an epoch that jumps by more than one per "
        "succession breaks the redirect-ordering contract every agent "
        "relies on. The chaos suite samples a few dozen interleavings; "
        "all three PR 16 bugs hid in schedules it did not draw. This "
        "rule explores EVERY schedule at the registry scopes — crash, "
        "notice, leave, restart-join, duplicated and reordered "
        "broadcasts — through the real plan_succession / "
        "plan_membership_apply / CoordinatorLease.adopt, and fails "
        "with the minimal event trace when any reachable state "
        "violates epoch safety (including the broadcast-lands-before-"
        "demote wedge, rediscovered from the pre-fix code by this "
        "exact check).")


@register
class LossAccountingRule(_InvariantRule):
    id = "KTL131"
    name = "protocol-loss-accounting"
    summary = ("exhaustive exploration of the delivery-plane models "
               "finds no reachable loss-accounting violation: no "
               "fabricated loss counts, no spool record skipped or "
               "stale-acked, rewinds bounded to concluded records")
    rationale = (
        "`windows_lost` is the fleet's data-integrity metric: "
        "operators page on it, and the at-least-once delivery design "
        "(spool + dedup window + watermark seeding) exists so that a "
        "membership change is replay, NOT loss. A seq tracker that "
        "counts a gap for windows that were delivered to their "
        "then-owner fabricates exactly the signal the metric exists "
        "to catch (the PR 16 ownership-return bug), and a spool "
        "cursor that hops past an un-acked record silently loses it. "
        "This rule drives the REAL SeqTracker seeding/observe rules "
        "and the REAL plan_ack_cursor / plan_rewind_tail through "
        "every FIFO delivery, response-loss, rewind, scale-flap, "
        "restart and eviction schedule at the registry scopes, and "
        "fails with the minimal trace when any reachable state counts "
        "loss for a delivered window or moves the cursor wrong.")


@register
class ReplayIdempotenceRule(_InvariantRule):
    id = "KTL132"
    name = "protocol-replay-idempotence"
    summary = ("exhaustive exploration finds replays idempotent: "
               "re-delivered seqs never count loss, duplicate "
               "keyframes still plant the delta base, and a 409 "
               "needs-keyframe answer converges in one round trip")
    rationale = (
        "At-least-once delivery makes duplicates a steady-state "
        "condition, not an edge case: every spool rewind, dropped "
        "2xx and ownership hand-off re-delivers concluded seqs. The "
        "protocol is only correct if replay is a no-op everywhere — "
        "the dedup window absorbs the seq, the duplicate keyframe "
        "STILL plants the server-side delta base (else the hand-off "
        "replay can never re-arm deltas), and a 409 forces a keyframe "
        "that cannot itself 409 (one round trip to convergence, never "
        "a loop). This rule explores the real keyframe_wanted / "
        "delta_base_matches machine and the real tracker replay path "
        "under every duplicate/reorder/hand-off schedule at the "
        "registry scopes and fails with the minimal trace when any "
        "replay changes accounting or the 409 loop fails to converge.")


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def analyze_protocol_specs(
        root: str,
        only: set[str] | None = None,
        specs: tuple[ProtocolSpec, ...] = PROTOCOL_SPECS,
) -> list[Diagnostic]:
    """Explore every registry case and run the protocol-tier families.

    ``only`` restricts to a subset of rule ids (the CLI's ``--only``);
    model build/exploration failures always report (as KTL000).
    ``root`` is unused (kept for runner-signature symmetry with the
    device tier).
    """
    del root
    from kepler_tpu.analysis.engine import REGISTRY

    def want(rule_id: str) -> bool:
        return only is None or rule_id in only

    diags: list[Diagnostic] = []
    rules = [REGISTRY[rid] for rid in PROTOCOL_RULE_IDS if want(rid)]
    if not rules:
        return diags
    for spec in specs:
        for case in spec.cases:
            try:
                report = explore_case(spec, case)
            except Exception as err:  # StateExplosionError included
                diags.append(Diagnostic(
                    path=spec.source, line=1, col=1, rule_id="KTL000",
                    severity=SEVERITY_ERROR,
                    message=f"[{spec.name}/{case.name}] protocol model "
                            f"failed to build/explore: "
                            f"{type(err).__name__}: {str(err)[:300]}"))
                continue
            for rule in rules:
                diags.extend(rule.check_model(report))
            # an invariant outside INVARIANT_RULE must not vanish just
            # because no rule claimed it
            for cex in report.result.counterexamples:
                if cex.invariant not in INVARIANT_RULE:
                    diags.append(_diag(
                        "KTL000", SEVERITY_ERROR, report,
                        f"counterexample for unmapped invariant "
                        f"{cex.invariant!r}: {cex.format()}"))
    return sorted(diags)
