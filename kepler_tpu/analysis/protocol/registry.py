"""The kepmc protocol registry: which models run at which scopes.

One :class:`ProtocolSpec` per fleet protocol (mirroring the kepljax
``ProgramSpec`` pattern), each with declared exploration cases — the
scope bounds (replica count, epoch caps, window counts, message caps)
at which the state space is BOTH exhaustively explorable and large
enough to contain every schedule class the protocol distinguishes
(crash/heal orderings, duplicate and reordered broadcasts, response
loss, ownership flaps, partitioned probes). Every case here explores
the SHIPPED transition code; the bug variants (``models.py``) exist
only for the negative-path tests.

``invariants`` documents, per spec, which safety properties the
model's :meth:`violations` checks — the strings match the
counterexample ``invariant`` field, and ``checks.INVARIANT_RULE`` maps
each to its KTL rule id. A spec's ``source`` anchors its diagnostics
at the module whose transition rules the model drives.

Scope discipline: ``max_states`` is a hard cap, not a budget — an
exploration that hits it raises instead of truncating, because a
truncated "all clear" is a false negative. The caps here sit ~10x
above the measured reachable counts so model growth trips loudly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "PROTOCOL_SPECS",
    "ProtocolCase",
    "ProtocolSpec",
    "spec_by_name",
]


@dataclass(frozen=True)
class ProtocolCase:
    """One exploration scope for a spec (name + model build knobs)."""

    name: str
    note: str = ""
    params: dict[str, Any] = field(default_factory=dict)
    max_states: int = 250_000


@dataclass(frozen=True)
class ProtocolSpec:
    """One registered protocol + its declared exploration contract."""

    name: str
    source: str  # repo-relative module whose transitions the model drives
    description: str
    model: str  # key into models.MODEL_BUILDERS
    cases: tuple[ProtocolCase, ...]
    invariants: tuple[str, ...]


PROTOCOL_SPECS: tuple[ProtocolSpec, ...] = (
    ProtocolSpec(
        name="lease.succession",
        source="kepler_tpu/fleet/membership.py",
        description="coordinator lease adopt/succession + membership "
                    "apply/replay under crash, true-death notice, "
                    "graceful leave, restart-join and duplicated/"
                    "reordered broadcasts (plan_succession, "
                    "plan_membership_apply, CoordinatorLease.adopt)",
        model="lease",
        cases=(
            ProtocolCase("n2_e4",
                         "the 2-replica fleet: every pairwise "
                         "crash/leave/heal ordering",
                         params={"replicas": 2, "epoch_cap": 4}),
            ProtocolCase("n3_e5",
                         "full 3-replica scope: concurrent notice, "
                         "competing issuers, restart-join races",
                         params={"replicas": 3, "epoch_cap": 5},
                         max_states=60_000),
        ),
        invariants=("no-split-brain", "holder-in-peers",
                    "contiguous-epochs", "no-await-wedge"),
    ),
    ProtocolSpec(
        name="lease.partitioned",
        source="kepler_tpu/fleet/membership.py",
        description="the same lease machine with a partitioned prober "
                    "that falsely suspects its live holder — transient "
                    "dual holders are legal here; the invariant is "
                    "that equal-epoch conflicts stay REJECTED and "
                    "epochs stay contiguous",
        model="lease",
        cases=(
            ProtocolCase("n3_e4_suspects",
                         params={"replicas": 3, "epoch_cap": 4,
                                 "suspects": True},
                         max_states=200_000),
        ),
        invariants=("holder-in-peers", "contiguous-epochs"),
    ),
    ProtocolSpec(
        name="seq.delivery",
        source="kepler_tpu/fleet/delivery.py",
        description="per-node seq dedup/gap/watermark accounting under "
                    "FIFO delivery, response loss, bounded spool "
                    "rewind, ownership scale-flaps and replica "
                    "restarts (SeqTracker, seed_fresh_tracker, "
                    "reseed_on_ownership_return)",
        model="seq",
        cases=(
            ProtocolCase("k6_w2_e4",
                         "6 windows, dedup window 2, 4 ring epochs "
                         "across 2 replicas",
                         params={}, max_states=400_000),
        ),
        invariants=("no-fabricated-loss", "replay-idempotent"),
    ),
    ProtocolSpec(
        name="spool.cursor",
        source="kepler_tpu/fleet/delivery.py",
        description="spool durability-cursor math under append/rotate, "
                    "in-order + segment-hop acks, stale acks racing "
                    "cap eviction, peek hops and bounded rewind "
                    "(plan_ack_cursor, plan_rewind_tail)",
        model="spool",
        cases=(
            ProtocolCase("r5_s2",
                         "5 records over 2-record segments, rewind "
                         "tail 2",
                         params={}),
        ),
        invariants=("cursor-no-skip", "stale-ack-rejected",
                    "rewind-bounded"),
    ),
    ProtocolSpec(
        name="keyframe.delta",
        source="kepler_tpu/fleet/delivery.py",
        description="wire-v2 base-row machine: keyframe/delta "
                    "selection, server-side base matching, 409 "
                    "needs-keyframe recovery, duplicate keyframe "
                    "replay, owner hand-off and base eviction "
                    "(keyframe_wanted, delta_base_matches)",
        model="keyframe",
        cases=(
            ProtocolCase("k4_every2",
                         "4 windows at keyframe cadence 2 across 2 "
                         "replicas",
                         params={}),
        ),
        invariants=("409-converges", "dup-keyframe-plants-base"),
    ),
)


def spec_by_name(name: str) -> ProtocolSpec:
    for spec in PROTOCOL_SPECS:
        if spec.name == name:
            return spec
    raise KeyError(name)
