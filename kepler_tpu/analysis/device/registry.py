"""The device-program registry: what kepljax traces, and each program's
declared contract.

One :class:`ProgramSpec` per jitted device program the attribution
stack serves, each with representative bucket-shape cases (including
the pad-row/minimal-bucket edges the ladders actually produce) and a
declarative contract the KTL120-123 checks enforce:

- ``donates`` — user-level argument positions whose buffers the
  program consumes; KTL121 requires every flattened leaf of those args
  to carry real input/output aliasing in the lowered module, and no
  undeclared arg to alias.
- ``allowed_collectives`` — the complete set of explicit communication
  primitives the program may contain (KTL122). Empty means "this
  program must be communication-free at the jaxpr tier" — the PR 7
  invariant that the only cross-shard step in a fleet window is the
  caller's result fetch.
- ``allowed_half_casts`` — the half-precision ``convert_element_type``
  pairs that are DECLARED boundaries (the packed f16 wire quantizer,
  bf16 matmul operand feeds). Any other half cast — and any half
  accumulation into a dot/reduction, which no entry may allow — is a
  KTL120 finding.
- ``require_shard_map`` — the program's shard-locality is structural:
  losing the ``shard_map`` (a regression to a replicated-index gather
  GSPMD would satisfy with an all-gather at partitioning time, which
  the jaxpr tier cannot see) fails KTL122 even with an empty
  collective set.

Builders import jax and the program modules lazily so importing the
analysis package (rule registration, docs generation) stays free of
accelerator toolchain costs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

# avals/builders talk in these dtype names; resolved lazily in _sds
_F32 = "float32"
_I32 = "int32"
_BOOL = "bool"

#: packed fleet programs quantize to the f16 wire format exactly once
_F16_OUT = frozenset({"float32->float16"})
#: bf16 matmul-operand feeds (accumulators stay f32 via acc_matmul)
_BF16_OPS = frozenset({"float32->bfloat16"})
#: training graphs additionally carry the transpose of each operand
#: cast (the backward of f32→bf16 is bf16→f32 on the cotangent)
_BF16_TRAIN = frozenset({"float32->bfloat16", "bfloat16->float32"})


@dataclass(frozen=True)
class ProgramCase:
    """One representative shape point for a spec (name + build knobs)."""

    name: str
    note: str = ""
    dims: dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class ProgramSpec:
    """One registered device program + its declared contract."""

    name: str
    source: str  # repo-relative module the program lives in
    description: str
    build: Callable[[ProgramCase], tuple]  # → (jitted fn, avals tuple)
    cases: tuple[ProgramCase, ...]
    n_devices: int = 8
    donates: tuple[int, ...] = ()
    allowed_collectives: frozenset[str] = frozenset()
    allowed_half_casts: frozenset[str] = frozenset()
    require_shard_map: bool = False


# ---------------------------------------------------------------------------
# builder helpers (lazy jax)
# ---------------------------------------------------------------------------


def _sds(shape: tuple[int, ...], dtype: str) -> Any:
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))


def _tree_avals(tree: Any) -> Any:
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def _mesh(n: int, axes: tuple[str, ...] = ("node",),
          shape: tuple[int, ...] | None = None) -> Any:
    import jax

    from kepler_tpu.parallel.mesh import make_mesh

    count = 1
    for s in shape or (n,):
        count *= s
    return make_mesh(shape or (n,), axes, devices=jax.devices()[:count])


def _mlp_avals(n_zones: int) -> Any:
    import jax

    from kepler_tpu.models.mlp import init_mlp

    return _tree_avals(dict(init_mlp(jax.random.PRNGKey(0),
                                     n_zones=n_zones)))


def _temporal_avals(n_zones: int) -> Any:
    import jax

    from kepler_tpu.models.temporal import init_temporal

    return _tree_avals(dict(init_temporal(jax.random.PRNGKey(0),
                                          n_zones=n_zones)))


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------


def _build_packed(case: ProgramCase) -> tuple:
    from kepler_tpu.parallel.packed import (make_packed_fleet_program,
                                            packed_width)

    d = case.dims
    nb, wb, z = d["n"], d["w"], d["z"]
    mb = d.get("m")
    model_mode = d.get("model_mode")
    backend = d.get("backend", "einsum")
    local = bool(d.get("local", 0))
    mesh = _mesh(d.get("devices", 8))
    fn = make_packed_fleet_program(
        mesh, n_workloads=wb, n_zones=z, model_mode=model_mode,
        backend=backend, model_bucket=mb, local_model_rows=local)
    params = _mlp_avals(z) if model_mode else _sds((), _F32)
    avals: list = [params, _sds((nb, packed_width(wb, z)), _F32)]
    if mb is not None:
        n_seg = d.get("devices", 8) if local else 1
        avals.append(_sds((n_seg * mb,), _I32))
    return fn, tuple(avals)


def _build_window_update(case: ProgramCase) -> tuple:
    from kepler_tpu.fleet.window import (MultiHostWindowEngine,
                                         PackedWindowEngine,
                                         ShardedWindowEngine)
    from kepler_tpu.parallel.packed import packed_width

    d = case.dims
    nb, wb, z, db = d["n"], d["w"], d["z"], d["db"]
    width = packed_width(wb, z)
    if d.get("multihost"):
        # virtual 2-host split over the 8 traced devices: the update is
        # the HOST-LOCAL donated scatter (identical discipline, owned
        # shards only) — traced from process 0's perspective
        mesh = _mesh(8)
        devs = list(mesh.devices.flat)
        proc_of = {dev: (0 if k < 4 else 1)
                   for k, dev in enumerate(devs)}.get
        engine: Any = MultiHostWindowEngine(mesh, process_index=0,
                                            device_process=proc_of)
    elif d.get("sharded"):
        engine = ShardedWindowEngine(_mesh(8))
    else:
        engine = PackedWindowEngine(_mesh(8))
    fn = engine._update_for(nb, width, db)[0]
    return fn, (_sds((nb, width), _F32), _sds((db, width), _F32),
                _sds((db,), _I32))


def _build_fused(case: ProgramCase) -> tuple:
    from kepler_tpu.parallel.packed import (make_fused_window_program,
                                            packed_width)

    d = case.dims
    nb, wb, z, k, db = d["n"], d["w"], d["z"], d["k"], d["db"]
    mb = d.get("m")
    model_mode = d.get("model_mode")
    mesh = _mesh(d.get("devices", 8))
    fn = make_fused_window_program(
        mesh, n_workloads=wb, n_zones=z, model_mode=model_mode,
        backend=d.get("backend", "einsum"), model_bucket=mb)
    params = _mlp_avals(z) if model_mode else _sds((), _F32)
    width = packed_width(wb, z)
    avals: list = [params, _sds((nb, width), _F32),
                   _sds((k, db, width), _F32), _sds((k, db), _I32)]
    if mb is not None:
        avals.append(_sds((k, mb), _I32))
    return fn, tuple(avals)


def _build_fleet(case: ProgramCase) -> tuple:
    from kepler_tpu.parallel.aggregator_core import (
        make_fleet_program, make_temporal_fleet_program)

    d = case.dims
    n, w, z = d["n"], d["w"], d["z"]
    mesh = _mesh(8)
    batch = (
        _sds((n, z), _F32), _sds((n, z), _BOOL), _sds((n,), _F32),
        _sds((n, w), _F32), _sds((n, w), _BOOL), _sds((n,), _F32),
        _sds((n,), _F32), _sds((n,), _I32),
    )
    if d.get("temporal"):
        t, f = d["t"], 7
        fn = make_temporal_fleet_program(mesh)
        return fn, (_temporal_avals(z),) + batch + (
            _sds((n, w, t, f), _F32), _sds((n, w, t), _BOOL))
    fn = make_fleet_program(mesh, model_mode="mlp")
    return fn, (_mlp_avals(z),) + batch


def _build_pallas_attribution(case: ProgramCase) -> tuple:
    import functools

    import jax

    from kepler_tpu.ops.pallas_attribution import attribute_fleet_pallas

    d = case.dims
    n, w, z = d["n"], d["w"], d["z"]
    fn = jax.jit(functools.partial(attribute_fleet_pallas, interpret=True))
    return fn, (
        _sds((n, z), _F32), _sds((n, z), _BOOL), _sds((n,), _F32),
        _sds((n, w), _F32), _sds((n, w), _BOOL), _sds((n,), _F32),
        _sds((n,), _F32))


def _build_ring(case: ProgramCase) -> tuple:
    from kepler_tpu.parallel.ring import make_ring_attention

    d = case.dims
    b, t, h, dh = d["b"], d["t"], d["h"], d["dh"]
    fn = make_ring_attention(_mesh(8, ("seq",)))
    q = _sds((b, t, h, dh), _F32)
    return fn, (q, q, q, _sds((b, t), _BOOL))


def _build_ulysses(case: ProgramCase) -> tuple:
    from kepler_tpu.parallel.ulysses import make_ulysses_attention

    d = case.dims
    b, t, h, dh = d["b"], d["t"], d["h"], d["dh"]
    fn = make_ulysses_attention(_mesh(4, ("seq",)))
    q = _sds((b, t, h, dh), _F32)
    return fn, (q, q, q, _sds((b, t), _BOOL))


def _build_pipeline(case: ProgramCase) -> tuple:
    import jax

    from kepler_tpu.models.deep import init_deep
    from kepler_tpu.parallel.pipeline import make_pipelined_deep

    d = case.dims
    fn = make_pipelined_deep(_mesh(8, ("stage",)),
                             n_microbatches=d.get("mb", 4))
    params = dict(init_deep(jax.random.PRNGKey(0), n_zones=d["z"],
                            n_stages=8))
    return fn, (_tree_avals(params), _sds((d["n"], 7), _F32),
                _sds((d["n"],), _BOOL))


def _build_expert(case: ProgramCase) -> tuple:
    import jax

    from kepler_tpu.models.moe import init_moe
    from kepler_tpu.parallel.expert import make_expert_parallel_moe

    d = case.dims
    fn = make_expert_parallel_moe(_mesh(8, ("expert",)))
    params = dict(init_moe(jax.random.PRNGKey(0), n_zones=d["z"],
                           n_experts=8))
    return fn, (_tree_avals(params), _sds((d["n"], 7), _F32),
                _sds((d["n"],), _I32), _sds((d["n"],), _F32))


def _build_sequence(case: ProgramCase) -> tuple:
    import jax

    from kepler_tpu.models.temporal import init_temporal
    from kepler_tpu.models.train import create_train_state, make_optimizer
    from kepler_tpu.parallel.sequence import (
        make_sequence_parallel_train_step, make_temporal_program)

    d = case.dims
    w, t, z, f = d["w"], d["t"], d["z"], 7
    mesh = _mesh(8, ("seq",))
    hist = _sds((w, t, f), _F32)
    wl_valid = _sds((w,), _BOOL)
    t_valid = _sds((w, t), _BOOL)
    params = dict(init_temporal(jax.random.PRNGKey(0), n_zones=z))
    if d.get("train"):
        step = make_sequence_parallel_train_step(mesh, make_optimizer())
        state = create_train_state(params, make_optimizer())
        return step, (_tree_avals(state), hist, wl_valid, t_valid,
                      _sds((w, z), _F32))
    fn = make_temporal_program(mesh)
    return fn, (_tree_avals(params), hist, wl_valid, t_valid)


def _build_trainer(case: ProgramCase) -> tuple:
    import jax

    from kepler_tpu.models.mlp import init_mlp
    from kepler_tpu.models.train import create_train_state, make_optimizer
    from kepler_tpu.parallel.trainer import make_distributed_train_step

    d = case.dims
    mesh = _mesh(8, ("node", "model"), shape=(4, 2))
    step = make_distributed_train_step(mesh, make_optimizer())
    state = create_train_state(
        init_mlp(jax.random.PRNGKey(0), n_zones=d["z"]), make_optimizer())
    return step, (_tree_avals(state), _sds((d["n"], d["w"], 7), _F32),
                  _sds((d["n"], d["w"]), _BOOL),
                  _sds((d["n"], d["w"], d["z"]), _F32))


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

DEVICE_PROGRAMS: tuple[ProgramSpec, ...] = (
    ProgramSpec(
        name="packed.dense_ratio",
        source="kepler_tpu/parallel/packed.py",
        description="packed-f16 ratio-only fleet program (einsum, GSPMD "
                    "node sharding)",
        build=_build_packed,
        cases=(
            ProgramCase("n16_w8_z2", dims={"n": 16, "w": 8, "z": 2}),
            ProgramCase("pad_n8_w1_z1", "minimal ladder rung: one "
                        "workload column, one zone, one row per shard",
                        dims={"n": 8, "w": 1, "z": 1}),
        ),
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="packed.dense_mlp",
        source="kepler_tpu/parallel/packed.py",
        description="packed-f16 mixed-fleet program, dense mlp estimator "
                    "(f32 compute off-TPU)",
        build=_build_packed,
        cases=(
            ProgramCase("n16_w8_z2",
                        dims={"n": 16, "w": 8, "z": 2,
                              "model_mode": "mlp"}),
        ),
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="packed.sparse_mlp",
        source="kepler_tpu/parallel/packed.py",
        description="sparse MODE_MODEL gather variant (replicated "
                    "model_rows; single-device engine path)",
        build=_build_packed,
        cases=(
            ProgramCase("n8_w8_z2_m4",
                        dims={"n": 8, "w": 8, "z": 2, "m": 4,
                              "model_mode": "mlp", "devices": 1}),
        ),
        n_devices=1,
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="packed.sparse_local_mlp",
        source="kepler_tpu/parallel/packed.py",
        description="shard_map sparse variant: shard-local model_rows "
                    "gather/scatter, zero collectives (PR 7 invariant)",
        build=_build_packed,
        cases=(
            ProgramCase("n16_w8_z2_m2",
                        dims={"n": 16, "w": 8, "z": 2, "m": 2,
                              "model_mode": "mlp", "local": 1}),
            ProgramCase("pad_n8_w1_z1_m1", "pad-heavy edge: every shard "
                        "one row, model bucket 1",
                        dims={"n": 8, "w": 1, "z": 1, "m": 1,
                              "model_mode": "mlp", "local": 1}),
        ),
        allowed_half_casts=_F16_OUT,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="packed.sparse_local_multihost",
        source="kepler_tpu/parallel/packed.py",
        description="the multi-host window's SPMD program: shard_map "
                    "sparse variant at the GLOBAL-mesh shape two "
                    "processes' device counts span (2 hosts x 4 "
                    "devices traced as one 8-shard mesh) — zero "
                    "collectives pins that the only cross-host traffic "
                    "in a window is the dispatch itself (ISSUE 15)",
        build=_build_packed,
        cases=(
            ProgramCase("hosts2_n16_w8_z2_m2",
                        "per-host bucket 2 over 2x4 devices",
                        dims={"n": 16, "w": 8, "z": 2, "m": 2,
                              "model_mode": "mlp", "local": 1}),
            ProgramCase("hosts2_pad_n8_w1_z1_m1", "minimal multi-host "
                        "rung: one row per shard across both hosts",
                        dims={"n": 8, "w": 1, "z": 1, "m": 1,
                              "model_mode": "mlp", "local": 1}),
        ),
        allowed_half_casts=_F16_OUT,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="packed.pallas_dense",
        source="kepler_tpu/parallel/packed.py",
        description="packed program with the Mosaic attribution kernel "
                    "(shard_map over node, interpret off-TPU)",
        build=_build_packed,
        cases=(
            ProgramCase("n16_w8_z2",
                        dims={"n": 16, "w": 8, "z": 2,
                              "backend": "pallas"}),
        ),
        allowed_half_casts=_F16_OUT,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="window.fused_ratio",
        source="kepler_tpu/parallel/packed.py",
        description="fused device-resident window loop, ratio-only: one "
                    "donated lax.scan applies K intervals' delta rows "
                    "and emits K packed f16 outputs per dispatch — the "
                    "per-window host↔device sync amortized K× (zero "
                    "collectives: the only cross-shard step stays the "
                    "caller's batched publish fetch)",
        build=_build_fused,
        cases=(
            ProgramCase("n16_w8_z2_k4_d8",
                        dims={"n": 16, "w": 8, "z": 2, "k": 4, "db": 8}),
            ProgramCase("pad_n8_w1_z1_k2_d1", "minimal fused rung: "
                        "steady fleet, one delta row per interval",
                        dims={"n": 8, "w": 1, "z": 1, "k": 2, "db": 1}),
        ),
        donates=(1,),
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="window.fused_sparse_mlp",
        source="kepler_tpu/parallel/packed.py",
        description="fused window loop, sparse MODE_MODEL variant: each "
                    "scan step gathers the interval's model rows "
                    "(replicated indices, single-device engine path) "
                    "through the mlp estimator — f32 accumulators, f16 "
                    "only at the packed output boundary",
        build=_build_fused,
        cases=(
            ProgramCase("n8_w8_z2_m4_k2_d4",
                        dims={"n": 8, "w": 8, "z": 2, "m": 4, "k": 2,
                              "db": 4, "model_mode": "mlp",
                              "devices": 1}),
        ),
        n_devices=1,
        donates=(1,),
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="window.fused_pallas",
        source="kepler_tpu/ops/pallas_attribution.py",
        description="fused window mega-kernel scan (single-device "
                    "pallas path): scatter + unpack + ratio attribution "
                    "as ONE kernel body per scan step, interpret mode "
                    "off-TPU",
        build=_build_fused,
        cases=(
            ProgramCase("n16_w8_z2_k2_d4",
                        dims={"n": 16, "w": 8, "z": 2, "k": 2, "db": 4,
                              "backend": "pallas", "devices": 1}),
        ),
        n_devices=1,
        donates=(1,),
        allowed_half_casts=_F16_OUT,
    ),
    ProgramSpec(
        name="window.update",
        source="kepler_tpu/fleet/window.py",
        description="donated in-place scatter-update of the resident "
                    "packed batch (delta H2D path)",
        build=_build_window_update,
        cases=(
            ProgramCase("n16_w8_z2_d8",
                        dims={"n": 16, "w": 8, "z": 2, "db": 8}),
            ProgramCase("d1", "single-row delta (the steady-fleet case)",
                        dims={"n": 16, "w": 8, "z": 2, "db": 1}),
        ),
        donates=(0,),
    ),
    ProgramSpec(
        name="window.update_sharded",
        source="kepler_tpu/fleet/window.py",
        description="shard-local donated scatter-update (per-shard ring "
                    "of the ShardedWindowEngine)",
        build=_build_window_update,
        cases=(
            ProgramCase("s2_w8_z2_d2",
                        dims={"n": 2, "w": 8, "z": 2, "db": 2,
                              "sharded": 1}),
        ),
        donates=(0,),
    ),
    ProgramSpec(
        name="window.update_multihost",
        source="kepler_tpu/fleet/window.py",
        description="host-local donated scatter-update of the "
                    "multi-host engine (a virtual 2-host topology's "
                    "process-0 view: same donation discipline, owned "
                    "shards only)",
        build=_build_window_update,
        cases=(
            ProgramCase("hosts2_s2_w8_z2_d2",
                        dims={"n": 2, "w": 8, "z": 2, "db": 2,
                              "multihost": 1}),
        ),
        donates=(0,),
    ),
    ProgramSpec(
        name="fleet.dense_mlp",
        source="kepler_tpu/parallel/aggregator_core.py",
        description="unpacked sharded fleet program with mlp estimator "
                    "(GSPMD node sharding, no explicit collectives)",
        build=_build_fleet,
        cases=(
            ProgramCase("n16_w4_z2", dims={"n": 16, "w": 4, "z": 2}),
        ),
        allowed_half_casts=_BF16_OPS,
    ),
    ProgramSpec(
        name="fleet.temporal",
        source="kepler_tpu/parallel/aggregator_core.py",
        description="temporal fleet program (dense causal attention over "
                    "per-workload history windows)",
        build=_build_fleet,
        cases=(
            ProgramCase("n8_w4_t8_z2",
                        dims={"n": 8, "w": 4, "z": 2, "t": 8,
                              "temporal": 1}),
        ),
        allowed_half_casts=_BF16_OPS,
    ),
    ProgramSpec(
        name="ops.pallas_attribution",
        source="kepler_tpu/ops/pallas_attribution.py",
        description="Mosaic outer-product attribution kernel, unsharded "
                    "(interpret mode off-TPU)",
        build=_build_pallas_attribution,
        cases=(
            ProgramCase("n8_w8_z2", dims={"n": 8, "w": 8, "z": 2}),
        ),
        n_devices=1,
    ),
    ProgramSpec(
        name="ring.attention",
        source="kepler_tpu/parallel/ring.py",
        description="ring attention: KV blocks rotate via ppermute, "
                    "online-softmax partials merge in f32",
        build=_build_ring,
        cases=(
            ProgramCase("b2_t16_h4_d8",
                        dims={"b": 2, "t": 16, "h": 4, "dh": 8}),
        ),
        allowed_collectives=frozenset({"ppermute"}),
        allowed_half_casts=_BF16_OPS,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="ulysses.attention",
        source="kepler_tpu/parallel/ulysses.py",
        description="Ulysses attention: all_to_all head/sequence "
                    "re-partition around dense attention",
        build=_build_ulysses,
        cases=(
            ProgramCase("b2_t16_h4_d8",
                        dims={"b": 2, "t": 16, "h": 4, "dh": 8}),
        ),
        n_devices=4,
        allowed_collectives=frozenset({"all_to_all", "all_gather"}),
        allowed_half_casts=_BF16_OPS,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="pipeline.deep",
        source="kepler_tpu/parallel/pipeline.py",
        description="GPipe microbatch pipeline over the deep estimator's "
                    "stage ring",
        build=_build_pipeline,
        cases=(
            ProgramCase("n16_z2_mb4", dims={"n": 16, "z": 2, "mb": 4}),
        ),
        allowed_collectives=frozenset({"ppermute", "psum"}),
        allowed_half_casts=_BF16_OPS,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="expert.moe",
        source="kepler_tpu/parallel/expert.py",
        description="expert-parallel MoE: all_to_all dispatch/combine "
                    "around batched expert MLPs",
        build=_build_expert,
        cases=(
            ProgramCase("n16_z2", dims={"n": 16, "z": 2}),
        ),
        allowed_collectives=frozenset({"all_to_all"}),
        allowed_half_casts=_BF16_OPS,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="sequence.temporal",
        source="kepler_tpu/parallel/sequence.py",
        description="sequence-parallel temporal estimator (ring attention "
                    "inside the trunk)",
        build=_build_sequence,
        cases=(
            ProgramCase("w4_t16_z2", dims={"w": 4, "t": 16, "z": 2}),
        ),
        allowed_collectives=frozenset({"ppermute"}),
        allowed_half_casts=_BF16_OPS,
        require_shard_map=True,
    ),
    ProgramSpec(
        name="sequence.train_step",
        source="kepler_tpu/parallel/sequence.py",
        description="sequence-parallel temporal TRAIN step (donated "
                    "state, ring reversed in the backward)",
        build=_build_sequence,
        cases=(
            ProgramCase("w4_t16_z2",
                        dims={"w": 4, "t": 16, "z": 2, "train": 1}),
        ),
        donates=(0,),
        allowed_collectives=frozenset({"ppermute", "psum"}),
        require_shard_map=True,
    ),
    ProgramSpec(
        name="trainer.train_step",
        source="kepler_tpu/parallel/trainer.py",
        description="DP×TP mlp train step (donated state; collectives "
                    "derived by GSPMD at partitioning, none explicit)",
        build=_build_trainer,
        cases=(
            ProgramCase("b8_w4_z2", dims={"n": 8, "w": 4, "z": 2}),
        ),
        donates=(0,),
        allowed_half_casts=_BF16_TRAIN,
    ),
)


def spec_by_name(name: str) -> ProgramSpec:
    for spec in DEVICE_PROGRAMS:
        if spec.name == name:
            return spec
    raise KeyError(name)
