"""Abstract tracing for the device tier: jaxpr + lowered-MLIR extraction.

Everything here runs WITHOUT devices or execution: programs are staged
with ``jit(...).trace(ShapeDtypeStruct...)`` (abstract shapes only) and
lowered to StableHLO text — no backend compile, no transfers, so the
whole tier completes on a CPU-only host (``JAX_PLATFORMS=cpu``) in
seconds. jax is imported lazily, pinned to the CPU platform with enough
virtual devices for the registry's meshes (the same
``xla_force_host_platform_device_count`` trick as tests/conftest.py).

What a trace yields (:class:`TraceReport`):

- the recursive **primitive histogram** of the jaxpr (sub-jaxprs of
  pjit/shard_map/scan/cond/pallas_call walked in), with version-noisy
  wrapper primitives (:data:`UNSTABLE_PRIMS`) excluded so fingerprints
  survive jax upgrades by design;
- the **collective set** (explicit communication primitives — the ones
  a ``shard_map`` schedule spells out; KTL122);
- **dtype-flow facts**: every half-precision ``convert_element_type``
  pair, every dot with a half-precision ACCUMULATOR (output dtype), and
  every reduction over half-precision operands (KTL120);
- **input/output aliasing** parsed from the lowered module's argument
  attributes: a donated argument XLA can alias carries
  ``tf.aliasing_output``; a donated-but-unaliasable one carries
  ``jax.buffer_donor`` (or nothing, plus a lower-time warning) — the
  silent perf cliff KTL121 exists to catch.
"""

from __future__ import annotations

import os
import re
import warnings
from dataclasses import dataclass, field
from typing import Any, Iterator, TYPE_CHECKING

if TYPE_CHECKING:  # registry imports stay import-light at runtime
    from kepler_tpu.analysis.device.registry import ProgramCase, ProgramSpec

#: explicit communication primitives a traced program can carry; the
#: KTL122 allowlists are spelled in these names
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "ppermute", "pmax", "pmin", "all_to_all",
    "all_gather", "all_gather_invariant", "reduce_scatter", "pgather",
})

#: wrapper/bookkeeping primitives whose counts are jax-version noise
#: (pjit nesting depth, replication-cast insertion); excluded from the
#: fingerprint histogram so the KTL123 ratchet pins PROGRAM structure,
#: not tracer internals
UNSTABLE_PRIMS = frozenset({
    "pjit", "jit", "closed_call", "core_call", "xla_call",
    "pbroadcast", "pvary", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "remat",
    "remat2", "checkpoint",
})

#: reductions whose OPERAND dtype must not be half precision
REDUCE_PRIMS = frozenset({
    "reduce_sum", "cumsum", "scatter-add", "add_any",
    "reduce_window_sum", "reduce_precision",
})

HALF_DTYPES = ("float16", "bfloat16")

_DONATION_WARNING = "donated buffers were not usable"


def ensure_cpu_devices(n_devices: int) -> Any:
    """Import jax pinned to a CPU host platform with ≥ ``n_devices``
    virtual devices and return the module.

    Must run before anything else initializes the jax backend in this
    process; if the backend is already up with too few devices (an
    embedding process that imported jax first), this raises instead of
    silently analyzing a differently-shaped mesh.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    have = len(jax.devices())
    if have < n_devices:
        raise RuntimeError(
            f"device tier needs {n_devices} virtual CPU devices, have "
            f"{have}; run in a fresh process (or set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 before jax "
            f"imports)")
    return jax


@dataclass
class TraceReport:
    """Everything the KTL120-123 checks read about one traced case."""

    spec: "ProgramSpec"
    case: "ProgramCase"
    in_avals: tuple[str, ...] = ()
    out_avals: tuple[str, ...] = ()
    prim_counts: dict[str, int] = field(default_factory=dict)
    collectives: set[str] = field(default_factory=set)
    half_casts: dict[str, int] = field(default_factory=dict)
    half_dots: list[str] = field(default_factory=list)
    half_reduces: list[str] = field(default_factory=list)
    has_shard_map: bool = False
    arg_leaves: tuple[int, ...] = ()  # flat leaves per user-level arg
    aliased_args: set[int] = field(default_factory=set)  # flat indices
    donor_args: set[int] = field(default_factory=set)
    donation_warnings: list[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.spec.name}/{self.case.name}"

    def flat_indices_of_arg(self, user_arg: int) -> set[int]:
        start = sum(self.arg_leaves[:user_arg])
        return set(range(start, start + self.arg_leaves[user_arg]))


def _sub_jaxprs(params: dict) -> Iterator[Any]:
    for value in params.values():
        items = value if isinstance(value, (list, tuple)) else (value,)
        for item in items:
            if hasattr(item, "eqns"):  # open Jaxpr
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr  # ClosedJaxpr


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every equation of ``jaxpr`` and its sub-jaxprs, depth-first."""
    for eqn in jaxpr.eqns:
        yield eqn
        yield from (e for sub in _sub_jaxprs(eqn.params)
                    for e in iter_eqns(sub))


def _aval_str(aval: Any) -> str:
    shape = ",".join(str(d) for d in getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", None)
    return f"{getattr(dtype, 'name', dtype)}[{shape}]"


def _dtype_name(var: Any) -> str:
    dtype = getattr(getattr(var, "aval", None), "dtype", None)
    return getattr(dtype, "name", str(dtype))


def parse_main_arg_attrs(text: str) -> dict[int, dict[str, bool]]:
    """Per-argument aliasing attributes of the lowered ``@main``.

    → ``{flat_arg_index: {"aliased": bool, "donor": bool}}``. The
    signature is located as the lines from ``func.func public @main(``
    up to the body-opening brace; attribute dicts may embed quoted
    strings that themselves contain braces (``mhlo.sharding``), which
    the regex tolerates.
    """
    start = text.find("func.func public @main(")
    if start < 0:
        start = text.find("func.func @main(")
    if start < 0:
        return {}
    sig_lines: list[str] = []
    for line in text[start:].splitlines():
        sig_lines.append(line)
        if line.rstrip().endswith("{"):
            break
    sig = " ".join(sig_lines)
    out: dict[int, dict[str, bool]] = {}
    for m in re.finditer(
            r'%arg(\d+):\s*tensor<[^>]*>\s*'
            r'(\{(?:[^{}"]|"[^"]*")*\})?', sig):
        idx = int(m.group(1))
        attrs = m.group(2) or ""
        out[idx] = {
            "aliased": "tf.aliasing_output" in attrs,
            "donor": "jax.buffer_donor" in attrs,
        }
    return out


def trace_case(spec: "ProgramSpec", case: "ProgramCase") -> TraceReport:
    """Stage one registry case abstractly and extract its report."""
    jax = ensure_cpu_devices(spec.n_devices)
    fn, avals = spec.build(case)
    traced = fn.trace(*avals)
    closed = traced.jaxpr
    report = TraceReport(spec=spec, case=case)
    report.in_avals = tuple(_aval_str(v.aval)
                            for v in closed.jaxpr.invars)
    report.out_avals = tuple(_aval_str(v.aval)
                             for v in closed.jaxpr.outvars)
    report.arg_leaves = tuple(len(jax.tree.leaves(a)) for a in avals)
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in UNSTABLE_PRIMS:
            report.prim_counts[name] = report.prim_counts.get(name, 0) + 1
        if name == "shard_map":
            report.has_shard_map = True
        if name in COLLECTIVE_PRIMS:
            report.collectives.add(name)
        elif name == "convert_element_type":
            src, dst = _dtype_name(eqn.invars[0]), _dtype_name(eqn.outvars[0])
            if src in HALF_DTYPES or dst in HALF_DTYPES:
                pair = f"{src}->{dst}"
                report.half_casts[pair] = report.half_casts.get(pair, 0) + 1
        elif name == "dot_general":
            out_dt = _dtype_name(eqn.outvars[0])
            if out_dt in HALF_DTYPES:
                operands = "/".join(_dtype_name(v) for v in eqn.invars)
                report.half_dots.append(f"{operands} -> {out_dt}")
        elif name in REDUCE_PRIMS:
            op_dt = _dtype_name(eqn.invars[0])
            if op_dt in HALF_DTYPES:
                report.half_reduces.append(f"{name}({op_dt})")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        text = traced.lower().as_text()
    for w in caught:
        if _DONATION_WARNING in str(w.message):
            report.donation_warnings.append(str(w.message))
    for idx, attrs in parse_main_arg_attrs(text).items():
        if attrs["aliased"]:
            report.aliased_args.add(idx)
        elif attrs["donor"]:
            report.donor_args.add(idx)
    return report


def fingerprint(report: TraceReport) -> dict:
    """Normalized structural fingerprint for the KTL123 ratchet.

    Built only from facts that are stable across jax versions by
    design: user-visible aval signatures, the histogram of REAL
    compute/data-movement primitives (:data:`UNSTABLE_PRIMS` excluded),
    the explicit collective set, half-precision cast pairs, shard_map
    presence, and which flat args alias their outputs.
    """
    return {
        "in_avals": list(report.in_avals),
        "out_avals": list(report.out_avals),
        "primitives": dict(sorted(report.prim_counts.items())),
        "collectives": sorted(report.collectives),
        "half_casts": dict(sorted(report.half_casts.items())),
        "shard_map": report.has_shard_map,
        "donated_args": sorted(report.aliased_args | report.donor_args),
    }
