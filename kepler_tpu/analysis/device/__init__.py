"""kepljax: jaxpr-tier analysis of the registered device programs.

The host-plane tiers (per-file KTL101-110/114, whole-program
KTL111-113) see source text; this tier sees what the attribution math
actually runs — the staged jaxprs and lowered modules of every jitted
device program, traced abstractly on a CPU-only host (no devices, no
execution). Four families ride each trace:

- **KTL120 dtype-flow** — half precision never accumulates; casts only
  at declared boundaries (the f16 wire quantizer, bf16 MXU operands).
- **KTL121 donation-alias** — the `donates` contract is REAL in the
  lowered module's input/output aliasing, both directions.
- **KTL122 collective-discipline** — explicit collectives match the
  entry's allowlist; shard-local programs keep their shard_map.
- **KTL123 program-ratchet** — normalized structural fingerprints
  against committed golden snapshots (``.kepljax.json``).

Run via ``python -m kepler_tpu.analysis --device-tier`` (wired into
``make lint``); regenerate snapshots with ``make kepljax-snapshots``.
Importing this package registers the rules but touches no jax.
"""

from kepler_tpu.analysis.device.checks import (  # noqa: F401
    DEVICE_RULE_IDS,
    SNAPSHOT_NAME,
    analyze_device_programs,
    clear_trace_cache,
    load_snapshots,
    write_snapshots,
)
from kepler_tpu.analysis.device.registry import (  # noqa: F401
    DEVICE_PROGRAMS,
    ProgramCase,
    ProgramSpec,
    spec_by_name,
)

__all__ = [
    "DEVICE_PROGRAMS",
    "DEVICE_RULE_IDS",
    "ProgramCase",
    "ProgramSpec",
    "SNAPSHOT_NAME",
    "analyze_device_programs",
    "clear_trace_cache",
    "load_snapshots",
    "spec_by_name",
    "write_snapshots",
]
