"""kepljax check families KTL120-123 + the device-tier runner.

Each rule consumes the :class:`~kepler_tpu.analysis.device.trace
.TraceReport` of one registry case and yields engine
:class:`~kepler_tpu.analysis.engine.Diagnostic`\\ s anchored at the
program's home module, so device-tier findings ride the same severity,
baseline-ratchet and text/json/SARIF machinery as every other keplint
rule. Traces are cached per (spec, case) for the life of the process —
the dominant cost is staging, paid once however many families run.

The KTL123 golden snapshots live in ``.kepljax.json`` at the repo root
(``make kepljax-snapshots`` / ``--update-snapshots`` regenerates); the
committed file is the ratchet — structural drift in any registered
program fails lint with a field-level diff instead of surfacing as a
bench regression rounds later.
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from kepler_tpu.analysis.engine import (
    Diagnostic,
    DeviceRule,
    SEVERITY_ERROR,
    register,
)
from kepler_tpu.analysis.device.registry import (
    DEVICE_PROGRAMS,
    ProgramSpec,
)
from kepler_tpu.analysis.device.trace import TraceReport, fingerprint

SNAPSHOT_NAME = ".kepljax.json"
SNAPSHOT_VERSION = 1

DEVICE_RULE_IDS = ("KTL120", "KTL121", "KTL122", "KTL123")

# process-lifetime trace cache: (spec.name, case.name) → TraceReport
_TRACE_CACHE: dict[tuple[str, str], TraceReport] = {}


def clear_trace_cache() -> None:
    _TRACE_CACHE.clear()


def _diag(rule: DeviceRule, report: TraceReport, message: str) -> Diagnostic:
    return Diagnostic(
        path=report.spec.source, line=1, col=1, rule_id=rule.id,
        severity=rule.severity,
        message=f"[{report.key}] {message}")


@register
class DtypeFlowRule(DeviceRule):
    id = "KTL120"
    name = "dtype-flow"
    summary = ("half precision (f16/bf16) never accumulates: no half "
               "dot accumulators or reduction operands, and half casts "
               "only at the boundaries the registry entry declares")
    rationale = (
        "The packed fleet wire format quantizes watts to f16 at exactly "
        "one declared boundary (~0.05% error, inside the 0.5%-of-RAPL "
        "budget) and estimator trunks feed bf16 OPERANDS to the MXU with "
        "f32 accumulators (`models.nn.acc_matmul`). That budget only "
        "holds while those are the ONLY half-precision touchpoints: a "
        "bare `x16 @ w16` rounds every partial sum to ~3 decimal digits, "
        "and a stray `.astype(f16)` mid-program quantizes an "
        "intermediate, both silently. This check walks every registered "
        "program's jaxpr dataflow: any dot_general with a half-precision "
        "accumulator (output dtype), any reduction over half operands, "
        "and any half `convert_element_type` pair outside the entry's "
        "`allowed_half_casts` declaration is a finding.")

    def check_trace(self, report: TraceReport) -> Iterable[Diagnostic]:
        for desc in report.half_dots:
            yield _diag(self, report,
                        f"dot_general accumulates in half precision "
                        f"({desc}); pin the accumulator f32 "
                        f"(models.nn.acc_matmul / "
                        f"preferred_element_type)")
        for desc in report.half_reduces:
            yield _diag(self, report,
                        f"reduction over half-precision operands "
                        f"({desc}); accumulate in f32")
        allowed = report.spec.allowed_half_casts
        for pair, count in sorted(report.half_casts.items()):
            if pair not in allowed:
                yield _diag(
                    self, report,
                    f"undeclared half-precision cast {pair} (×{count}); "
                    f"declared boundaries: "
                    f"{sorted(allowed) or 'none'}")


@register
class DonationAliasRule(DeviceRule):
    id = "KTL121"
    name = "donation-alias"
    summary = ("the lowered module's input/output aliasing matches the "
               "entry's `donates` contract — every declared-donated leaf "
               "really aliases, nothing else does")
    rationale = (
        "`donate_argnums` is a request, not a guarantee: XLA only "
        "aliases a donated buffer into an output of matching "
        "shape/dtype. A declared-donated arg that could NOT alias is a "
        "silent perf cliff (the resident fleet batch gets copied every "
        "window instead of updated in place) and a latent hazard — "
        "KTL110's rebind discipline assumes the handle really dies. The "
        "reverse is worse: an UNdeclared donation consumes a buffer the "
        "engine still holds. The check parses the lowered module's "
        "argument attributes — `tf.aliasing_output` (alias placed at "
        "lowering) and `jax.buffer_donor` (donation deferred to the "
        "compiler) both realize the contract; an arg with NEITHER was "
        "dropped, which jax also announces with a 'donated buffers "
        "were not usable' warning — and compares the flattened-leaf "
        "donation map against the registry contract, both directions.")

    def check_trace(self, report: TraceReport) -> Iterable[Diagnostic]:
        expected: set[int] = set()
        for user_arg in report.spec.donates:
            expected |= report.flat_indices_of_arg(user_arg)
        realized = report.aliased_args | report.donor_args
        dropped = sorted(expected - realized)
        if dropped:
            yield _diag(
                self, report,
                f"declared donation (user args "
                f"{list(report.spec.donates)}) is not realized: flat "
                f"args {dropped} carry neither tf.aliasing_output nor "
                f"jax.buffer_donor — every call pays a full copy")
        unexpected = sorted(realized - expected)
        if unexpected:
            yield _diag(
                self, report,
                f"undeclared donation/aliasing on flat args "
                f"{unexpected}: the caller's buffer dies without a "
                f"`donates` contract saying so")
        for warning in report.donation_warnings:
            yield _diag(self, report,
                        f"lowering warned: {warning[:160]}")


@register
class CollectiveDisciplineRule(DeviceRule):
    id = "KTL122"
    name = "collective-discipline"
    summary = ("explicit collectives stay inside the entry's allowlist, "
               "and shard-local programs keep their shard_map structure")
    rationale = (
        "The fleet window's scaling contract (PR 7) is that the only "
        "cross-shard step is the caller's result fetch — the packed "
        "program's sparse gather stays shard-local under `shard_map`, "
        "and the attention/pipeline/MoE programs each have a KNOWN "
        "collective schedule (ppermute ring, all_to_all pair, …). This "
        "check enumerates the traced jaxpr's communication primitives "
        "against the entry's allowlist, and — because GSPMD inserts "
        "collectives at partitioning time where the jaxpr tier cannot "
        "see them — additionally requires `require_shard_map` entries "
        "to actually contain a shard_map: a regression to a "
        "replicated-index gather (plain GSPMD jit) would be satisfied "
        "with an all-gather of the whole resident batch at compile "
        "time, and losing the shard_map is exactly how that reads at "
        "the jaxpr tier.")

    def check_trace(self, report: TraceReport) -> Iterable[Diagnostic]:
        rogue = report.collectives - report.spec.allowed_collectives
        if rogue:
            yield _diag(
                self, report,
                f"collectives {sorted(rogue)} outside the allowlist "
                f"{sorted(report.spec.allowed_collectives) or '(none)'}")
        if report.spec.require_shard_map and not report.has_shard_map:
            yield _diag(
                self, report,
                "program lost its shard_map structure: GSPMD would now "
                "satisfy cross-shard data movement (e.g. a "
                "replicated-index gather → all-gather of the resident "
                "batch) at partitioning time, invisible to this tier")


@register
class ProgramRatchetRule(DeviceRule):
    id = "KTL123"
    name = "program-ratchet"
    summary = ("each registered program's normalized jaxpr fingerprint "
               "matches its committed golden snapshot (.kepljax.json); "
               "drift fails with a diff, --update-snapshots regenerates")
    rationale = (
        "Program structure predicts cost (PAPERS.md: portable "
        "prediction of kernel time/power from program structure) — so "
        "pin the structure. The fingerprint is deliberately normalized "
        "(user-visible aval signatures, compute/data-movement primitive "
        "histogram with version-noisy wrapper primitives excluded, "
        "collective set, half-cast pairs, shard_map presence, aliasing "
        "map) so it is stable across jax versions by design while still "
        "catching an accidental extra transpose, a dtype widen, a lost "
        "donation or a new collective in review — instead of three "
        "bench rounds later as an unexplained regression. "
        "`make kepljax-snapshots` regenerates after INTENDED changes; "
        "the diff in the commit is the review surface.")

    def check_snapshot(self, report: TraceReport,
                       snapshot: dict | None) -> Iterable[Diagnostic]:
        fp = fingerprint(report)
        if snapshot is None:
            yield _diag(
                self, report,
                "no golden snapshot for this program/case; run "
                "`make kepljax-snapshots` and commit .kepljax.json")
            return
        for field in sorted(set(fp) | set(snapshot)):
            got, want = fp.get(field), snapshot.get(field)
            if got != want:
                yield _diag(
                    self, report,
                    f"fingerprint drift in `{field}`: snapshot "
                    f"{_compact(want)} != traced {_compact(got)} — "
                    f"intended? regenerate with `make kepljax-snapshots` "
                    f"and review the diff")


def _compact(value: object, limit: int = 160) -> str:
    text = json.dumps(value, sort_keys=True, default=str)
    return text if len(text) <= limit else text[:limit] + "…"


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def snapshot_path(root: str) -> str:
    return os.path.join(root, SNAPSHOT_NAME)


def load_snapshots(root: str) -> dict[str, dict] | None:
    path = snapshot_path(root)
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return None
    if not isinstance(data, dict) or data.get("version") != SNAPSHOT_VERSION:
        raise ValueError(f"unsupported snapshot file {path!r}")
    programs = data.get("programs", {})
    if not isinstance(programs, dict):
        raise ValueError(f"malformed snapshot file {path!r}")
    return programs


def _trace_all(specs: tuple[ProgramSpec, ...]) -> tuple[
        list[TraceReport], list[Diagnostic]]:
    from kepler_tpu.analysis.device.trace import trace_case

    reports: list[TraceReport] = []
    errors: list[Diagnostic] = []
    for spec in specs:
        for case in spec.cases:
            key = (spec.name, case.name)
            report = _TRACE_CACHE.get(key)
            if report is None:
                try:
                    report = trace_case(spec, case)
                except Exception as err:  # tracing is hostile territory
                    errors.append(Diagnostic(
                        path=spec.source, line=1, col=1,
                        rule_id="KTL000", severity=SEVERITY_ERROR,
                        message=f"[{spec.name}/{case.name}] device "
                                f"program failed to build/trace: "
                                f"{type(err).__name__}: "
                                f"{str(err)[:200]}"))
                    continue
                _TRACE_CACHE[key] = report
            reports.append(report)
    return reports, errors


def write_snapshots(root: str,
                    specs: tuple[ProgramSpec, ...] = DEVICE_PROGRAMS,
                    ) -> tuple[int, list[Diagnostic]]:
    """Regenerate ``.kepljax.json`` from live traces → (count, errors)."""
    reports, errors = _trace_all(specs)
    payload = {
        "version": SNAPSHOT_VERSION,
        "comment": "kepljax golden program fingerprints (KTL123): "
                   "normalized jaxpr structure per registry entry/case. "
                   "Regenerate with `make kepljax-snapshots` after an "
                   "INTENDED program change; review the diff. Never "
                   "edit by hand.",
        "programs": {r.key: fingerprint(r)
                     for r in sorted(reports, key=lambda r: r.key)},
    }
    with open(snapshot_path(root), "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return len(reports), errors


def analyze_device_programs(
        root: str,
        only: set[str] | None = None,
        specs: tuple[ProgramSpec, ...] = DEVICE_PROGRAMS,
) -> list[Diagnostic]:
    """Trace every registry case and run the device-tier families.

    ``only`` restricts to a subset of rule ids (the CLI's ``--only``);
    trace/build failures always report (as KTL000).
    """
    from kepler_tpu.analysis.engine import REGISTRY

    def want(rule_id: str) -> bool:
        return only is None or rule_id in only

    reports, diags = _trace_all(specs)
    trace_rules = [REGISTRY[rid] for rid in ("KTL120", "KTL121", "KTL122")
                   if want(rid)]
    for report in reports:
        for rule in trace_rules:
            diags.extend(rule.check_trace(report))
    if want("KTL123"):
        ratchet = REGISTRY["KTL123"]
        try:
            snapshots = load_snapshots(root)
        except ValueError as err:
            diags.append(Diagnostic(
                path=SNAPSHOT_NAME, line=1, col=1, rule_id="KTL123",
                severity=SEVERITY_ERROR, message=str(err)))
            snapshots = {}
        if snapshots is None:
            diags.append(Diagnostic(
                path=SNAPSHOT_NAME, line=1, col=1, rule_id="KTL123",
                severity=SEVERITY_ERROR,
                message=f"missing {SNAPSHOT_NAME}; generate the golden "
                        f"program snapshots with `make kepljax-snapshots` "
                        f"and commit them"))
        else:
            for report in reports:
                diags.extend(ratchet.check_snapshot(
                    report, snapshots.get(report.key)))
            live = {r.key for r in reports}
            wanted_specs = {s.name for s in specs}
            registered = {s.name for s in DEVICE_PROGRAMS}
            for key in sorted(snapshots):
                spec_name = key.rsplit("/", 1)[0]
                # a snapshot key is stale when its case disappeared from
                # an analyzed spec, OR its whole spec left the registry
                # (a test analyzing a specs SUBSET must not false-flag
                # the other still-registered programs' entries)
                if key not in live and (spec_name in wanted_specs
                                        or spec_name not in registered):
                    diags.append(Diagnostic(
                        path=SNAPSHOT_NAME, line=1, col=1,
                        rule_id="KTL123", severity=SEVERITY_ERROR,
                        message=f"stale snapshot entry {key!r} (program/"
                                f"case no longer registered); regenerate "
                                f"with `make kepljax-snapshots`"))
    return sorted(diags)
