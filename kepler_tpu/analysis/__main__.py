"""keplint CLI: ``python -m kepler_tpu.analysis [paths]``.

Exit codes: 0 clean (baselined violations and stale-baseline notices do
not fail), 1 new error-severity findings, 2 usage errors. The default
baseline is ``.keplint.json`` at the repo root (the directory holding
pyproject.toml, walked up from the first path).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Sequence

from kepler_tpu.analysis.engine import (
    Baseline,
    LintResult,
    all_rules,
    find_repo_root,
    lint_paths,
)

BASELINE_NAME = ".keplint.json"


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kepler_tpu.analysis",
        description="keplint: AST invariant checks for the attribution "
                    "stack (see docs/developer/static-analysis.md)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to lint "
                             "(default: kepler_tpu under the repo root)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/"
                             f"{BASELINE_NAME} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze current violations into the "
                             "baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<20} [{rule.severity}] "
                  f"{rule.summary}")
        return 0

    root = find_repo_root(args.paths[0] if args.paths else os.getcwd())
    paths = args.paths or [os.path.join(root, "kepler_tpu")]
    for path in paths:
        if not os.path.exists(path):
            print(f"keplint: no such path: {path}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline: Baseline | None = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = Baseline.load(baseline_path)
            except (OSError, ValueError) as err:
                print(f"keplint: bad baseline {baseline_path}: {err}",
                      file=sys.stderr)
                return 2

    if args.write_baseline:
        full = lint_paths(paths, root=root)
        Baseline.from_diagnostics(full.diagnostics).save(baseline_path)
        print(f"keplint: wrote {baseline_path} "
              f"({len(full.diagnostics)} frozen violation(s))")
        return 0

    result: LintResult = lint_paths(paths, root=root, baseline=baseline)
    return report(result)


def report(result: LintResult) -> int:
    for diag in result.diagnostics:
        print(diag.render())
    if result.stale_entries:
        print("keplint: stale baseline entries (violations fixed — "
              "regenerate with --write-baseline to ratchet down):",
              file=sys.stderr)
        for key in result.stale_entries:
            print(f"  {key}", file=sys.stderr)
    if result.diagnostics:
        n = len(result.diagnostics)
        suffix = (f" ({result.baselined} more baselined)"
                  if result.baselined else "")
        print(f"keplint: {n} new violation(s){suffix}", file=sys.stderr)
        return 1 if result.failed else 0
    extra = (f" ({result.baselined} baselined violation(s) tolerated)"
             if result.baselined else "")
    print(f"keplint: clean{extra}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
