"""keplint CLI: ``python -m kepler_tpu.analysis [paths]``.

Exit codes: 0 clean (baselined violations and stale-baseline notices do
not fail), 1 new error-severity findings, 2 usage errors. The default
baseline is ``.keplint.json`` at the repo root (the directory holding
pyproject.toml, walked up from the first path).

``--format`` selects the report shape: ``text`` (default, one line per
finding), ``json`` (machine-readable summary), or ``sarif`` (SARIF
2.1.0 minimal profile, consumable as CI annotations — see ``make
keplint-sarif``). ``--per-file`` restricts the whole-program rules
(KTL111-113) to single-file contexts: cross-module findings disappear,
which is useful for bisecting whether a finding needs the call graph.

``--device-tier`` additionally traces the registered device programs
(``kepler_tpu/analysis/device``) and runs the KTL120-123 families over
their jaxprs — seconds of staging cost, so it is opt-in (``make lint``
passes it). ``--update-snapshots`` regenerates the KTL123 golden
fingerprints (``.kepljax.json``) and exits. ``--only=KTL110,KTL120``
restricts a run to the named rules — a single-rule iteration loop no
longer pays every other family's cost (the device tier's trace cost
made that painful).

``--protocol-tier`` exhaustively explores the registered protocol
models (``kepler_tpu/analysis/protocol``, the kepmc checker) and runs
the KTL130-132 families over their reachable state spaces — a couple
of seconds of BFS, opt-in like the device tier (``make lint`` passes
it; ``make protocheck`` runs it alone). Naming a KTL13x id in
``--only`` implies the tier. KTL133 (the protocol-transition marker
fence) is an ordinary per-file rule and always runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

from kepler_tpu.analysis.engine import (
    Baseline,
    Diagnostic,
    LintResult,
    SEVERITY_ERROR,
    all_rules,
    find_repo_root,
    lint_paths,
)

BASELINE_NAME = ".keplint.json"
# default lint surface: the package plus the tooling/bench trees that
# the widened-scope rules (KTL101/KTL105) police
DEFAULT_TREES = ("kepler_tpu", "hack", "benchmarks")

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kepler_tpu.analysis",
        description="keplint: AST invariant checks for the attribution "
                    "stack (see docs/developer/static-analysis.md)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to lint (default: "
                             "kepler_tpu, hack, benchmarks under the "
                             "repo root)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/"
                             f"{BASELINE_NAME} when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="freeze current violations into the "
                             "baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="report format (default: text)")
    parser.add_argument("--per-file", action="store_true",
                        help="restrict whole-program rules (KTL111-113) "
                             "to single-file contexts — no cross-module "
                             "call graph")
    parser.add_argument("--device-tier", action="store_true",
                        help="also trace the registered device programs "
                             "and run the KTL120-123 jaxpr-tier checks")
    parser.add_argument("--update-snapshots", action="store_true",
                        help="regenerate the KTL123 golden program "
                             "fingerprints (.kepljax.json) and exit")
    parser.add_argument("--protocol-tier", action="store_true",
                        help="also explore the registered protocol "
                             "models (kepmc) and run the KTL130-132 "
                             "state-space checks")
    parser.add_argument("--only", default=None, metavar="KTLxxx[,KTLxxx]",
                        help="run only the named rules; naming a KTL12x "
                             "id implies --device-tier, a KTL130-132 id "
                             "implies --protocol-tier")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name:<20} [{rule.severity}] "
                  f"{rule.summary}")
        return 0

    only_ids: set[str] | None = None
    if args.only:
        only_ids = {p.strip() for p in args.only.split(",") if p.strip()}
        known = {r.id for r in all_rules()}
        unknown = only_ids - known
        if unknown:
            print(f"keplint: unknown rule id(s) in --only: "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    root = find_repo_root(args.paths[0] if args.paths else os.getcwd())
    if args.paths:
        paths = args.paths
    else:
        paths = [os.path.join(root, tree) for tree in DEFAULT_TREES
                 if os.path.isdir(os.path.join(root, tree))]
    for path in paths:
        if not os.path.exists(path):
            print(f"keplint: no such path: {path}", file=sys.stderr)
            return 2

    if args.update_snapshots:
        from kepler_tpu.analysis.device import (SNAPSHOT_NAME,
                                                write_snapshots)

        count, errors = write_snapshots(root)
        for diag in errors:
            print(diag.render(), file=sys.stderr)
        print(f"keplint: wrote {os.path.join(root, SNAPSHOT_NAME)} "
              f"({count} program fingerprint(s))")
        return 1 if errors else 0

    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    baseline: Baseline | None = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            try:
                baseline = Baseline.load(baseline_path)
            except (OSError, ValueError) as err:
                print(f"keplint: bad baseline {baseline_path}: {err}",
                      file=sys.stderr)
                return 2

    rules = all_rules()
    if only_ids is not None:
        rules = [r for r in rules if r.id in only_ids]
    # --only with a device-rule id implies the device tier: silently
    # skipping the only rules the user named (and printing "clean")
    # would be a false all-clear
    device_ids = {"KTL120", "KTL121", "KTL122", "KTL123"}
    protocol_ids = {"KTL130", "KTL131", "KTL132"}
    if only_ids is None:
        device_wanted = args.device_tier
        protocol_wanted = args.protocol_tier
    else:
        device_wanted = bool(only_ids & device_ids)
        protocol_wanted = bool(only_ids & protocol_ids)

    def run_lint() -> LintResult:
        result = lint_paths(paths, root=root, rules=rules,
                            per_file=args.per_file)
        if device_wanted:
            from kepler_tpu.analysis.device import analyze_device_programs

            result.diagnostics.extend(
                analyze_device_programs(root, only=only_ids))
            result.diagnostics.sort()
        if protocol_wanted:
            from kepler_tpu.analysis.protocol import (
                analyze_protocol_specs)

            result.diagnostics.extend(
                analyze_protocol_specs(root, only=only_ids))
            result.diagnostics.sort()
        return result

    if args.write_baseline:
        full = run_lint()
        Baseline.from_diagnostics(full.diagnostics).save(baseline_path)
        print(f"keplint: wrote {baseline_path} "
              f"({len(full.diagnostics)} frozen violation(s))")
        return 0

    result = run_lint()
    if baseline is not None:
        result = baseline.apply(result.diagnostics)
    if args.format == "sarif":
        print(json.dumps(render_sarif(result), indent=2))
        return 1 if result.failed else 0
    if args.format == "json":
        print(json.dumps(render_json(result), indent=2))
        return 1 if result.failed else 0
    return report(result)


def report(result: LintResult) -> int:
    for diag in result.diagnostics:
        print(diag.render())
    if result.stale_entries:
        print("keplint: stale baseline entries (violations fixed — "
              "regenerate with --write-baseline to ratchet down):",
              file=sys.stderr)
        for key in result.stale_entries:
            print(f"  {key}", file=sys.stderr)
    if result.diagnostics:
        n = len(result.diagnostics)
        suffix = (f" ({result.baselined} more baselined)"
                  if result.baselined else "")
        print(f"keplint: {n} new violation(s){suffix}", file=sys.stderr)
        return 1 if result.failed else 0
    extra = (f" ({result.baselined} baselined violation(s) tolerated)"
             if result.baselined else "")
    print(f"keplint: clean{extra}")
    return 0


def render_json(result: LintResult) -> dict:
    return {
        "violations": [
            {"path": d.path, "line": d.line, "col": d.col,
             "rule": d.rule_id, "severity": d.severity,
             "message": d.message}
            for d in result.diagnostics],
        "baselined": result.baselined,
        "stale_baseline_entries": list(result.stale_entries),
        "failed": result.failed,
    }


def render_sarif(result: LintResult) -> dict:
    """SARIF 2.1.0 minimal profile: one run, the rule catalog as
    reportingDescriptors, one result per diagnostic with a physical
    location (CI annotation shape)."""
    rules = all_rules()
    rule_index = {r.id: i for i, r in enumerate(rules)}
    results = []
    for d in result.diagnostics:
        results.append({
            "ruleId": d.rule_id,
            "ruleIndex": rule_index.get(d.rule_id, -1),
            "level": ("error" if d.severity == SEVERITY_ERROR
                      else "warning"),
            "message": {"text": d.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": d.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": d.line,
                        "startColumn": d.col,
                    },
                },
            }],
        })
    driver = {
        "name": "keplint",
        "informationUri": ("https://github.com/sustainable-computing-io/"
                           "kepler"),
        "rules": [{
            "id": r.id,
            "name": r.name,
            "shortDescription": {"text": r.summary},
            "fullDescription": {"text": r.rationale},
            "defaultConfiguration": {
                "level": ("error" if r.severity == SEVERITY_ERROR
                          else "warning"),
            },
        } for r in rules],
    }
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": driver},
            "results": results,
        }],
    }


if __name__ == "__main__":
    sys.exit(main())
