"""Health/readiness registry behind ``/healthz`` and ``/readyz``.

Kubernetes-style probe plane for both roles (node exporter and cluster
aggregator): components register cheap probe callables and the API server
exposes two endpoints —

- ``GET /healthz``: **degradation.** 200 when every registered health
  probe reports ``ok``; 503 with per-component JSON otherwise. Probes
  surface the resilience machinery's state: the fleet agent's circuit
  breaker, the monitor watchdog's stall detection, the aggregator's
  degraded-node quarantine accounting. NOTE: degradation includes
  EXTERNAL dependencies (an open circuit breaker means the aggregator is
  unreachable, not that this process is broken) — wire alerting and
  traffic gating to it, NOT a kubelet livenessProbe, which would
  restart-loop healthy exporters during an aggregator outage.
- ``GET /readyz``: **readiness.** 200 once every registered readiness
  probe reports ``ok`` (e.g. the monitor published its first snapshot,
  the aggregator finished init). With no readiness probes registered the
  endpoint reports ready — a bare APIServer that serves requests is ready.

Probe contract: a zero-argument callable returning a mapping with at
least ``{"ok": bool}``; extra keys are passed through as detail. A probe
that raises is reported as failed (the health plane itself must never
500 because a component is broken — that is exactly when it is needed).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Mapping

log = logging.getLogger("kepler.server.health")

Probe = Callable[[], Mapping]


class HealthRegistry:
    """Thread-safe probe registry; components register during init()."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._health: dict[str, Probe] = {}
        self._ready: dict[str, Probe] = {}

    def register_probe(self, name: str, probe: Probe) -> None:
        """Add a liveness/degradation probe (re-registration replaces)."""
        with self._lock:
            self._health[name] = probe

    def register_readiness(self, name: str, probe: Probe) -> None:
        with self._lock:
            self._ready[name] = probe

    def unregister(self, name: str) -> None:
        with self._lock:
            self._health.pop(name, None)
            self._ready.pop(name, None)

    @staticmethod
    def _run_probes(probes: dict[str, Probe]) -> tuple[bool, dict]:
        ok = True
        components: dict[str, dict] = {}
        for name, probe in probes.items():
            try:
                result = dict(probe())
                result["ok"] = bool(result.get("ok", False))
            except Exception as err:  # a broken probe is a failing probe
                log.exception("health probe %s raised", name)
                result = {"ok": False, "error": f"{type(err).__name__}: {err}"}
            ok = ok and result["ok"]
            components[name] = result
        return ok, components

    def check_health(self) -> tuple[bool, dict]:
        with self._lock:
            probes = dict(self._health)
        return self._run_probes(probes)

    def check_ready(self) -> tuple[bool, dict]:
        with self._lock:
            probes = dict(self._ready)
        return self._run_probes(probes)

    # -- endpoint handlers (APIServer handler signature) -------------------

    def handle_healthz(self, _request) -> tuple[int, dict[str, str], bytes]:
        ok, components = self.check_health()
        body = json.dumps({"status": "ok" if ok else "degraded",
                           "components": components},
                          sort_keys=True).encode() + b"\n"
        return (200 if ok else 503,
                {"Content-Type": "application/json"}, body)

    def handle_readyz(self, _request) -> tuple[int, dict[str, str], bytes]:
        ok, components = self.check_ready()
        body = json.dumps({"status": "ok" if ok else "unready",
                           "components": components},
                          sort_keys=True).encode() + b"\n"
        return (200 if ok else 503,
                {"Content-Type": "application/json"}, body)
