"""API server.

Reference parity: ``internal/server/server.go`` — an HTTP mux where services
``register(endpoint, name, description, handler)`` themselves; an HTML
landing page listing registered endpoints (:109-131); graceful shutdown with
a 5 s bound (:158-165). TLS/basic-auth web-config (exporter-toolkit) is
supported via optional cert/key paths.

Handlers return ``(status, headers, body_bytes)`` — kept framework-free so
tests can call them directly.
"""

from __future__ import annotations

import html
import logging
import ssl
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.server")

Handler = Callable[[BaseHTTPRequestHandler], tuple[int, dict[str, str], bytes]]


@dataclass
class Endpoint:
    path: str
    name: str
    description: str
    handler: Handler


class APIServer:
    def __init__(
        self,
        listen_addresses: list[str] | None = None,
        tls_cert: str = "",
        tls_key: str = "",
    ) -> None:
        self._addresses = listen_addresses or [":28282"]
        self._tls_cert = tls_cert
        self._tls_key = tls_key
        self._endpoints: dict[str, Endpoint] = {}
        self._servers: list[ThreadingHTTPServer] = []
        self._threads: list[threading.Thread] = []

    def name(self) -> str:
        return "api-server"

    def register(self, path: str, name: str, description: str,
                 handler: Handler) -> None:
        """Add an endpoint to the catalog (reference Register :167)."""
        self._endpoints[path] = Endpoint(path, name, description, handler)

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        outer = self

        class RequestHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route into our logger
                log.debug("http: " + fmt, *args)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                path = self.path.split("?", 1)[0]
                endpoint = outer._match(path)
                if endpoint is None:
                    self._respond(404, {"Content-Type": "text/plain"},
                                  b"not found\n")
                    return
                try:
                    status, headers, body = endpoint.handler(self)
                except Exception:
                    log.exception("handler %s failed", path)
                    self._respond(500, {"Content-Type": "text/plain"},
                                  b"internal error\n")
                    return
                self._respond(status, headers, body)

            def _respond(self, status, headers, body):
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._handler_cls = RequestHandler
        self.register("/", "Home", "Landing page", self._landing_page)
        for addr in self._addresses:
            host, _, port = addr.rpartition(":")
            server = ThreadingHTTPServer(
                (host or "0.0.0.0", int(port)), RequestHandler)
            if self._tls_cert and self._tls_key:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self._tls_cert, self._tls_key)
                server.socket = ctx.wrap_socket(server.socket,
                                                server_side=True)
            self._servers.append(server)
        log.info("api server listening on %s",
                 [s.server_address for s in self._servers])

    def run(self, ctx: CancelContext) -> None:
        for server in self._servers:
            t = threading.Thread(target=server.serve_forever,
                                 name="http-serve", daemon=True)
            t.start()
            self._threads.append(t)
        ctx.wait(None)

    def shutdown(self) -> None:
        """Graceful shutdown, 5 s bound (reference :158-165)."""
        for server in self._servers:
            server.shutdown()
            server.server_close()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- internals ---------------------------------------------------------

    def _match(self, path: str) -> Endpoint | None:
        if path in self._endpoints:
            return self._endpoints[path]
        # prefix match for subtree handlers (e.g. /debug/...)
        best = None
        for ep_path, ep in self._endpoints.items():
            if ep_path != "/" and path.startswith(ep_path.rstrip("/") + "/"):
                if best is None or len(ep_path) > len(best.path):
                    best = ep
        return best

    def _landing_page(self, _request) -> tuple[int, dict[str, str], bytes]:
        rows = "".join(
            f'<li><a href="{html.escape(e.path)}">{html.escape(e.name)}</a>'
            f" — {html.escape(e.description)}</li>"
            for e in sorted(self._endpoints.values(), key=lambda e: e.path)
            if e.path != "/"
        )
        body = (
            "<html><head><title>kepler-tpu</title></head><body>"
            "<h1>kepler-tpu</h1><ul>" + rows + "</ul></body></html>"
        ).encode()
        return 200, {"Content-Type": "text/html; charset=utf-8"}, body

    @property
    def addresses(self) -> list[tuple[str, int]]:
        """Actual bound (host, port) pairs — ports resolve 0 → ephemeral."""
        return [s.server_address for s in self._servers]
