"""API server.

Reference parity: ``internal/server/server.go`` — an HTTP mux where services
``register(endpoint, name, description, handler)`` themselves; an HTML
landing page listing registered endpoints (:109-131); graceful shutdown with
a 5 s bound (:158-165). TLS and basic auth mirror the reference's
exporter-toolkit web config (``server.go:136-156``): cert/key paths plus an
authenticator from ``kepler_tpu.server.webconfig``.

Handlers return ``(status, headers, body_bytes)`` — kept framework-free so
tests can call them directly.
"""

from __future__ import annotations

import html
import logging
import ssl
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from kepler_tpu.server.health import HealthRegistry
from kepler_tpu.service.lifecycle import CancelContext

log = logging.getLogger("kepler.server")

Handler = Callable[[BaseHTTPRequestHandler], tuple[int, dict[str, str], bytes]]


# keplint: sanitizes — request paths/headers go into log lines; control
# bytes (an encoded %00, a smuggled ESC sequence) would forge log records
# or corrupt terminals, so log fields are filtered to printable ASCII
def printable(value: str, cap: int = 256) -> str:
    return "".join(c for c in str(value)[:cap] if " " <= c <= "\x7e")


@dataclass
class Endpoint:
    path: str
    name: str
    description: str
    handler: Handler
    # largest POST body accepted; bigger requests get 413 without the body
    # ever being buffered (and the connection closes, since the unread
    # bytes would desync keep-alive)
    max_body: int = 1 << 20


_OVERFLOW_BODY = b"connection cap reached\n"
_OVERFLOW_RESPONSE = (b"HTTP/1.1 503 Service Unavailable\r\n"
                      b"Content-Type: text/plain\r\n"
                      b"Content-Length: "
                      + str(len(_OVERFLOW_BODY)).encode() + b"\r\n"
                      b"Retry-After: 1\r\n"
                      b"Connection: close\r\n"
                      b"\r\n"
                      + _OVERFLOW_BODY)


class _CappedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a bound on concurrent connections.

    The stock mixin spawns one handler thread per accepted connection,
    unboundedly — a connection storm (thundering herd after a replica
    kill, a misbehaving client) grows threads and their stacks without
    limit. With ``max_connections`` set, an accept over the cap is
    answered ``503 + Connection: close`` IMMEDIATELY on the accepting
    thread and closed — no handler thread is ever spawned for it — and
    the client retries against a replica with headroom (or the same one,
    later). 0 keeps the unbounded stock behavior."""

    daemon_threads = True

    def __init__(self, server_address, handler_cls,
                 max_connections: int = 0) -> None:
        super().__init__(server_address, handler_cls)
        self._conn_sema = (threading.BoundedSemaphore(max_connections)
                           if max_connections > 0 else None)
        self.max_connections = max_connections
        self._conn_lock = threading.Lock()
        self.active_connections = 0  # keplint: guarded-by=_conn_lock
        self.rejected_connections_total = 0  # keplint: guarded-by=_conn_lock

    def process_request(self, request, client_address):
        if self._conn_sema is not None \
                and not self._conn_sema.acquire(blocking=False):
            with self._conn_lock:
                self.rejected_connections_total += 1
            try:
                # best-effort: over TLS the handshake may not have run,
                # so the bytes can be unreadable to the client — the
                # close alone still sheds the connection without a thread
                request.sendall(_OVERFLOW_RESPONSE)
            except OSError:
                pass
            self.shutdown_request(request)
            return
        with self._conn_lock:
            self.active_connections += 1
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._conn_lock:
                self.active_connections -= 1
            if self._conn_sema is not None:
                self._conn_sema.release()


class APIServer:
    def __init__(
        self,
        listen_addresses: list[str] | None = None,
        tls_cert: str = "",
        tls_key: str = "",
        basic_auth_check: Callable[[str | None], bool] | None = None,
        max_connections: int = 0,
    ) -> None:
        self._addresses = listen_addresses or [":28282"]
        self._tls_cert = tls_cert
        self._tls_key = tls_key
        self._auth_check = basic_auth_check
        # concurrent-connection cap shared across all listen addresses'
        # servers is deliberately NOT pooled: each listener gets the
        # full cap (operators size per listener; 0 = unbounded)
        self._max_connections = max(0, int(max_connections))
        self._endpoints: dict[str, Endpoint] = {}
        self._servers: list[_CappedThreadingHTTPServer] = []
        self._threads: list[threading.Thread] = []
        # flipped by shutdown(): established keep-alive connections get
        # one 503 + close instead of being served forever by their
        # handler threads (stopping the listener alone leaves a
        # "stopped" server happily answering persistent clients — a
        # killed ingest replica must actually go dark)
        self._draining = False
        # probe plane: services register health/readiness callables here
        # (fleet agent breaker, monitor watchdog, aggregator quarantine)
        self.health = HealthRegistry()

    def name(self) -> str:
        return "api-server"

    # keplint: role-registrar=http-handler — every callable registered
    # here runs on a ThreadingHTTPServer worker thread; keplint roots the
    # http-handler thread role at the registered handler (KTL112/KTL113)
    def register(self, path: str, name: str, description: str,
                 handler: Handler, max_body: int = 1 << 20) -> None:
        """Add an endpoint to the catalog (reference Register :167)."""
        self._endpoints[path] = Endpoint(path, name, description, handler,
                                         max_body)

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> None:
        outer = self

        # keplint: thread-role=http-handler
        class RequestHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route into our logger
                log.debug("http: " + fmt, *args)

            def _dispatch(self):
                if outer._draining:
                    # shutting down: refuse (retryable) and sever the
                    # keep-alive so the client reconnects elsewhere
                    self.close_connection = True
                    self._respond(503, {"Content-Type": "text/plain"},
                                  b"shutting down\n")
                    return
                if outer._auth_check is not None and not outer._auth_check(
                        self.headers.get("Authorization")):
                    # body (if any) was never read — drop the connection so
                    # keep-alive can't desync
                    self.close_connection = True
                    self._respond(
                        401,
                        {"Content-Type": "text/plain",
                         "WWW-Authenticate": 'Basic realm="kepler-tpu"'},
                        b"unauthorized\n")
                    return
                path = self.path.split("?", 1)[0]
                endpoint = outer._match(path)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = -1
                cap = endpoint.max_body if endpoint else 0
                if length < 0 or length > cap:
                    # don't buffer or trust the remainder of the stream
                    self.close_connection = True
                    if endpoint is not None:
                        self._respond(413, {"Content-Type": "text/plain"},
                                      b"payload too large\n")
                        return
                elif length:
                    # pre-read so keep-alive connections never desync on
                    # handlers that ignore the body
                    self.body = self.rfile.read(length)
                else:
                    self.body = b""
                if endpoint is None:
                    self._respond(404, {"Content-Type": "text/plain"},
                                  b"not found\n")
                    return
                try:
                    status, headers, body = endpoint.handler(self)
                except Exception:
                    log.exception("handler %s failed", printable(path))
                    self._respond(500, {"Content-Type": "text/plain"},
                                  b"internal error\n")
                    return
                self._respond(status, headers, body)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                self._dispatch()

            def do_POST(self):  # noqa: N802
                # handlers see request.command and the pre-read request.body
                self._dispatch()

            def _respond(self, status, headers, body):
                try:
                    self.send_response(status)
                    for k, v in headers.items():
                        self.send_header(k, v)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # client gave up (e.g. agent timeout) — not our error
                    log.debug("client disconnected before response: %s",
                              printable(self.path))

        self._handler_cls = RequestHandler
        self.register("/", "Home", "Landing page", self._landing_page)
        self.register("/healthz", "Health",
                      "degradation probe (503 while degraded; includes "
                      "external dependencies — not a kubelet livenessProbe)",
                      self.health.handle_healthz)
        self.register("/readyz", "Readiness",
                      "readiness probe (503 until components are ready)",
                      self.health.handle_readyz)
        for addr in self._addresses:
            host, _, port = addr.rpartition(":")
            server = _CappedThreadingHTTPServer(
                (host or "0.0.0.0", int(port)), RequestHandler,
                max_connections=self._max_connections)
            if self._tls_cert and self._tls_key:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self._tls_cert, self._tls_key)
                server.socket = ctx.wrap_socket(server.socket,
                                                server_side=True)
            self._servers.append(server)
        log.info("api server listening on %s",
                 [s.server_address for s in self._servers])

    def run(self, ctx: CancelContext) -> None:
        for server in self._servers:
            t = threading.Thread(target=server.serve_forever,
                                 name="http-serve", daemon=True)
            t.start()
            self._threads.append(t)
        ctx.wait(None)

    def shutdown(self) -> None:
        """Graceful shutdown, 5 s bound (reference :158-165)."""
        self._draining = True
        for server in self._servers:
            server.shutdown()
            server.server_close()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- internals ---------------------------------------------------------

    def _match(self, path: str) -> Endpoint | None:
        if path in self._endpoints:
            return self._endpoints[path]
        # prefix match for subtree handlers (e.g. /debug/...)
        best = None
        for ep_path, ep in self._endpoints.items():
            if ep_path != "/" and path.startswith(ep_path.rstrip("/") + "/"):
                if best is None or len(ep_path) > len(best.path):
                    best = ep
        return best

    def _landing_page(self, _request) -> tuple[int, dict[str, str], bytes]:
        rows = "".join(
            f'<li><a href="{html.escape(e.path)}">{html.escape(e.name)}</a>'
            f" — {html.escape(e.description)}</li>"
            for e in sorted(self._endpoints.values(), key=lambda e: e.path)
            if e.path != "/"
        )
        body = (
            "<html><head><title>kepler-tpu</title></head><body>"
            "<h1>kepler-tpu</h1><ul>" + rows + "</ul></body></html>"
        ).encode()
        return 200, {"Content-Type": "text/html; charset=utf-8"}, body

    def connection_stats(self) -> dict:
        """Connection-cap accounting across listeners (operator/test
        introspection; ``rejected_total`` counts accepts answered 503
        at the cap without ever spawning a handler thread)."""
        active = rejected = 0
        for s in self._servers:
            with s._conn_lock:
                active += s.active_connections
                rejected += s.rejected_connections_total
        return {"max_connections": self._max_connections,
                "active_connections": active,
                "rejected_total": rejected}

    @property
    def addresses(self) -> list[tuple[str, int]]:
        """Actual bound (host, port) pairs — ports resolve 0 → ephemeral."""
        return [s.server_address for s in self._servers]
