"""Web config file: TLS + basic auth for the API server.

Reference parity: ``internal/server/server.go:136-156`` hands the listener
to ``prometheus/exporter-toolkit`` when ``web.config-file`` is set. This
module reads the same file format (the exporter-toolkit subset that the
reference's ``server_tls_test.go`` exercises):

.. code-block:: yaml

    tls_server_config:
      cert_file: /path/server.crt
      key_file: /path/server.key
    basic_auth_users:
      alice: $2y$10$...       # bcrypt (needs the optional bcrypt module)
      bob: $5$rounds=...      # or crypt(3) sha256/sha512 from stdlib

Password hashes: exporter-toolkit mandates bcrypt; that module is optional
here, so SHA-crypt ``$5$``/``$6$`` hashes are accepted as the
always-available alternative, verified by the pure-Python
:mod:`kepler_tpu.server.shacrypt` (the stdlib ``crypt`` module this path
once used was removed in Python 3.13). Generate one with
``python -c "from kepler_tpu.server.shacrypt import mksha512crypt;
print(mksha512crypt('pw'))"``.
"""

from __future__ import annotations

import base64
import binascii
import logging
from dataclasses import dataclass, field
from typing import Callable, Mapping

import yaml

log = logging.getLogger("kepler.server")


@dataclass
class WebConfigFile:
    cert_file: str = ""
    key_file: str = ""
    basic_auth_users: dict[str, str] = field(default_factory=dict)

    @property
    def has_tls(self) -> bool:
        return bool(self.cert_file and self.key_file)


def load_web_config(path: str) -> WebConfigFile:
    """Parse + validate a web config file (exporter-toolkit subset)."""
    with open(path, encoding="utf-8") as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, Mapping):
        raise ValueError(f"web config {path!r}: root must be a mapping")
    known = {"tls_server_config", "basic_auth_users"}
    unknown = set(data) - known
    if unknown:
        raise ValueError(f"web config {path!r}: unknown keys {sorted(unknown)}"
                         f" (supported: {sorted(known)})")
    tls = data.get("tls_server_config") or {}
    if not isinstance(tls, Mapping):
        raise ValueError("tls_server_config must be a mapping")
    cert = str(tls.get("cert_file", "") or "")
    key = str(tls.get("key_file", "") or "")
    if bool(cert) != bool(key):
        raise ValueError("tls_server_config needs both cert_file and key_file")
    users = data.get("basic_auth_users") or {}
    if not isinstance(users, Mapping):
        raise ValueError("basic_auth_users must be a mapping")
    users = {str(u): str(h) for u, h in users.items()}
    for user, h in users.items():
        _verify_hash_supported(user, h)
    return WebConfigFile(cert_file=cert, key_file=key,
                         basic_auth_users=users)


def _verify_hash_supported(user: str, h: str) -> None:
    if h.startswith(("$2a$", "$2b$", "$2y$")):
        try:
            import bcrypt  # noqa: F401
        except ImportError:
            raise ValueError(
                f"basic_auth_users[{user!r}]: bcrypt hash but the bcrypt "
                "module is not installed; use a crypt(3) $5$/$6$ hash "
                "instead") from None
        return
    if h.startswith(("$5$", "$6$")):
        return  # SHA-crypt: verified by the bundled pure-Python shacrypt
    raise ValueError(
        f"basic_auth_users[{user!r}]: unsupported hash format "
        f"{h[:4]!r}… (supported: bcrypt $2*$, SHA-crypt $5$/$6$)")


def _check_password(password: str, hashed: str) -> bool:
    if hashed.startswith(("$2a$", "$2b$", "$2y$")):
        import bcrypt

        return bcrypt.checkpw(password.encode(), hashed.encode())
    from kepler_tpu.server import shacrypt

    return shacrypt.verify(password, hashed)


def _make_dummy_hash(users: Mapping[str, str]) -> str:
    """A fixed dummy hash for unknown-user verifies, PRECOMPUTED once at
    authenticator build time from an unguessable password.

    The previous equalizer verified against ``next(iter(users.values()))``
    — an arbitrary REAL user's hash. With mixed bcrypt/SHA-crypt configs
    that pins the unknown-user cost to whichever scheme happens to sit
    first in dict order, so the timing difference against a probe of a
    known user under the OTHER scheme leaked username existence (and it
    ran a real credential check against a real hash with attacker-chosen
    input). The dummy is its own hash: bcrypt when any configured user is
    bcrypt (the costlier scheme), SHA-512-crypt otherwise — at the MAX
    cost parameter configured for that scheme, so within a scheme an
    unknown-user verify is never cheaper than a real one (a lower-cost
    dummy would leak existence by being faster than the costliest user).
    Users configured with differing costs remain distinguishable from
    each other by timing regardless of what the dummy does — per-user
    cost divergence is a config smell, not something a dummy can mask.
    """
    import re
    import secrets

    password = secrets.token_hex(16)
    bcrypt_hashes = [h for h in users.values()
                     if h.startswith(("$2a$", "$2b$", "$2y$"))]
    if bcrypt_hashes:
        import bcrypt  # load_web_config verified availability

        costs = [int(m.group(1)) for h in bcrypt_hashes
                 if (m := re.match(r"\$2[aby]\$(\d{2})\$", h))]
        salt = bcrypt.gensalt(rounds=max(costs)) if costs \
            else bcrypt.gensalt()
        return bcrypt.hashpw(password.encode(), salt).decode()
    from kepler_tpu.server import shacrypt

    # a rounds-less $5/$6 hash runs at the scheme default — it must
    # count toward the max or default-cost users would out-cost the dummy
    rounds = [int(m.group(1))
              if (m := re.match(r"\$[56]\$rounds=(\d+)\$", h))
              else shacrypt._ROUNDS_DEFAULT
              for h in users.values()]
    return shacrypt.mksha512crypt(password,
                                  rounds=max(rounds) if rounds else None)


def make_authenticator(users: Mapping[str, str]
                       ) -> Callable[[str | None], bool] | None:
    """→ fn(Authorization header) -> allowed, or None when auth is off."""
    if not users:
        return None
    # unknown-user timing equalizer: a fixed constant-cost dummy hash,
    # never a configured user's real hash (see _make_dummy_hash)
    dummy_hash = _make_dummy_hash(users)

    def check(header: str | None) -> bool:
        if not header or not header.startswith("Basic "):
            return False
        try:
            raw = base64.b64decode(header[6:], validate=True).decode()
            user, _, password = raw.partition(":")
        except (binascii.Error, UnicodeDecodeError):
            return False
        hashed = users.get(user)
        try:
            if hashed is None:
                # burn the dummy verify so a timing probe can't
                # enumerate usernames; the result is discarded
                _check_password(password, dummy_hash)
                return False
            return _check_password(password, hashed)
        except Exception:
            log.exception("basic-auth check failed for user %r", user)
            return False

    return check


def make_api_server(listen_addresses: list[str], config_file: str = "",
                    max_connections: int = 0):
    """API server honouring a web config file (TLS + basic auth) —
    reference ``server.go:136-156`` via exporter-toolkit. Shared by the
    node-agent and aggregator entry points. ``max_connections`` caps
    concurrent handler threads (``web.maxConnections``; 0 = unbounded)."""
    from kepler_tpu.server.http import APIServer

    web = load_web_config(config_file) if config_file else None
    return APIServer(
        listen_addresses=listen_addresses,
        tls_cert=web.cert_file if web else "",
        tls_key=web.key_file if web else "",
        basic_auth_check=(make_authenticator(web.basic_auth_users)
                          if web else None),
        max_connections=max_connections,
    )
