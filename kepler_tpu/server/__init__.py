"""HTTP API server + debug endpoints (reference ``internal/server/``)."""

from kepler_tpu.server.debug import DebugService
from kepler_tpu.server.http import APIServer

__all__ = ["APIServer", "DebugService"]
