"""Debug/profiling endpoints.

Reference parity: ``internal/server/pprof.go`` mounts ``net/http/pprof``
under ``/debug/pprof/`` when ``debug.pprof`` is enabled. The Python analog
serves:

- ``/debug/pprof/``         — index of available profiles
- ``/debug/pprof/stack``    — live stack dump of every thread (goroutine
                              profile analog)
- ``/debug/pprof/profile``  — sampling CPU profile across ALL threads
                              (?seconds=N&hz=M); aggregates
                              ``sys._current_frames()`` samples, so it sees
                              the monitor/exporter threads, which a
                              per-thread cProfile cannot
- ``/debug/pprof/jax``      — one-shot JAX device profiler trace to a temp
                              dir (TPU addition; inspect with TensorBoard)
"""

from __future__ import annotations

# keplint: monotonic-only — profile/trace deadlines use elapsed time

import collections
import io
import math
import sys
import tempfile
import threading
import time
import traceback
from urllib.parse import parse_qs, urlparse

from kepler_tpu.server.http import APIServer


class DebugService:
    def __init__(self, server: APIServer) -> None:
        self._server = server

    def name(self) -> str:
        return "pprof"

    def init(self) -> None:
        self._server.register("/debug/pprof/", "Profiling",
                              "pprof-style debug profiles", self._handle)

    def _handle(self, request) -> tuple[int, dict[str, str], bytes]:
        url = urlparse(request.path)
        parts = [p for p in url.path.split("/") if p]
        which = parts[2] if len(parts) > 2 else "index"
        if which == "stack":
            return self._stacks()
        if which == "profile":
            qs = parse_qs(url.query)
            # query values come off the wire: a non-numeric (or NaN/inf)
            # seconds/hz must be a 400, never a traceback into the
            # server's generic 500 handler
            try:
                seconds = float(qs.get("seconds", ["5"])[0])
                hz = float(qs.get("hz", ["100"])[0])
            except ValueError:
                return (400, {"Content-Type": "text/plain"},
                        b"seconds/hz must be numeric\n")
            if not (math.isfinite(seconds) and math.isfinite(hz)):
                return (400, {"Content-Type": "text/plain"},
                        b"seconds/hz must be finite\n")
            return self._profile(min(max(seconds, 0.0), 60.0),
                                 min(max(hz, 1.0), 1000.0))
        if which == "jax":
            return self._jax_trace()
        body = (
            "<html><body><h1>debug/pprof</h1><ul>"
            '<li><a href="/debug/pprof/stack">stack</a></li>'
            '<li><a href="/debug/pprof/profile?seconds=5">profile</a></li>'
            '<li><a href="/debug/pprof/jax">jax trace</a></li>'
            "</ul>"
            "<h2>other debug surfaces</h2><ul>"
            '<li><a href="/debug/traces">traces</a> — recent cycle span '
            "traces (?format=chrome loads in Perfetto)</li>"
            '<li><a href="/debug/window">window</a> — device-plane '
            "introspection: rung + timeline, shards, compile-cache cost "
            "stats (aggregator role)</li>"
            '<li><a href="/debug/fleet">fleet</a> — per-node scoreboard '
            "(aggregator role)</li>"
            '<li><a href="/debug/journal">journal</a> — fleet black box: '
            "HLC-stamped causal event journal (?since=&lt;cursor&gt; "
            "paginates)</li>"
            '<li><a href="/debug/bundle">bundle</a> — one-shot incident '
            "snapshot (feed to python -m kepler_tpu.blackbox)</li>"
            "</ul></body></html>"
        ).encode()
        return 200, {"Content-Type": "text/html"}, body

    @staticmethod
    def _stacks() -> tuple[int, dict[str, str], bytes]:
        out = io.StringIO()
        frames = sys._current_frames()
        for thread in threading.enumerate():
            frame = frames.get(thread.ident)
            out.write(f"--- thread {thread.name} (id {thread.ident}) ---\n")
            if frame:
                traceback.print_stack(frame, file=out)
            out.write("\n")
        return 200, {"Content-Type": "text/plain"}, out.getvalue().encode()

    @staticmethod
    def _profile(seconds: float, hz: float
                 ) -> tuple[int, dict[str, str], bytes]:
        """Statistical profile: sample every thread's stack at ``hz``."""
        own = threading.get_ident()
        counts: collections.Counter[tuple[str, ...]] = collections.Counter()
        samples = 0
        deadline = time.monotonic() + seconds
        period = 1.0 / hz
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == own:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < 24:
                    code = f.f_code
                    stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                                 f"{f.f_lineno} {code.co_name}")
                    f = f.f_back
                counts[tuple(reversed(stack))] += 1
            samples += 1
            time.sleep(period)
        out = io.StringIO()
        out.write(f"sampling profile: {samples} samples over {seconds}s "
                  f"at {hz:g} Hz (all threads except handler)\n\n")
        for stack, n in counts.most_common(40):
            out.write(f"{n}/{samples} samples ({n / max(samples, 1):.1%}):\n")
            for line in stack:
                out.write(f"    {line}\n")
            out.write("\n")
        return 200, {"Content-Type": "text/plain"}, out.getvalue().encode()

    @staticmethod
    def _jax_trace() -> tuple[int, dict[str, str], bytes]:
        try:
            import jax
        except ImportError:  # pragma: no cover
            return 503, {"Content-Type": "text/plain"}, b"jax unavailable\n"
        trace_dir = tempfile.mkdtemp(prefix="kepler-jax-trace-")
        with jax.profiler.trace(trace_dir):
            # capture one trivial device op so the trace isn't empty; real
            # attribution steps landing in this window are also captured
            jax.numpy.zeros(8).block_until_ready()
            time.sleep(0.5)
        msg = f"jax trace written to {trace_dir}\n"
        return 200, {"Content-Type": "text/plain"}, msg.encode()
