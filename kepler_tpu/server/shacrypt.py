"""Pure-Python SHA-crypt ($5$ sha256 / $6$ sha512) password verification.

Replaces the stdlib ``crypt`` module in the basic-auth path
(``webconfig.py``): ``crypt(3)`` was deprecated in Python 3.11 and
REMOVED in 3.13, so hash verification must not depend on it. This is an
independent implementation of Ulrich Drepper's public SHA-crypt
specification (https://www.akkadia.org/drepper/SHA-crypt.txt, released
to the public domain) — the same scheme glibc's ``crypt(3)`` implements
— and is fuzz-verified against the real ``crypt(3)`` in
``tests/test_server_tls.py`` wherever that module still exists.

Reference parity: the reference delegates basic auth to
``prometheus/exporter-toolkit`` (``internal/server/server.go:136-156``),
which mandates bcrypt; this repo additionally accepts SHA-crypt hashes
so auth works without the optional ``bcrypt`` dependency.

Only verification (and the hash computation it needs) is provided —
generating new hashes should use ``mksha512crypt`` below or any htpasswd
tooling.
"""

from __future__ import annotations

import hashlib
import hmac
import re
import secrets

_B64_CHARS = "./0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"

# Output-byte permutations from the spec (step 22): digest bytes are
# regrouped into 24-bit words before base64 coding.
_ORDER_512 = (
    (0, 21, 42), (22, 43, 1), (44, 2, 23), (3, 24, 45), (25, 46, 4),
    (47, 5, 26), (6, 27, 48), (28, 49, 7), (50, 8, 29), (9, 30, 51),
    (31, 52, 10), (53, 11, 32), (12, 33, 54), (34, 55, 13), (56, 14, 35),
    (15, 36, 57), (37, 58, 16), (59, 17, 38), (18, 39, 60), (40, 61, 19),
    (62, 20, 41),
)
_ORDER_256 = (
    (0, 10, 20), (21, 1, 11), (12, 22, 2), (3, 13, 23), (24, 4, 14),
    (15, 25, 5), (6, 16, 26), (27, 7, 17), (18, 28, 8), (9, 19, 29),
)

_ROUNDS_DEFAULT = 5000
_ROUNDS_MIN = 1000
_ROUNDS_MAX = 999_999_999
_SALT_MAX = 16

_HASH_RE = re.compile(
    r"^\$(?P<id>5|6)\$(?:rounds=(?P<rounds>\d+)\$)?"
    r"(?P<salt>[^$]{0,16})\$(?P<digest>[./0-9A-Za-z]+)$")


def _b64_from_24bit(b2: int, b1: int, b0: int, n: int) -> str:
    w = (b2 << 16) | (b1 << 8) | b0
    out = []
    for _ in range(n):
        out.append(_B64_CHARS[w & 0x3F])
        w >>= 6
    return "".join(out)


def _encode_digest(digest: bytes, use_512: bool) -> str:
    order = _ORDER_512 if use_512 else _ORDER_256
    parts = [_b64_from_24bit(digest[a], digest[b], digest[c], 4)
             for a, b, c in order]
    if use_512:
        parts.append(_b64_from_24bit(0, 0, digest[63], 2))
    else:
        parts.append(_b64_from_24bit(0, digest[31], digest[30], 3))
    return "".join(parts)


def _sha_crypt_digest(password: bytes, salt: bytes, rounds: int,
                      use_512: bool) -> bytes:
    """Steps 1-21 of the spec, shared by the $5$ and $6$ variants."""
    H = hashlib.sha512 if use_512 else hashlib.sha256
    dlen = 64 if use_512 else 32

    # B: password + salt + password (steps 4-8)
    b = H(password + salt + password).digest()
    # A: password + salt + B stretched to len(password) + binary-length
    # walk over B/password (steps 1-3, 9-12)
    a = H()
    a.update(password)
    a.update(salt)
    n = len(password)
    a.update(b * (n // dlen) + b[: n % dlen])
    bits = n
    while bits > 0:
        a.update(b if bits & 1 else password)
        bits >>= 1
    a_digest = a.digest()

    # DP → P: password repeated len(password) times (steps 13-16)
    dp = H(password * n).digest()
    p = dp * (n // dlen) + dp[: n % dlen]
    # DS → S: salt repeated 16 + A[0] times (steps 17-20)
    ds = H(salt * (16 + a_digest[0])).digest()
    s = ds * (len(salt) // dlen) + ds[: len(salt) % dlen]

    # step 21: the rounds loop
    c = a_digest
    for i in range(rounds):
        h = H()
        h.update(p if i % 2 else c)
        if i % 3:
            h.update(s)
        if i % 7:
            h.update(p)
        h.update(c if i % 2 else p)
        c = h.digest()
    return c


def sha_crypt(password: str | bytes, salt_spec: str) -> str:
    """Full crypt(3)-compatible hash for ``salt_spec`` = ``$5$…``/``$6$…``.

    ``salt_spec`` may be a bare salt spec (``$6$somesalt``, with optional
    ``rounds=N$``) or a complete prior hash — matching ``crypt.crypt``'s
    contract that ``crypt(pw, hashed) == hashed`` verifies a password.
    """
    m = re.match(
        r"^\$(?P<id>5|6)\$(?:rounds=(?P<rounds>\d+)\$)?(?P<salt>[^$]{0,16})",
        salt_spec)
    if m is None:
        raise ValueError(f"unsupported salt spec {salt_spec[:8]!r}…")
    use_512 = m.group("id") == "6"
    rounds_given = m.group("rounds") is not None
    rounds = int(m.group("rounds")) if rounds_given else _ROUNDS_DEFAULT
    rounds = max(_ROUNDS_MIN, min(_ROUNDS_MAX, rounds))
    salt = m.group("salt")[:_SALT_MAX]
    pw = password.encode() if isinstance(password, str) else password
    digest = _sha_crypt_digest(pw, salt.encode(), rounds, use_512)
    prefix = f"${m.group('id')}$"
    if rounds_given:
        prefix += f"rounds={rounds}$"
    return f"{prefix}{salt}${_encode_digest(digest, use_512)}"


def verify(password: str | bytes, hashed: str) -> bool:
    """Constant-time check of ``password`` against a $5$/$6$ hash."""
    if _HASH_RE.match(hashed) is None:
        return False
    return hmac.compare_digest(sha_crypt(password, hashed), hashed)


def mksha512crypt(password: str, rounds: int | None = None) -> str:
    """Generate a fresh ``$6$`` hash (utility for htpasswd-style setup)."""
    salt = "".join(secrets.choice(_B64_CHARS) for _ in range(_SALT_MAX))
    spec = (f"$6$rounds={rounds}${salt}" if rounds is not None
            else f"$6${salt}")
    return sha_crypt(password, spec)
