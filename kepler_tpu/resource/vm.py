"""Virtual-machine (hypervisor process) detection.

Reference parity: ``internal/resource/vm.go`` — QEMU/KVM detection via
cmdline regex (:15); VM id from ``-uuid``, name from ``-name guest=...``;
deterministic fallback id from a hash of the cmdline (:103-109).
"""

from __future__ import annotations

import hashlib
import re

from kepler_tpu.resource.procfs import ProcInfo
from kepler_tpu.resource.types import Hypervisor, VirtualMachine

_QEMU_RE = re.compile(r"(bin/qemu-system-\w+|libexec/qemu-kvm)")


def _extract_flag(cmdline: list[str], flag: str) -> str:
    # "-name foo" and "-name=foo" forms (reference vm_test.go covers both)
    for i, arg in enumerate(cmdline):
        if arg == flag and i + 1 < len(cmdline):
            return cmdline[i + 1]
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
    return ""


def _guest_name(name_arg: str) -> str:
    # "-name guest=myvm,debug-threads=on" → "myvm"; bare "-name foo" → "foo"
    for part in name_arg.split(","):
        if part.startswith("guest="):
            return part.split("=", 1)[1]
    if "=" not in name_arg:
        return name_arg
    return ""


def vm_info_from_cmdline(cmdline: list[str]) -> VirtualMachine | None:
    """QEMU/KVM detection from an already-read cmdline (the batched
    first-sight path hands contents over; no file IO here)."""
    if not cmdline:
        return None
    joined = " ".join(cmdline)
    if not _QEMU_RE.search(joined):
        return None
    vm_id = _extract_flag(cmdline, "-uuid")
    name = _guest_name(_extract_flag(cmdline, "-name"))
    if not vm_id:
        if name:
            vm_id = name
        else:  # deterministic fallback hash (reference vm.go:103-109)
            vm_id = hashlib.sha256(joined.encode()).hexdigest()[:16]
    return VirtualMachine(id=vm_id, name=name or vm_id,
                          hypervisor=Hypervisor.KVM)


def vm_info_from_proc(proc: ProcInfo) -> VirtualMachine | None:
    try:
        cmdline = proc.cmdline()
    except OSError:
        return None
    return vm_info_from_cmdline(cmdline)
