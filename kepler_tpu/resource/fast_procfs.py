"""Native-accelerated /proc reader.

Same semantics as :class:`kepler_tpu.resource.procfs.ProcFSReader`
(reference ``internal/resource/procfs_reader.go``), but the per-tick hot
path — one stat read per PID plus the /proc/stat totals — is a single C
call into ``kepler_tpu.native`` instead of thousands of Python
open/read/parse round-trips. Everything cold (comm/exe/cgroup/environ/
cmdline, read once per PID at classification time) stays the Python
implementation.

``make_proc_reader`` picks the fast path when the native library is
available and falls back silently otherwise, so callers never care.
"""

from __future__ import annotations

import logging

import numpy as np

from kepler_tpu import native
from kepler_tpu.resource.procfs import ProcFSInfo, ProcFSReader

log = logging.getLogger("kepler.resource")


class FastProcInfo(ProcFSInfo):
    """ProcFSInfo whose cpu_time came from the batched native scan."""

    def __init__(self, procfs: str, pid: int, cpu_time_s: float) -> None:
        super().__init__(procfs, pid)
        self._cpu_time_s = cpu_time_s

    def cpu_time(self) -> float:
        return self._cpu_time_s


class FastProcFSReader(ProcFSReader):
    def __init__(self, scanner: native.NativeScanner,
                 procfs: str = "/proc") -> None:
        super().__init__(procfs)
        self._scanner = scanner

    def all_procs(self) -> list[FastProcInfo]:
        pids, cpu, _ = self._scanner.scan_procs(self._procfs,
                                                want_comms=False)
        return [
            FastProcInfo(self._procfs, int(p), float(c))
            for p, c in zip(pids, cpu)
        ]

    def scan_arrays(self) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
        """→ (pids int32, cpu_seconds f64, comms S32) numpy arrays — the
        allocation-free tick path: the informer updates its cache straight
        from these and only materializes a ProcInfo for NEW pids
        (classification). One C call, zero per-proc objects; comm comes
        from the stat line (the same field /proc/<pid>/comm serves), so no
        per-PID comm reads happen at all."""
        return self._scanner.scan_procs(self._procfs)

    def proc_info(self, pid: int) -> ProcFSInfo:
        """Cold-path reader for one PID (classification/comm/exe)."""
        return ProcFSInfo(self._procfs, pid)

    #: slot size for read_proc_files when the caller doesn't override it.
    #: Consumers that need truncation detection (informer
    #: _reread_if_truncated) read THIS attribute rather than duplicating
    #: the number — a content of exactly cap-1 bytes means ReadSmallFile
    #: hit the slot end.
    batch_read_cap: int = 16384

    def read_proc_files(self, relpaths: list[str],
                        per_cap: int | None = None) -> list[bytes | None]:
        """Batch-read ``<procfs>/<relpath>`` files in one threaded C call
        (first-sight classification bursts stay native)."""
        paths = [f"{self._procfs}/{rel}" for rel in relpaths]
        if per_cap is None:
            per_cap = self.batch_read_cap
        return self._scanner.read_files(paths, per_cap=per_cap)

    def read_proc_links(self, relpaths: list[str]) -> list[str | None]:
        """Batch-readlink ``<procfs>/<relpath>`` (e.g. ``<pid>/exe``)."""
        paths = [f"{self._procfs}/{rel}" for rel in relpaths]
        return self._scanner.read_links(paths)

    def _read_stat_totals(self) -> tuple[float, float]:
        return self._scanner.stat_totals(self._procfs)


def make_proc_reader(procfs: str = "/proc",
                     use_native: bool | None = None) -> ProcFSReader:
    """Best available reader: native batched scan if buildable, else Python.

    ``use_native``: True forces native (raises if unavailable), False forces
    Python, None (default) auto-detects.
    """
    if use_native is False:
        return ProcFSReader(procfs)
    scanner = native.scanner()
    if scanner is None:
        if use_native:
            import os
            why = ("disabled via KEPLER_NO_NATIVE"
                   if os.environ.get("KEPLER_NO_NATIVE")
                   else "no g++ or build failed")
            raise RuntimeError(
                f"native scanner requested but unavailable ({why})")
        return ProcFSReader(procfs)
    log.debug("using native procfs scanner (%s)", native.lib_path())
    return FastProcFSReader(scanner, procfs)
