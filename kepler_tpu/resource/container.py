"""Container detection from cgroup paths.

Reference parity: ``internal/resource/container.go`` — regex over
``/proc/<pid>/cgroup`` paths for 7 runtime patterns (:14-25), choosing the
*deepest* (most path components) match (:92-141); container name from
HOSTNAME / CONTAINER_NAME env (:144-159) or ``--name`` in cmdline (:162-190).
"""

from __future__ import annotations

import re

from kepler_tpu.resource.types import Container, ContainerRuntime
from kepler_tpu.resource.procfs import ProcInfo

# (pattern, runtime) in reference order (container.go:14-40).
_PATTERNS: list[tuple[re.Pattern[str], ContainerRuntime]] = [
    (re.compile(r"/docker[-/]([0-9a-f]{64})"), ContainerRuntime.DOCKER),
    (re.compile(r"/containerd[-/]([0-9a-f]{64})"), ContainerRuntime.CONTAINERD),
    (re.compile(r"[:/]cri-containerd[-:]([0-9a-f]{64})"),
     ContainerRuntime.CONTAINERD),
    (re.compile(r"/crio-([0-9a-f]{64})"), ContainerRuntime.CRIO),
    (re.compile(r"libpod-([0-9a-f]{64})"), ContainerRuntime.PODMAN),
    (re.compile(r"/libpod-payload-([0-9a-f]+)"), ContainerRuntime.PODMAN),
    (re.compile(r"/kubepods/[^/]+/pod[0-9a-f\-]+/([0-9a-f]{64})"),
     ContainerRuntime.KUBEPODS),
]

# Cheap PREFILTER: one alternation (group-free, patterns verbatim) that
# matches iff ANY runtime pattern would. The per-pattern deepest-match
# loop below is exact but ~7 scans per path; most processes on a real
# node are NOT containers, and a burst of new system procs classifies in
# one combined scan each. A single left-to-right alternation cannot
# REPLACE the loop — a long early match (kubepods) would consume the
# span and hide a deeper-starting inner match (libpod nested inside) —
# so it only gates it.
_PREFILTER = re.compile("|".join(f"(?:{p.pattern})" for p, _ in _PATTERNS))


def container_info_from_cgroup_paths(
    paths: list[str],
) -> tuple[ContainerRuntime, str]:
    """Return (runtime, container_id) of the deepest match.

    Deepest = highest match start index, across ALL matches in all paths —
    systemd nesting (kind-in-docker) puts the leaf container scope after
    its host's, so the later match identifies the process (reference
    container.go:92-141 sorts by StartIdx descending).
    """
    best: tuple[int, ContainerRuntime, str] | None = None
    for path in paths:
        if _PREFILTER.search(path) is None:
            continue
        for pattern, runtime in _PATTERNS:
            for m in pattern.finditer(path):
                if best is None or m.start() > best[0]:
                    best = (m.start(), runtime, m.group(1))
    if best is None:
        return ContainerRuntime.UNKNOWN, ""
    return best[1], best[2]


def _name_from_env(env: dict[str, str]) -> str:
    # CONTAINER_NAME beats HOSTNAME (reference container.go:144-159)
    if env.get("CONTAINER_NAME"):
        return env["CONTAINER_NAME"]
    return env.get("HOSTNAME", "")


def _name_from_cmdline(cmdline: list[str]) -> str:
    # docker/podman runtimes pass --name <name> or --name=<name>; the
    # containerd shims pass the container name positionally as argv[3]
    # (reference container.go:162-190)
    if len(cmdline) <= 1:
        return ""
    exe = cmdline[0].rsplit("/", 1)[-1]
    shim = exe in ("docker-containerd-shim", "containerd-shim")
    for i, arg in enumerate(cmdline):
        if i > 0:
            if arg == "--name" and i + 1 < len(cmdline):
                return cmdline[i + 1]
            if arg.startswith("--name="):
                return arg.split("=", 1)[1]
        if shim and i == 3:
            return arg
    return ""


def container_name(env: dict[str, str], cmdline: list[str],
                   container_id: str) -> str:
    """Resolve a container's display name: env beats cmdline beats the
    id-prefix fallback (reference container.go:144-190)."""
    name = _name_from_env(env)
    if not name:
        name = _name_from_cmdline(cmdline)
    return name or container_id[:12]


def container_info_from_proc(proc: ProcInfo) -> Container | None:
    """Detect containment; None when the process isn't in a container."""
    try:
        paths = proc.cgroups()
    except OSError:
        return None
    if not paths:
        return None
    runtime, container_id = container_info_from_cgroup_paths(paths)
    if not container_id:
        return None
    env: dict[str, str] = {}
    cmdline: list[str] = []
    try:
        env = proc.environ()
    except OSError:
        pass
    try:
        cmdline = proc.cmdline()
    except OSError:
        pass
    return Container(id=container_id,
                     name=container_name(env, cmdline, container_id),
                     runtime=runtime)
