"""Resource informer: per-interval workload discovery and delta accounting.

Reference parity: ``internal/resource/informer.go`` — scan all PIDs each
refresh; cache processes/containers/VMs/pods keyed by PID/ID; compute
CPU-time deltas vs cache; classify processes as container/VM (classification
cached; re-done only when a process's CPU delta is non-negligible,
``populateProcessFields`` :512); aggregate deltas hierarchically
proc → container → pod; detect terminated entities by set difference
(:167-221); compute node totals + usage ratio (:328-345).

TPU-first pivot: besides the object views (``processes()`` etc., same shape
as the reference API :49-66), every refresh also materializes a
``FeatureBatch`` — dense numpy columns (cpu_time_delta per workload, stable
row ids) that feed the jitted attribution kernel without per-object Python
iteration (SURVEY §2 row 10 "representational pivot").
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Mapping, Protocol, Sequence

import numpy as np

from kepler_tpu.resource.container import container_info_from_proc
from kepler_tpu.resource.procfs import ProcFSReader, ProcInfo, ProcReader
from kepler_tpu.resource.types import (
    Container,
    Node,
    Pod,
    Process,
    VirtualMachine,
)
from kepler_tpu.resource.vm import vm_info_from_proc

log = logging.getLogger("kepler.resource")

# Δcpu below this (seconds) skips re-classification (reference :512-558 —
# idle processes don't pay the cgroup/environ re-read).
_RECLASSIFY_EPSILON = 1e-9


class PodLookup(Protocol):
    """Pod-metadata join point (reference pod.Informer.LookupByContainerID)."""

    def lookup_by_container_id(
        self, container_id: str
    ) -> tuple[str, str, str, str] | None:
        """→ (pod_id, pod_name, namespace, container_name) or None."""
        ...


@dataclass
class Processes:
    running: dict[int, Process] = field(default_factory=dict)
    terminated: dict[int, Process] = field(default_factory=dict)


@dataclass
class Containers:
    running: dict[str, Container] = field(default_factory=dict)
    terminated: dict[str, Container] = field(default_factory=dict)


@dataclass
class VirtualMachines:
    running: dict[str, VirtualMachine] = field(default_factory=dict)
    terminated: dict[str, VirtualMachine] = field(default_factory=dict)


@dataclass
class Pods:
    running: dict[str, Pod] = field(default_factory=dict)
    terminated: dict[str, Pod] = field(default_factory=dict)
    containers_no_pod: list[str] = field(default_factory=list)


@dataclass
class FeatureBatch:
    """Dense per-workload feature columns for one refresh window.

    Row order is stable for the lifetime of a workload (rows are appended on
    first sight and compacted on termination), so downstream per-row energy
    accumulators can be gathered/scattered by index on device.
    """

    kinds: np.ndarray  # int8 [W]: 0=process 1=container 2=vm 3=pod
    ids: list[str]  # [W] workload ids (str(pid) for processes)
    cpu_deltas: np.ndarray  # f32 [W] seconds
    node_cpu_delta: float  # Σ process deltas (attribution denominator)
    usage_ratio: float  # node active/total CPU ratio

    KIND_PROCESS = 0
    KIND_CONTAINER = 1
    KIND_VM = 2
    KIND_POD = 3


class ResourceInformer:
    def __init__(
        self,
        reader: ProcReader | None = None,
        procfs_path: str = "/proc",
        pod_lookup: PodLookup | None = None,
        clock: Callable[[], float] | None = None,
    ) -> None:
        import time as _time

        self._fs: ProcReader = reader or ProcFSReader(procfs_path)
        self._pod_lookup = pod_lookup
        self._clock = clock or _time.time
        self._node = Node()
        self._proc_cache: dict[int, Process] = {}
        self._container_cache: dict[str, Container] = {}
        self._vm_cache: dict[str, VirtualMachine] = {}
        self._pod_cache: dict[str, Pod] = {}
        self._processes = Processes()
        self._containers = Containers()
        self._vms = VirtualMachines()
        self._pods = Pods()
        self._last_scan: float | None = None

    def name(self) -> str:
        return "resource-informer"

    def init(self) -> None:
        """Probe the proc reader once (reference Init :155)."""
        list(self._fs.all_procs())

    # -- accessors (reference informer.go:49-66) --------------------------

    def node(self) -> Node:
        return self._node

    def processes(self) -> Processes:
        return self._processes

    def containers(self) -> Containers:
        return self._containers

    def virtual_machines(self) -> VirtualMachines:
        return self._vms

    def pods(self) -> Pods:
        return self._pods

    # -- refresh ----------------------------------------------------------

    def refresh(self) -> None:
        """One full scan: processes first, then container/VM/pod rollups and
        node totals (reference Refresh :349-410 runs the rollups in three
        goroutines; they are independent dict walks, sequential here — the
        scan itself dominates)."""
        self._refresh_processes()
        self._refresh_containers()
        self._refresh_vms()
        self._refresh_pods()
        self._refresh_node()
        self._last_scan = self._clock()

    def _refresh_processes(self) -> None:
        scan = getattr(self._fs, "scan_arrays", None)
        if scan is not None:
            pids, cpus = scan()
            running = self._refresh_from_arrays(pids, cpus)
        else:
            running = {}
            for proc in self._fs.all_procs():
                try:
                    entry = self._update_process_cache(proc)
                except OSError:
                    continue  # PID vanished mid-scan (reference :186-190)
                except (ValueError, IndexError):
                    continue  # truncated/garbage stat line mid-exit
                running[entry.pid] = entry
        terminated = {
            pid: p for pid, p in self._proc_cache.items() if pid not in running
        }
        for pid in terminated:
            del self._proc_cache[pid]
        self._processes = Processes(running=running, terminated=terminated)

    def _refresh_from_arrays(self, pids: list[int], cpus: list[float]
                             ) -> dict[int, Process]:
        """Tick path for readers with a batched scan (`scan_arrays`): same
        cache semantics as `_update_process_cache`, but the 10k-per-tick
        steady state touches only the cache dict — ProcInfo objects (and
        their file reads) exist only for NEW pids and for procs whose
        nonzero delta warrants a comm refresh."""
        cache = self._proc_cache
        proc_info = self._fs.proc_info
        running: dict[int, Process] = {}
        for pid, cpu in zip(pids, cpus):
            cached = cache.get(pid)
            if cached is None:
                try:
                    info = proc_info(pid)
                    cached = Process(pid=pid, comm=info.comm(),
                                     exe=info.executable(),
                                     cpu_total_time=cpu, cpu_time_delta=cpu)
                    self._classify(info, cached)
                except (OSError, ValueError, IndexError):
                    # vanished mid-scan, or truncated/garbage proc files
                    # mid-exit — same tolerance as the legacy scan loop
                    continue
                cache[pid] = cached
                running[pid] = cached
                continue
            delta = cpu - cached.cpu_total_time
            delta = delta if delta > 0.0 else 0.0
            cached.cpu_time_delta = delta
            cached.cpu_total_time = cpu
            if delta > _RECLASSIFY_EPSILON:
                try:
                    info = proc_info(pid)
                    cached.comm = info.comm()
                    if not cached.classified:
                        self._classify(info, cached)
                except (OSError, ValueError, IndexError):
                    pass  # mid-exit garbage: keep cached identity
            running[pid] = cached
        return running

    def _update_process_cache(self, proc: ProcInfo) -> Process:
        pid = proc.pid()
        cpu = proc.cpu_time()
        cached = self._proc_cache.get(pid)
        if cached is None:
            cached = Process(pid=pid, comm=proc.comm(),
                             exe=proc.executable(),
                             cpu_total_time=cpu, cpu_time_delta=cpu)
            self._classify(proc, cached)
            self._proc_cache[pid] = cached
            return cached
        delta = max(cpu - cached.cpu_total_time, 0.0)
        cached.cpu_time_delta = delta
        cached.cpu_total_time = cpu
        if delta > _RECLASSIFY_EPSILON:
            # cheap refresh of mutable identity (comm changes on exec);
            # classification itself is cached — the cgroup/environ/cmdline
            # reads run once per PID, not per tick
            try:
                cached.comm = proc.comm()
            except OSError:
                pass
            if not cached.classified:
                self._classify(proc, cached)
        return cached

    def _classify(self, proc: ProcInfo, entry: Process) -> None:
        """Container-vs-VM detection (reference computeTypeInfoFromProc :560
        fans the two regex passes to two goroutines; both are sub-µs host
        work here)."""
        entry.container = container_info_from_proc(proc)
        if entry.container is None:
            entry.virtual_machine = vm_info_from_proc(proc)
        entry.classified = True

    def _refresh_containers(self) -> None:
        running: dict[str, Container] = {}
        for p in self._processes.running.values():
            if p.container is None:
                continue
            cid = p.container.id
            entry = running.get(cid)
            if entry is None:
                cached = self._container_cache.get(cid)
                if cached is None:
                    cached = p.container.clone()
                    cached.cpu_total_time = 0.0
                    self._container_cache[cid] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[cid] = entry
            # hierarchical delta rollup (reference updateContainerCache :469)
            entry.cpu_time_delta += p.cpu_time_delta
            entry.cpu_total_time += p.cpu_time_delta
        terminated = {
            cid: c
            for cid, c in self._container_cache.items()
            if cid not in running
        }
        for cid in terminated:
            del self._container_cache[cid]
        self._containers = Containers(running=running, terminated=terminated)

    def _refresh_vms(self) -> None:
        running: dict[str, VirtualMachine] = {}
        for p in self._processes.running.values():
            if p.virtual_machine is None:
                continue
            vid = p.virtual_machine.id
            entry = running.get(vid)
            if entry is None:
                cached = self._vm_cache.get(vid)
                if cached is None:
                    cached = p.virtual_machine.clone()
                    cached.cpu_total_time = 0.0
                    self._vm_cache[vid] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[vid] = entry
            entry.cpu_time_delta += p.cpu_time_delta
            entry.cpu_total_time += p.cpu_time_delta
        terminated = {
            vid: v for vid, v in self._vm_cache.items() if vid not in running
        }
        for vid in terminated:
            del self._vm_cache[vid]
        self._vms = VirtualMachines(running=running, terminated=terminated)

    def _refresh_pods(self) -> None:
        running: dict[str, Pod] = {}
        no_pod: list[str] = []
        for c in self._containers.running.values():
            info = None
            if self._pod_lookup is not None:
                info = self._pod_lookup.lookup_by_container_id(c.id)
            if info is None:
                c.pod_id = None
                no_pod.append(c.id)
                continue
            pod_id, pod_name, namespace, container_name = info
            c.pod_id = pod_id
            if container_name and (not c.name or c.name == c.id[:12]):
                c.name = container_name
            entry = running.get(pod_id)
            if entry is None:
                cached = self._pod_cache.get(pod_id)
                if cached is None:
                    cached = Pod(id=pod_id, name=pod_name, namespace=namespace)
                self._pod_cache[pod_id] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[pod_id] = entry
            entry.cpu_time_delta += c.cpu_time_delta
            entry.cpu_total_time += c.cpu_time_delta
        terminated = {
            pid_: p for pid_, p in self._pod_cache.items() if pid_ not in running
        }
        for pid_ in terminated:
            del self._pod_cache[pid_]
        self._pods = Pods(running=running, terminated=terminated,
                          containers_no_pod=no_pod)

    def _refresh_node(self) -> None:
        # running processes only: a terminated process's delta was already
        # attributed in the window it ran (reference informer.go:328-345);
        # re-adding it would deflate every running workload's ratio and
        # break Σ workload == node active conservation
        total_delta = sum(
            p.cpu_time_delta for p in self._processes.running.values()
        )
        self._node = Node(
            cpu_usage_ratio=self._fs.cpu_usage_ratio(),
            process_total_cpu_time_delta=total_delta,
        )

    # -- feature batch (TPU-first output) ---------------------------------

    def feature_batch(self) -> FeatureBatch:
        """Dense columns over all running workloads, in kind-major order."""
        kinds: list[int] = []
        ids: list[str] = []
        deltas: list[float] = []

        def extend(kind: int, items: Mapping, key=str) -> None:
            for k, wl in items.items():
                kinds.append(kind)
                ids.append(key(k))
                deltas.append(wl.cpu_time_delta)

        extend(FeatureBatch.KIND_PROCESS, self._processes.running)
        extend(FeatureBatch.KIND_CONTAINER, self._containers.running)
        extend(FeatureBatch.KIND_VM, self._vms.running)
        extend(FeatureBatch.KIND_POD, self._pods.running)
        return FeatureBatch(
            kinds=np.asarray(kinds, dtype=np.int8),
            ids=ids,
            cpu_deltas=np.asarray(deltas, dtype=np.float32),
            node_cpu_delta=float(self._node.process_total_cpu_time_delta),
            usage_ratio=float(self._node.cpu_usage_ratio),
        )
