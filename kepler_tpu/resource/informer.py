"""Resource informer: per-interval workload discovery and delta accounting.

Reference parity: ``internal/resource/informer.go`` — scan all PIDs each
refresh; cache processes/containers/VMs/pods keyed by PID/ID; compute
CPU-time deltas vs cache; classify processes as container/VM (classification
cached; re-done only when a process's CPU delta is non-negligible,
``populateProcessFields`` :512); aggregate deltas hierarchically
proc → container → pod; detect terminated entities by set difference
(:167-221); compute node totals + usage ratio (:328-345).

TPU-first pivot: besides the object views (``processes()`` etc., same shape
as the reference API :49-66), every refresh also materializes a
``FeatureBatch`` — dense numpy columns (cpu_time_delta per workload, stable
row ids) that feed the jitted attribution kernel without per-object Python
iteration (SURVEY §2 row 10 "representational pivot").
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Mapping, Protocol, Sequence

import numpy as np

from kepler_tpu.resource.container import container_info_from_proc
from kepler_tpu.resource.procfs import ProcFSReader, ProcInfo, ProcReader
from kepler_tpu.resource.types import (
    Container,
    Node,
    Pod,
    Process,
    VirtualMachine,
)
from kepler_tpu.resource.vm import vm_info_from_proc

log = logging.getLogger("kepler.resource")

# Δcpu below this (seconds) skips re-classification (reference :512-558 —
# idle processes don't pay the cgroup/environ re-read).
_RECLASSIFY_EPSILON = 1e-9


class PodLookup(Protocol):
    """Pod-metadata join point (reference pod.Informer.LookupByContainerID)."""

    def lookup_by_container_id(
        self, container_id: str
    ) -> tuple[str, str, str, str] | None:
        """→ (pod_id, pod_name, namespace, container_name) or None."""
        ...


@dataclass
class Processes:
    running: dict[int, Process] = field(default_factory=dict)
    terminated: dict[int, Process] = field(default_factory=dict)


@dataclass
class Containers:
    running: dict[str, Container] = field(default_factory=dict)
    terminated: dict[str, Container] = field(default_factory=dict)


@dataclass
class VirtualMachines:
    running: dict[str, VirtualMachine] = field(default_factory=dict)
    terminated: dict[str, VirtualMachine] = field(default_factory=dict)


@dataclass
class Pods:
    running: dict[str, Pod] = field(default_factory=dict)
    terminated: dict[str, Pod] = field(default_factory=dict)
    containers_no_pod: list[str] = field(default_factory=list)


@dataclass
class FeatureBatch:
    """Dense per-workload feature columns for one refresh window.

    Row order is stable for the lifetime of a workload (rows are appended on
    first sight and compacted on termination), so downstream per-row energy
    accumulators can be gathered/scattered by index on device. Rows are
    kind-major: all processes, then containers, then VMs, then pods
    (``kind_offsets`` marks the boundaries).
    """

    kinds: np.ndarray  # int8 [W]: 0=process 1=container 2=vm 3=pod
    ids: list[str]  # [W] workload ids (str(pid) for processes)
    cpu_deltas: np.ndarray  # f32 [W] seconds
    node_cpu_delta: float  # Σ process deltas (attribution denominator)
    usage_ratio: float  # node active/total CPU ratio
    # cumulative CPU seconds per row (f64; the process rows back
    # kepler_process_cpu_seconds_total). Optional: wire payloads omit it.
    cpu_totals: np.ndarray | None = None
    # kind-major boundaries: (0, P, P+C, P+C+V, W). Optional convenience;
    # derivable from ``kinds``.
    kind_offsets: tuple[int, int, int, int, int] | None = None

    KIND_PROCESS = 0
    KIND_CONTAINER = 1
    KIND_VM = 2
    KIND_POD = 3


class _ArrayState:
    """Row-aligned numpy state for the batched (native-scan) tick path.

    The authoritative per-PID numbers live in arrays; ``Process`` objects
    are the metadata view, touched only for rows whose numbers changed.
    Group indices (proc row → container/VM slot) turn the hierarchical
    delta rollups into ``np.bincount`` calls.
    """

    __slots__ = ("pids", "cpu", "deltas", "active", "procs", "running",
                 "pid_rows", "ids", "cont_idx", "vm_idx", "cont_slots",
                 "cont_rows", "cont_members", "cont_delta", "cont_total",
                 "cont_ids", "cont_running", "vm_slots", "vm_rows",
                 "vm_members", "vm_delta", "vm_total", "vm_ids",
                 "vm_running", "kinds", "kind_offsets")

    def __init__(self) -> None:
        self.pids = np.zeros(0, np.int32)  # [P] row-aligned scan order
        self.cpu = np.zeros(0, np.float64)  # [P] cumulative seconds
        self.deltas = np.zeros(0, np.float64)  # [P] this window
        self.active = np.zeros(0, bool)  # [P] delta > eps last window
        self.procs: list[Process] = []  # [P]
        self.running: dict[int, Process] = {}
        self.pid_rows: dict[int, int] = {}
        self.ids: list[str] = []  # [P] str(pid), cached
        # container grouping
        self.cont_idx = np.zeros(0, np.int32)  # [P] row → slot | -1
        self.cont_slots: list[Container] = []
        self.cont_rows: dict[str, int] = {}
        self.cont_members = np.zeros(0, np.int64)  # [C] live member procs
        self.cont_delta = np.zeros(0, np.float64)  # [C] this window
        self.cont_total = np.zeros(0, np.float64)  # [C] Σ deltas
        self.cont_ids: list[str] = []
        self.cont_running: dict[str, Container] = {}
        # VM grouping
        self.vm_idx = np.zeros(0, np.int32)
        self.vm_slots: list[VirtualMachine] = []
        self.vm_rows: dict[str, int] = {}
        self.vm_members = np.zeros(0, np.int64)
        self.vm_delta = np.zeros(0, np.float64)
        self.vm_total = np.zeros(0, np.float64)
        self.vm_ids: list[str] = []
        self.vm_running: dict[str, VirtualMachine] = {}
        # cached kind-major arrays for feature_batch (rebuilt on
        # membership change)
        self.kinds: np.ndarray | None = None
        self.kind_offsets: tuple[int, int, int, int, int] | None = None


class ResourceInformer:
    def __init__(
        self,
        reader: ProcReader | None = None,
        procfs_path: str = "/proc",
        pod_lookup: PodLookup | None = None,
        clock: Callable[[], float] | None = None,
    ) -> None:
        import time as _time

        self._fs: ProcReader = reader or ProcFSReader(procfs_path)
        self._pod_lookup = pod_lookup
        self._clock = clock or _time.time
        self._node = Node()
        self._proc_cache: dict[int, Process] = {}
        self._container_cache: dict[str, Container] = {}
        self._vm_cache: dict[str, VirtualMachine] = {}
        self._pod_cache: dict[str, Pod] = {}
        self._processes = Processes()
        self._containers = Containers()
        self._vms = VirtualMachines()
        self._pods = Pods()
        self._last_scan: float | None = None
        self._arr: _ArrayState | None = None
        # bumped whenever any workload's exporter labels may have changed
        # (comm/exec, classification, pod binding, membership) — lets the
        # monitor cache its per-kind meta tuples between ticks
        self.meta_gen = 0

    def name(self) -> str:
        return "resource-informer"

    def init(self) -> None:
        """Probe the proc reader once (reference Init :155)."""
        list(self._fs.all_procs())

    # -- accessors (reference informer.go:49-66) --------------------------

    def node(self) -> Node:
        return self._node

    def processes(self) -> Processes:
        return self._processes

    def containers(self) -> Containers:
        return self._containers

    def virtual_machines(self) -> VirtualMachines:
        return self._vms

    def pods(self) -> Pods:
        return self._pods

    # -- refresh ----------------------------------------------------------

    # keplint: role-boundary — reading /proc IS this component's
    # measurement seam (the meter analog); it keeps its own I/O budget
    # contract rather than inheriting the hot-loop blocking ban
    def refresh(self) -> None:
        """One full scan: processes first, then container/VM/pod rollups and
        node totals (reference Refresh :349-410 runs the rollups in three
        goroutines; they are independent dict walks, sequential here — the
        scan itself dominates)."""
        self._refresh_processes()
        self._refresh_containers()
        self._refresh_vms()
        self._refresh_pods()
        self._refresh_node()
        self._last_scan = self._clock()

    def _refresh_processes(self) -> None:
        scan = getattr(self._fs, "scan_arrays", None)
        if scan is not None:
            pids, cpus, comms = scan()
            self._refresh_from_arrays(
                np.ascontiguousarray(pids, np.int32),
                np.ascontiguousarray(cpus, np.float64),
                np.asarray(comms) if comms is not None else None)
            return
        self._arr = None
        running = {}
        for proc in self._fs.all_procs():
            try:
                entry = self._update_process_cache(proc)
            except OSError:
                continue  # PID vanished mid-scan (reference :186-190)
            except (ValueError, IndexError):
                continue  # truncated/garbage stat line mid-exit
            running[entry.pid] = entry
        terminated = {
            pid: p for pid, p in self._proc_cache.items() if pid not in running
        }
        for pid in terminated:
            del self._proc_cache[pid]
        self._processes = Processes(running=running, terminated=terminated)

    # -- batched (array) tick path ----------------------------------------

    def _refresh_from_arrays(self, pids: np.ndarray, cpus: np.ndarray,
                             comms: np.ndarray | None) -> None:
        """Tick path for readers with a batched scan (``scan_arrays``):
        the per-PID numbers live in row-aligned arrays and the steady
        state is pure numpy — ``Process`` objects are touched only for
        rows whose delta changed (comm updates come free from the scan's
        stat parse). Membership changes fall into :meth:`_rebuild_rows`,
        which batch-classifies all first-sight PIDs in one threaded
        native read."""
        st = self._arr
        if (st is None or len(st.pids) != len(pids)
                or not np.array_equal(st.pids, pids)):
            self._rebuild_rows(pids, cpus, comms)
            return
        deltas = cpus - st.cpu
        # counter REGRESSION (pid reuse): the clamp hides the drop from
        # the delta, but the object's total must still follow the
        # kernel's current value — the exporter renders
        # process_cpu_seconds_total from the object view, and the legacy
        # path refreshes it every tick (parity pinned by the dual-path
        # fuzz in tests/test_resource.py, which caught this diverging)
        regressed = np.flatnonzero(deltas < 0.0)
        np.maximum(deltas, 0.0, out=deltas)
        active = deltas > _RECLASSIFY_EPSILON
        changed = np.flatnonzero(active)
        went_idle = np.flatnonzero(st.active & ~active)
        self._touch_changed(st.procs, changed.tolist(), deltas, cpus, comms,
                            pids)
        procs = st.procs
        for i in regressed.tolist():
            procs[i].cpu_time_delta = 0.0
            procs[i].cpu_total_time = float(cpus[i])
        for i in went_idle.tolist():
            procs[i].cpu_time_delta = 0.0
        st.cpu = cpus
        st.deltas = deltas
        st.active = active
        self._processes = Processes(running=st.running, terminated={})
        self._proc_cache = st.running

    def _touch_changed(self, procs: list[Process], rows: list[int],
                       deltas: np.ndarray, cpus: np.ndarray,
                       comms: np.ndarray | None, pids: np.ndarray) -> None:
        """Write numbers (and any changed comm) onto the object views of
        rows whose CPU delta is nonzero this tick."""
        if comms is not None:
            for i in rows:
                p = procs[i]
                p.cpu_time_delta = float(deltas[i])
                p.cpu_total_time = float(cpus[i])
                cb = comms[i]
                if cb != p.comm_raw:
                    # exec changes comm; the label block must re-render
                    p.comm_raw = cb
                    p.comm = cb.decode("utf-8", "replace")
                    p.meta_cache = None
                    self.meta_gen += 1
            return
        proc_info = getattr(self._fs, "proc_info", None)
        for i in rows:
            p = procs[i]
            p.cpu_time_delta = float(deltas[i])
            p.cpu_total_time = float(cpus[i])
            if proc_info is None:
                continue
            try:
                new_comm = proc_info(int(pids[i])).comm()
            except (OSError, ValueError, IndexError):
                continue  # mid-exit garbage: keep cached identity
            if new_comm != p.comm:
                p.comm = new_comm
                p.meta_cache = None
                self.meta_gen += 1

    def _rebuild_rows(self, pids: np.ndarray, cpus: np.ndarray,
                      comms: np.ndarray | None) -> None:
        """Membership/order changed: re-align row state, batch-classify
        first-sight PIDs, detect terminated ones, rebuild group indices."""
        st_old = self._arr
        n = len(pids)
        pid_list = pids.tolist()
        old_rows = st_old.pid_rows if st_old is not None else {}
        old_procs = st_old.procs if st_old is not None else []
        get = old_rows.get
        prev_row = [get(p, -1) for p in pid_list]
        prev_row_np = np.asarray(prev_row, np.int64) if n else np.zeros(
            0, np.int64)
        known = prev_row_np >= 0
        procs: list[Process | None] = [None] * n
        new_idx: list[int] = []
        for i, r in enumerate(prev_row):
            if r >= 0:
                procs[i] = old_procs[r]
            else:
                new_idx.append(i)
        created = self._create_processes_batch(
            [pid_list[i] for i in new_idx],
            ([comms[i] for i in new_idx] if comms is not None
             else [None] * len(new_idx)),
            [float(cpus[i]) for i in new_idx])
        keep = np.ones(n, bool)
        for i, obj in zip(new_idx, created):
            if obj is None:
                keep[i] = False  # vanished between scan and classify
            else:
                procs[i] = obj
        # deltas: first sight counts its whole total as this window's
        # delta (legacy/reference semantics); known rows diff the cache
        deltas = cpus.copy()
        regressed = np.zeros(n, bool)
        if st_old is not None:
            kr = prev_row_np[known]
            raw = cpus[known] - st_old.cpu[kr]
            deltas[known] = np.maximum(raw, 0.0)
            regressed[known] = raw < 0.0
        active = deltas > _RECLASSIFY_EPSILON
        self._touch_changed(procs, np.flatnonzero(known & active).tolist(),
                            deltas, cpus, comms, pids)
        # counter regression (pid reuse): totals follow the kernel even
        # though the clamped delta is 0 — see _refresh_from_arrays
        for i in np.flatnonzero(regressed).tolist():
            procs[i].cpu_time_delta = 0.0
            procs[i].cpu_total_time = float(cpus[i])
        if st_old is not None:
            was_active = np.zeros(n, bool)
            was_active[known] = st_old.active[prev_row_np[known]]
            for i in np.flatnonzero(was_active & ~active).tolist():
                procs[i].cpu_time_delta = 0.0
        # terminated = old rows never matched by the new scan
        seen = np.zeros(len(old_procs), bool)
        if st_old is not None:
            seen[prev_row_np[known]] = True
        terminated = {pid: old_procs[r] for pid, r in old_rows.items()
                      if not seen[r]}
        if not bool(keep.all()):
            sel = np.flatnonzero(keep)
            pids = pids[sel]
            cpus = cpus[sel]
            deltas = deltas[sel]
            active = active[sel]
            procs = [procs[i] for i in sel.tolist()]
            pid_list = pids.tolist()
        st = _ArrayState()
        st.pids = pids
        st.cpu = cpus
        st.deltas = deltas
        st.active = active
        st.procs = procs  # type: ignore[assignment]
        st.running = dict(zip(pid_list, procs))
        st.pid_rows = {pid: i for i, pid in enumerate(pid_list)}
        st.ids = list(map(str, pid_list))
        self._build_groups(st, st_old)
        self._arr = st
        self.meta_gen += 1  # membership changed
        self._processes = Processes(running=st.running,
                                    terminated=terminated)
        self._proc_cache = st.running

    def _create_processes_batch(
            self, pids: list[int], comms: list, cpus: list[float]
    ) -> list[Process | None]:
        """Create+classify first-sight processes. With a native reader the
        cgroup/cmdline/environ/exe reads for ALL new PIDs happen in a few
        threaded C calls (chunked to bound transient memory), so churn
        bursts — a mass pod reschedule — stay off the per-file Python
        path. None entries mark PIDs that vanished before classification."""
        out: list[Process | None] = [None] * len(pids)
        if not pids:
            return out
        read_files = getattr(self._fs, "read_proc_files", None)
        read_links = getattr(self._fs, "read_proc_links", None)
        if read_files is None or read_links is None:
            proc_info = self._fs.proc_info
            for j, pid in enumerate(pids):
                try:
                    info = proc_info(pid)
                    comm_b = comms[j]
                    comm = (comm_b.decode("utf-8", "replace")
                            if comm_b else info.comm())
                    p = Process(pid=pid, comm=comm, exe=info.executable(),
                                cpu_total_time=cpus[j],
                                cpu_time_delta=cpus[j],
                                comm_raw=comm_b or b"")
                    self._classify(info, p)
                except (OSError, ValueError, IndexError):
                    continue  # vanished mid-scan / mid-exit garbage
                out[j] = p
            return out
        chunk = 512  # bounds transient content buffers (~24 MB/chunk)
        for lo in range(0, len(pids), chunk):
            hi = min(lo + chunk, len(pids))
            batch = pids[lo:hi]
            rels = ([f"{pid}/cgroup" for pid in batch]
                    + [f"{pid}/cmdline" for pid in batch]
                    + [f"{pid}/environ" for pid in batch])
            try:
                contents = read_files(rels)
                exes = read_links([f"{pid}/exe" for pid in batch])
            except OSError:
                contents = [None] * (3 * len(batch))
                exes = [None] * len(batch)
            k = len(batch)
            for j, pid in enumerate(batch):
                cg, cmd, env_raw = (contents[j], contents[k + j],
                                    contents[2 * k + j])
                # a content that exactly fills its slot was truncated
                # (kubelet-injected environs and java classpaths routinely
                # exceed any fixed cap) — re-read that file unbatched so
                # the container-name labels never depend on which reader
                # path classified the workload
                cmd = self._reread_if_truncated(pid, "cmdline", cmd)
                env_raw = self._reread_if_truncated(pid, "environ", env_raw)
                cg = self._reread_if_truncated(pid, "cgroup", cg)
                exe = exes[j]
                if cg is None and cmd is None and env_raw is None \
                        and exe is None:
                    continue  # vanished between scan and classification
                try:
                    out[lo + j] = self._process_from_contents(
                        pid, comms[lo + j], cpus[lo + j], cg, cmd, env_raw,
                        exe)
                except (ValueError, IndexError):
                    continue  # truncated/garbage content mid-exit
        return out

    # fallback slot size when the reader doesn't expose its own cap; a
    # content of exactly cap-1 bytes means ReadSmallFile hit the slot end
    _BATCH_FILE_CAP = 16384

    def _reread_if_truncated(self, pid: int, name: str,
                             content: bytes | None) -> bytes | None:
        # derive the threshold from the READER's actual cap so a changed
        # per_cap default can't silently disable truncation detection
        cap = getattr(self._fs, "batch_read_cap", self._BATCH_FILE_CAP)
        if content is None or len(content) < cap - 1:
            return content
        procfs = getattr(self._fs, "_procfs", "/proc")
        try:
            with open(f"{procfs}/{pid}/{name}", "rb") as f:
                return f.read()
        except OSError:
            return content

    def _process_from_contents(self, pid: int, comm_b, cpu: float,
                               cg: bytes | None, cmd: bytes | None,
                               env_raw: bytes | None,
                               exe: str | None) -> Process:
        from kepler_tpu.resource.container import (
            container_info_from_cgroup_paths, container_name)
        from kepler_tpu.resource.procfs import (parse_cgroup_text,
                                                parse_cmdline_bytes,
                                                parse_environ_bytes)
        from kepler_tpu.resource.types import Container
        from kepler_tpu.resource.vm import vm_info_from_cmdline

        paths = (parse_cgroup_text(cg.decode("utf-8", "replace"))
                 if cg else [])
        cmdline = parse_cmdline_bytes(cmd) if cmd else []
        container = vm = None
        if paths:
            runtime, cid = container_info_from_cgroup_paths(paths)
            if cid:
                env = parse_environ_bytes(env_raw) if env_raw else {}
                container = Container(
                    id=cid, name=container_name(env, cmdline, cid),
                    runtime=runtime)
        if container is None:
            vm = vm_info_from_cmdline(cmdline)
        comm_b = comm_b or b""
        return Process(pid=pid, comm=comm_b.decode("utf-8", "replace"),
                       exe=exe or "", cpu_total_time=cpu,
                       cpu_time_delta=cpu, container=container,
                       virtual_machine=vm, classified=True,
                       comm_raw=comm_b)

    def _build_groups(self, st: _ArrayState,
                      st_old: _ArrayState | None) -> None:
        """Container/VM slot tables + per-row group indices. Slots carry
        the accumulated totals forward from the previous state (the array
        analog of the legacy ``_container_cache``); slots whose ids vanish
        are recorded as terminated by the rollup refreshes."""
        n = len(st.procs)
        cont_idx = np.full(n, -1, np.int32)
        vm_idx = np.full(n, -1, np.int32)
        old_cont = st_old.cont_rows if st_old is not None else {}
        old_vm = st_old.vm_rows if st_old is not None else {}
        for i, p in enumerate(st.procs):
            c = p.container
            if c is not None:
                slot = st.cont_rows.get(c.id)
                if slot is None:
                    slot = len(st.cont_slots)
                    old = old_cont.get(c.id)
                    if old is not None:
                        entry = st_old.cont_slots[old]  # carries totals
                    else:
                        entry = c.clone()
                        entry.cpu_total_time = 0.0
                        entry.cpu_time_delta = 0.0
                        entry.meta_cache = None
                    st.cont_rows[c.id] = slot
                    st.cont_slots.append(entry)
                cont_idx[i] = slot
                continue
            v = p.virtual_machine
            if v is not None:
                slot = st.vm_rows.get(v.id)
                if slot is None:
                    slot = len(st.vm_slots)
                    old = old_vm.get(v.id)
                    if old is not None:
                        entry = st_old.vm_slots[old]
                    else:
                        entry = v.clone()
                        entry.cpu_total_time = 0.0
                        entry.cpu_time_delta = 0.0
                        entry.meta_cache = None
                    st.vm_rows[v.id] = slot
                    st.vm_slots.append(entry)
                vm_idx[i] = slot
        st.cont_idx = cont_idx
        st.vm_idx = vm_idx
        c_n = len(st.cont_slots)
        v_n = len(st.vm_slots)
        st.cont_members = np.bincount(cont_idx[cont_idx >= 0],
                                      minlength=c_n).astype(np.int64)
        st.vm_members = np.bincount(vm_idx[vm_idx >= 0],
                                    minlength=v_n).astype(np.int64)
        st.cont_delta = np.array(
            [st_old.cont_delta[old_cont[c.id]]
             if st_old is not None and c.id in old_cont else 0.0
             for c in st.cont_slots])
        st.cont_total = np.array([c.cpu_total_time for c in st.cont_slots])
        st.vm_delta = np.array(
            [st_old.vm_delta[old_vm[v.id]]
             if st_old is not None and v.id in old_vm else 0.0
             for v in st.vm_slots])
        st.vm_total = np.array([v.cpu_total_time for v in st.vm_slots])
        st.cont_ids = [c.id for c in st.cont_slots]
        st.vm_ids = [v.id for v in st.vm_slots]
        st.cont_running = dict(zip(st.cont_ids, st.cont_slots))
        st.vm_running = dict(zip(st.vm_ids, st.vm_slots))
        st.kinds = None  # feature_batch rebuilds its cached prefix

    def _update_process_cache(self, proc: ProcInfo) -> Process:
        pid = proc.pid()
        cpu = proc.cpu_time()
        cached = self._proc_cache.get(pid)
        if cached is None:
            cached = Process(pid=pid, comm=proc.comm(),
                             exe=proc.executable(),
                             cpu_total_time=cpu, cpu_time_delta=cpu)
            self._classify(proc, cached)
            self._proc_cache[pid] = cached
            return cached
        delta = max(cpu - cached.cpu_total_time, 0.0)
        cached.cpu_time_delta = delta
        cached.cpu_total_time = cpu
        if delta > _RECLASSIFY_EPSILON:
            # cheap refresh of mutable identity (comm changes on exec);
            # classification itself is cached — the cgroup/environ/cmdline
            # reads run once per PID, not per tick
            try:
                new_comm = proc.comm()
            except OSError:
                new_comm = cached.comm
            if new_comm != cached.comm:
                cached.comm = new_comm
                cached.meta_cache = None
                self.meta_gen += 1
            if not cached.classified:
                self._classify(proc, cached)
        return cached

    def _classify(self, proc: ProcInfo, entry: Process) -> None:
        """Container-vs-VM detection (reference computeTypeInfoFromProc :560
        fans the two regex passes to two goroutines; both are sub-µs host
        work here)."""
        entry.container = container_info_from_proc(proc)
        if entry.container is None:
            entry.virtual_machine = vm_info_from_proc(proc)
        entry.classified = True

    def _refresh_containers(self) -> None:
        st = self._arr
        if st is not None:
            # vectorized rollup: one bincount over the proc rows; objects
            # are touched only where this or last window's delta ≠ 0
            c_n = len(st.cont_slots)
            if c_n:
                mask = st.cont_idx >= 0
                cd = np.bincount(st.cont_idx[mask],
                                 weights=st.deltas[mask], minlength=c_n)
            else:
                cd = np.zeros(0)
            st.cont_total = st.cont_total + cd
            for i in np.flatnonzero((cd > 0) | (st.cont_delta > 0)).tolist():
                c = st.cont_slots[i]
                c.cpu_time_delta = float(cd[i])
                c.cpu_total_time = float(st.cont_total[i])
            st.cont_delta = cd
            terminated = {
                cid: c for cid, c in self._container_cache.items()
                if cid not in st.cont_running
            }
            self._container_cache = st.cont_running
            self._containers = Containers(running=st.cont_running,
                                          terminated=terminated)
            return
        running: dict[str, Container] = {}
        for p in self._processes.running.values():
            if p.container is None:
                continue
            cid = p.container.id
            entry = running.get(cid)
            if entry is None:
                cached = self._container_cache.get(cid)
                if cached is None:
                    cached = p.container.clone()
                    cached.cpu_total_time = 0.0
                    self._container_cache[cid] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[cid] = entry
            # hierarchical delta rollup (reference updateContainerCache :469)
            entry.cpu_time_delta += p.cpu_time_delta
            entry.cpu_total_time += p.cpu_time_delta
        terminated = {
            cid: c
            for cid, c in self._container_cache.items()
            if cid not in running
        }
        for cid in terminated:
            del self._container_cache[cid]
        self._containers = Containers(running=running, terminated=terminated)

    def _refresh_vms(self) -> None:
        st = self._arr
        if st is not None:
            v_n = len(st.vm_slots)
            if v_n:
                mask = st.vm_idx >= 0
                vd = np.bincount(st.vm_idx[mask],
                                 weights=st.deltas[mask], minlength=v_n)
            else:
                vd = np.zeros(0)
            st.vm_total = st.vm_total + vd
            for i in np.flatnonzero((vd > 0) | (st.vm_delta > 0)).tolist():
                v = st.vm_slots[i]
                v.cpu_time_delta = float(vd[i])
                v.cpu_total_time = float(st.vm_total[i])
            st.vm_delta = vd
            terminated = {
                vid: v for vid, v in self._vm_cache.items()
                if vid not in st.vm_running
            }
            self._vm_cache = st.vm_running
            self._vms = VirtualMachines(running=st.vm_running,
                                        terminated=terminated)
            return
        running: dict[str, VirtualMachine] = {}
        for p in self._processes.running.values():
            if p.virtual_machine is None:
                continue
            vid = p.virtual_machine.id
            entry = running.get(vid)
            if entry is None:
                cached = self._vm_cache.get(vid)
                if cached is None:
                    cached = p.virtual_machine.clone()
                    cached.cpu_total_time = 0.0
                    self._vm_cache[vid] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[vid] = entry
            entry.cpu_time_delta += p.cpu_time_delta
            entry.cpu_total_time += p.cpu_time_delta
        terminated = {
            vid: v for vid, v in self._vm_cache.items() if vid not in running
        }
        for vid in terminated:
            del self._vm_cache[vid]
        self._vms = VirtualMachines(running=running, terminated=terminated)

    def _refresh_pods(self) -> None:
        running: dict[str, Pod] = {}
        no_pod: list[str] = []
        for c in self._containers.running.values():
            info = None
            if self._pod_lookup is not None:
                info = self._pod_lookup.lookup_by_container_id(c.id)
            if info is None:
                if c.pod_id is not None:
                    c.pod_id = None
                    c.meta_cache = None
                    self.meta_gen += 1
                no_pod.append(c.id)
                continue
            pod_id, pod_name, namespace, container_name = info
            if c.pod_id != pod_id:
                c.pod_id = pod_id
                c.meta_cache = None
                self.meta_gen += 1
            if container_name and (not c.name or c.name == c.id[:12]):
                if c.name != container_name:
                    c.name = container_name
                    c.meta_cache = None
                    self.meta_gen += 1
            entry = running.get(pod_id)
            if entry is None:
                cached = self._pod_cache.get(pod_id)
                if cached is None:
                    cached = Pod(id=pod_id, name=pod_name, namespace=namespace)
                self._pod_cache[pod_id] = cached
                entry = cached
                entry.cpu_time_delta = 0.0
                running[pod_id] = entry
            entry.cpu_time_delta += c.cpu_time_delta
            entry.cpu_total_time += c.cpu_time_delta
        terminated = {
            pid_: p for pid_, p in self._pod_cache.items() if pid_ not in running
        }
        for pid_ in terminated:
            del self._pod_cache[pid_]
        self._pods = Pods(running=running, terminated=terminated,
                          containers_no_pod=no_pod)

    def _refresh_node(self) -> None:
        # running processes only: a terminated process's delta was already
        # attributed in the window it ran (reference informer.go:328-345);
        # re-adding it would deflate every running workload's ratio and
        # break Σ workload == node active conservation
        st = self._arr
        if st is not None:
            total_delta = float(st.deltas.sum())
        else:
            total_delta = sum(
                p.cpu_time_delta for p in self._processes.running.values()
            )
        self._node = Node(
            cpu_usage_ratio=self._fs.cpu_usage_ratio(),
            process_total_cpu_time_delta=total_delta,
        )

    # -- feature batch (TPU-first output) ---------------------------------

    def feature_batch(self) -> FeatureBatch:
        """Dense columns over all running workloads, in kind-major order."""
        st = self._arr
        if st is not None:
            p_n, c_n, v_n = len(st.ids), len(st.cont_ids), len(st.vm_ids)
            pod_ids = list(self._pods.running)
            pod_objs = self._pods.running.values()
            pod_deltas = np.fromiter(
                (p.cpu_time_delta for p in pod_objs), np.float64,
                len(pod_ids))
            pod_totals = np.fromiter(
                (p.cpu_total_time for p in self._pods.running.values()),
                np.float64, len(pod_ids))
            if st.kinds is None or st.kind_offsets[4] != (
                    p_n + c_n + v_n + len(pod_ids)):
                st.kind_offsets = (0, p_n, p_n + c_n, p_n + c_n + v_n,
                                   p_n + c_n + v_n + len(pod_ids))
                st.kinds = np.repeat(
                    np.arange(4, dtype=np.int8),
                    [p_n, c_n, v_n, len(pod_ids)])
            return FeatureBatch(
                kinds=st.kinds,
                ids=st.ids + st.cont_ids + st.vm_ids + pod_ids,
                cpu_deltas=np.concatenate(
                    [st.deltas, st.cont_delta, st.vm_delta,
                     pod_deltas]).astype(np.float32),
                node_cpu_delta=float(
                    self._node.process_total_cpu_time_delta),
                usage_ratio=float(self._node.cpu_usage_ratio),
                cpu_totals=np.concatenate(
                    [st.cpu, st.cont_total, st.vm_total, pod_totals]),
                kind_offsets=st.kind_offsets,
            )
        kinds: list[int] = []
        ids: list[str] = []
        deltas: list[float] = []
        totals: list[float] = []

        def extend(kind: int, items: Mapping, key=str) -> None:
            for k, wl in items.items():
                kinds.append(kind)
                ids.append(key(k))
                deltas.append(wl.cpu_time_delta)
                totals.append(wl.cpu_total_time)

        extend(FeatureBatch.KIND_PROCESS, self._processes.running)
        extend(FeatureBatch.KIND_CONTAINER, self._containers.running)
        extend(FeatureBatch.KIND_VM, self._vms.running)
        extend(FeatureBatch.KIND_POD, self._pods.running)
        p_n = len(self._processes.running)
        c_n = len(self._containers.running)
        v_n = len(self._vms.running)
        return FeatureBatch(
            kinds=np.asarray(kinds, dtype=np.int8),
            ids=ids,
            cpu_deltas=np.asarray(deltas, dtype=np.float32),
            node_cpu_delta=float(self._node.process_total_cpu_time_delta),
            usage_ratio=float(self._node.cpu_usage_ratio),
            cpu_totals=np.asarray(totals, dtype=np.float64),
            kind_offsets=(0, p_n, p_n + c_n, p_n + c_n + v_n, len(ids)),
        )
