"""Resource layer: workload discovery from procfs (reference
``internal/resource/``)."""

from kepler_tpu.resource.container import (
    container_info_from_cgroup_paths,
    container_info_from_proc,
)
from kepler_tpu.resource.informer import (
    Containers,
    FeatureBatch,
    Pods,
    Processes,
    ResourceInformer,
    VirtualMachines,
)
from kepler_tpu.resource.fast_procfs import (
    FastProcFSReader,
    make_proc_reader,
)
from kepler_tpu.resource.procfs import ProcFSReader, ProcInfo, ProcReader
from kepler_tpu.resource.types import (
    Container,
    ContainerRuntime,
    Hypervisor,
    Node,
    Pod,
    Process,
    VirtualMachine,
)
from kepler_tpu.resource.vm import vm_info_from_proc

__all__ = [
    "Container",
    "ContainerRuntime",
    "Containers",
    "FastProcFSReader",
    "FeatureBatch",
    "Hypervisor",
    "Node",
    "Pod",
    "Pods",
    "ProcFSReader",
    "ProcInfo",
    "ProcReader",
    "Process",
    "Processes",
    "ResourceInformer",
    "VirtualMachine",
    "VirtualMachines",
    "container_info_from_cgroup_paths",
    "container_info_from_proc",
    "make_proc_reader",
    "vm_info_from_proc",
]
