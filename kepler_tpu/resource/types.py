"""Workload types.

Reference parity: ``internal/resource/types.go`` — Process / Container /
VirtualMachine / Pod with cumulative CPU time + per-interval delta, runtime
and hypervisor enums.

TPU-first pivot: these objects are the *metadata* view; the attribution math
never iterates them. ``informer.FeatureBatch`` carries the numeric columns
(cpu_time_delta per workload) as numpy arrays aligned to stable row indices.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace


class ContainerRuntime(str, enum.Enum):
    UNKNOWN = "unknown"
    DOCKER = "docker"
    CONTAINERD = "containerd"
    CRIO = "crio"
    PODMAN = "podman"
    KUBEPODS = "kubepods"


class Hypervisor(str, enum.Enum):
    UNKNOWN = "unknown"
    KVM = "kvm"


@dataclass
class Pod:
    id: str
    name: str = ""
    namespace: str = ""
    cpu_total_time: float = 0.0
    cpu_time_delta: float = 0.0
    # exporter label dict, built lazily and treated as immutable; set to
    # None whenever an identity field changes so label caches re-render
    meta_cache: dict | None = None

    def clone(self) -> "Pod":
        return replace(self)


@dataclass
class Container:
    id: str
    name: str = ""
    runtime: ContainerRuntime = ContainerRuntime.UNKNOWN
    pod_id: str | None = None
    cpu_total_time: float = 0.0
    cpu_time_delta: float = 0.0
    meta_cache: dict | None = None

    def clone(self) -> "Container":
        return replace(self)


@dataclass
class VirtualMachine:
    id: str
    name: str = ""
    hypervisor: Hypervisor = Hypervisor.UNKNOWN
    cpu_total_time: float = 0.0
    cpu_time_delta: float = 0.0
    meta_cache: dict | None = None

    def clone(self) -> "VirtualMachine":
        return replace(self)


@dataclass
class Process:
    pid: int
    comm: str = ""
    exe: str = ""
    cmdline: list[str] = field(default_factory=list)
    cpu_total_time: float = 0.0
    cpu_time_delta: float = 0.0
    container: Container | None = None
    virtual_machine: VirtualMachine | None = None
    # classification already ran (container/VM/regular verdict is cached;
    # reference caches via Process.Type in populateProcessFields)
    classified: bool = False
    # raw comm bytes from the batched stat scan (cheap change detection
    # without decoding 10k strings per tick)
    comm_raw: bytes = b""
    meta_cache: dict | None = None

    def clone(self) -> "Process":
        c = replace(self, cmdline=list(self.cmdline))
        return c


@dataclass
class Node:
    """Node-level CPU accounting (reference types.go Node / informer node)."""

    cpu_usage_ratio: float = 0.0  # active/(active+idle) from /proc/stat deltas
    process_total_cpu_time_delta: float = 0.0
