"""/proc filesystem reader.

Reference parity: ``internal/resource/procfs_reader.go`` — a thin interface
over per-PID reads (stat → CPU time, comm, exe, cgroup paths, environ,
cmdline) plus node CPU usage ratio from ``/proc/stat`` deltas
(active = total − idle − iowait, :107-141).

CPU time = (utime + stime) / USER_HZ with USER_HZ = 100 (:73-82).

Implemented with direct file reads (no psutil dependency in the hot path —
one open+read per PID per tick is the dominant host-side cost; see the C
accelerator in ``kepler_tpu.native`` for the batched fast path).
"""

from __future__ import annotations

import os
from typing import Iterable, Protocol

USER_HZ = 100.0


def parse_cgroup_text(text: str) -> list[str]:
    """Cgroup paths from /proc/<pid>/cgroup content (v1 and v2 lines)."""
    paths = []
    for line in text.splitlines():
        # format: hierarchy-ID:controller-list:cgroup-path
        parts = line.split(":", 2)
        if len(parts) == 3 and parts[2]:
            paths.append(parts[2])
    return paths


def parse_environ_bytes(raw: bytes) -> dict[str, str]:
    """Env dict from /proc/<pid>/environ content (NUL-separated)."""
    env: dict[str, str] = {}
    for entry in raw.decode("utf-8", "replace").split("\0"):
        if "=" in entry:
            k, _, v = entry.partition("=")
            env[k] = v
    return env


def parse_cmdline_bytes(raw: bytes) -> list[str]:
    """Argv from /proc/<pid>/cmdline content (NUL-separated)."""
    return [a for a in raw.decode("utf-8", "replace").split("\0") if a]


class ProcInfo(Protocol):
    """Per-process accessor (reference procInfo, procfs_reader.go:18-26)."""

    def pid(self) -> int: ...
    def comm(self) -> str: ...
    def executable(self) -> str: ...
    def cgroups(self) -> list[str]: ...
    def environ(self) -> dict[str, str]: ...
    def cmdline(self) -> list[str]: ...
    def cpu_time(self) -> float: ...


class ProcReader(Protocol):
    """All-process enumerator (reference allProcReader, :90-96)."""

    def all_procs(self) -> Iterable[ProcInfo]: ...
    def cpu_usage_ratio(self) -> float: ...


class ProcFSInfo:
    def __init__(self, procfs: str, pid: int) -> None:
        self._dir = os.path.join(procfs, str(pid))
        self._pid = pid

    def pid(self) -> int:
        return self._pid

    def _read(self, name: str) -> str:
        with open(os.path.join(self._dir, name), "rb") as f:
            return f.read().decode("utf-8", "replace")

    def comm(self) -> str:
        return self._read("comm").strip()

    def executable(self) -> str:
        try:
            return os.readlink(os.path.join(self._dir, "exe"))
        except OSError:
            return ""

    def cgroups(self) -> list[str]:
        """Cgroup paths from /proc/<pid>/cgroup (v1 and v2 lines)."""
        return parse_cgroup_text(self._read("cgroup"))

    def environ(self) -> dict[str, str]:
        try:
            with open(os.path.join(self._dir, "environ"), "rb") as f:
                raw = f.read()
        except OSError:
            return {}
        return parse_environ_bytes(raw)

    def cmdline(self) -> list[str]:
        with open(os.path.join(self._dir, "cmdline"), "rb") as f:
            raw = f.read()
        return parse_cmdline_bytes(raw)

    def cpu_time(self) -> float:
        """(utime + stime) / USER_HZ seconds from /proc/<pid>/stat."""
        raw = self._read("stat")
        # comm may contain spaces/parens; fields resume after the last ')'
        rparen = raw.rfind(")")
        fields = raw[rparen + 2:].split()
        # fields[0] is state (field 3); utime=field 14, stime=field 15
        utime = float(fields[11])
        stime = float(fields[12])
        return (utime + stime) / USER_HZ


class ProcFSReader:
    def __init__(self, procfs: str = "/proc") -> None:
        self._procfs = procfs
        self._prev_stat: tuple[float, float] | None = None  # (active, total)

    def all_procs(self) -> list[ProcFSInfo]:
        procs = []
        for entry in os.listdir(self._procfs):
            if entry.isdigit():
                procs.append(ProcFSInfo(self._procfs, int(entry)))
        return procs

    def _read_stat_totals(self) -> tuple[float, float]:
        """(active, total) jiffies from the aggregate 'cpu' line."""
        with open(os.path.join(self._procfs, "stat"), "rb") as f:
            first = f.readline().decode("ascii")
        parts = first.split()
        if parts[0] != "cpu":
            raise RuntimeError(f"unexpected /proc/stat first line: {first!r}")
        values = [float(v) for v in parts[1:]]
        total = sum(values)
        idle = values[3] if len(values) > 3 else 0.0
        iowait = values[4] if len(values) > 4 else 0.0
        active = total - idle - iowait
        return active, total

    def cpu_usage_ratio(self) -> float:
        """Node active/total ratio over the window since the previous call.

        First call returns 0.0 (no delta yet) — mirrors the reference's
        first-reading semantics (procfs_reader.go:107-141).
        """
        active, total = self._read_stat_totals()
        prev = self._prev_stat
        self._prev_stat = (active, total)
        if prev is None:
            return 0.0
        d_active = active - prev[0]
        d_total = total - prev[1]
        if d_total <= 0:
            return 0.0
        return min(max(d_active / d_total, 0.0), 1.0)
