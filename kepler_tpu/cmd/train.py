"""Estimator training CLI — the kepler-model-server train half.

``python -m kepler_tpu.cmd.train --data DIR --model mlp --out params.npz``

Reads the training windows the aggregator dumps
(`fleet/aggregator.py:_dump_training_window`: RAPL nodes' feature inputs
labelled with their own ratio-attributed watts), fits the chosen estimator
family, and writes serve-ready ``.npz`` params (`models.estimator
.save_params`) for ``--aggregator.params-path``. Long fits checkpoint to
``--ckpt-dir`` every ``--ckpt-every`` steps and RESUME from the latest
checkpoint automatically — preemption-safe by default
(`models/checkpoint.py`).

This closes the loop the reference ecosystem runs as a sidecar service:
RAPL fleet → labels → train → params → serve non-RAPL fleet. No
Prometheus round-trip: labels are captured at the attribution source.
"""

from __future__ import annotations

import argparse
import glob
import logging
import os
import sys
from typing import Sequence

import numpy as np

log = logging.getLogger("kepler.train")

FAMILIES = ("linear", "mlp", "moe", "deep", "temporal")


def load_windows(data_dir: str):
    """Concatenate dumped windows along the node-row axis.

    Each file carries its own zone axis (the per-round sorted union can
    change as fleet membership changes), so label columns align by ZONE
    NAME onto the union across all files; zones a row's file or node
    lacked are masked out of ``label_valid`` rather than read as 0-watt
    labels. Workload-slot padding (W) likewise aligns to the widest file.
    """
    files = sorted(glob.glob(os.path.join(data_dir, "window-*.npz")))
    if not files:
        raise FileNotFoundError(
            f"no window-*.npz training files under {data_dir!r} — point "
            "--data at an aggregator.trainingDumpDir")
    raw = []
    for f in files:
        with np.load(f) as z:
            raw.append({k: z[k] for k in z.files})
    zone_names = sorted({str(n) for r in raw
                         for n in r["zone_names"].tolist()})
    z_index = {n: i for i, n in enumerate(zone_names)}
    nz = len(zone_names)
    w_max = max(r["cpu_deltas"].shape[1] for r in raw)

    # temporal dumps carry per-workload history windows; T can vary
    # across files if aggregator.historyWindow changed — right-pad to the
    # longest (the temporal model pools the last VALID position)
    has_hist = [("feat_hist" in r) for r in raw]
    t_max = max((r["feat_hist"].shape[2] for r, h in zip(raw, has_hist)
                 if h), default=0)

    cols: dict[str, list[np.ndarray]] = {}
    for r, hist in zip(raw, has_hist):
        rows, w = r["cpu_deltas"].shape
        targets = np.zeros((rows, w_max, nz), np.float32)
        lvalid = np.zeros((rows, w_max, nz), bool)
        wvalid = np.zeros((rows, w_max), bool)
        cpu = np.zeros((rows, w_max), np.float32)
        cpu[:, :w] = r["cpu_deltas"]
        wvalid[:, :w] = r["workload_valid"]
        for j, name in enumerate(r["zone_names"].tolist()):
            i = z_index[str(name)]
            targets[:, :w, i] = r["target_watts"][:, :, j]
            lvalid[:, :w, i] = (r["workload_valid"]
                                & r["zone_valid"][:, None, j])
        cols.setdefault("cpu_deltas", []).append(cpu)
        cols.setdefault("workload_valid", []).append(wvalid)
        cols.setdefault("target_watts", []).append(targets)
        cols.setdefault("label_valid", []).append(lvalid)
        for k in ("node_cpu_delta", "usage_ratio", "dt_s"):
            cols.setdefault(k, []).append(r[k])
        if t_max:
            f_dim = (r["feat_hist"].shape[3] if hist
                     else next(x["feat_hist"].shape[3]
                               for x, h in zip(raw, has_hist) if h))
            fh = np.zeros((rows, w_max, t_max, f_dim), np.float32)
            tv = np.zeros((rows, w_max, t_max), bool)
            if hist:
                _, wh, th, _ = r["feat_hist"].shape
                fh[:, :wh, :th] = r["feat_hist"]
                tv[:, :wh, :th] = r["t_valid"]
            cols.setdefault("feat_hist", []).append(fh)
            cols.setdefault("t_valid", []).append(tv)
    data = {k: np.concatenate(v, axis=0) for k, v in cols.items()}
    data["zone_names"] = zone_names
    return data, files


def main(argv: Sequence[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="kepler-tpu-train",
        description="fit a power estimator on aggregator-dumped windows")
    p.add_argument("--data", required=True,
                   help="dir of window-*.npz files (aggregator dump)")
    p.add_argument("--model", default="mlp", choices=FAMILIES)
    p.add_argument("--out", required=True, help="output params .npz")
    p.add_argument("--steps", type=int, default=500)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ckpt-dir", default="",
                   help="orbax checkpoint dir (enables resume)")
    p.add_argument("--ckpt-every", type=int, default=100)
    p.add_argument("--log-every", type=int, default=50)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stderr)

    import jax
    import jax.numpy as jnp

    from kepler_tpu.models import build_features, initializer
    from kepler_tpu.models.estimator import predictor, save_params
    from kepler_tpu.models.train import (
        create_train_state,
        make_optimizer,
        make_temporal_train_step,
        make_train_step,
    )

    data, files = load_windows(args.data)
    n_zones = data["target_watts"].shape[-1]
    b, w = data["cpu_deltas"].shape
    log.info("loaded %d windows: %d node-rows × %d workload slots, "
             "zones %s, %d labelled workloads", len(files), b, w,
             data["zone_names"], int(data["workload_valid"].sum()))

    valid = jnp.asarray(data["workload_valid"])
    targets = jnp.asarray(data["target_watts"], jnp.float32)
    label_valid = jnp.asarray(data["label_valid"])
    optimizer = make_optimizer(args.lr)

    if args.model == "temporal":
        if "feat_hist" not in data:
            log.error(
                "--model temporal needs history windows in the dumps — "
                "run the aggregator with model=temporal AND a "
                "trainingDumpDir so ratio nodes' feature histories are "
                "captured (fleet/aggregator.py:_dump_training_window)")
            return 2
        feat_hist = jnp.asarray(data["feat_hist"])
        t_valid = jnp.asarray(data["t_valid"])
        t_max = int(feat_hist.shape[2])
        params = initializer("temporal")(
            jax.random.PRNGKey(args.seed), n_zones,
            t_max=max(t_max, 128))
        state = create_train_state(params, optimizer)
        temporal_step = make_temporal_train_step(optimizer)

        def step_fn(state, feats_, valid_, targets_, label_valid_):
            return temporal_step(state, feat_hist, valid_, t_valid,
                                 targets_, label_valid_)

        feats = None
    else:
        feats = build_features(
            jnp.asarray(data["cpu_deltas"]),
            jnp.asarray(data["workload_valid"]),
            jnp.asarray(data["node_cpu_delta"]),
            jnp.asarray(data["usage_ratio"]),
            jnp.asarray(data["dt_s"]),
        )
        params = initializer(args.model)(jax.random.PRNGKey(args.seed),
                                         n_zones)
        state = create_train_state(params, optimizer)
        step_fn = make_train_step(predictor(args.model), optimizer)

    ck = None
    if args.ckpt_dir:
        from kepler_tpu.models.checkpoint import TrainCheckpointer

        ck = TrainCheckpointer(args.ckpt_dir)
        resumed = ck.restore_latest(state)
        if resumed is not None:
            state = resumed
            log.info("resumed from checkpoint step %d", int(state.step))

    loss = float("nan")
    try:
        while int(state.step) < args.steps:
            state, loss = step_fn(state, feats, valid, targets, label_valid)
            step = int(state.step)
            if args.log_every and step % args.log_every == 0:
                log.info("step %d/%d loss %.6f", step, args.steps,
                         float(loss))
            if (ck is not None and args.ckpt_every
                    and step % args.ckpt_every == 0):
                ck.save(state)
        if ck is not None:
            if ck.latest_step() != int(state.step):  # periodic may have hit
                ck.save(state, force=True)
            ck.wait()
    finally:
        if ck is not None:
            ck.close()

    save_params(args.out, state.params)
    log.info("trained %s for %d steps (final loss %.6f) → %s",
             args.model, int(state.step), float(loss), args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
