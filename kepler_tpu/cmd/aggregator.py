"""Cluster-aggregator entry point.

The second role of the framework (SURVEY §7: "two roles, one codebase"):
``python -m kepler_tpu.cmd.aggregator`` starts the fleet ingest + sharded
TPU attribution service. Node agents point at it via
``--aggregator.endpoint`` on the regular ``kepler_tpu.cmd.main`` binary.
"""

from __future__ import annotations

import logging
import sys
from typing import Sequence

from kepler_tpu import version
from kepler_tpu.config import parse_args_and_config
from kepler_tpu.fleet import Aggregator
from kepler_tpu.service.lifecycle import (
    CancelContext,
    RestartPolicy,
    SignalHandler,
    init_services,
    run_services,
)
from kepler_tpu.utils.logger import new_logger

log = logging.getLogger("kepler.aggregator")


def main(argv: Sequence[str] | None = None) -> int:
    try:
        cfg = parse_args_and_config(argv, skip_validation=("host",))
        # the aggregator binary IS the replica role regardless of the
        # aggregator.enabled flag (which gates the node binary's embedded
        # aggregator) — ring membership must be coherent here too, as a
        # friendly startup error rather than a constructor traceback
        if cfg.aggregator.peers and not cfg.aggregator.self_peer:
            raise ValueError("aggregator.selfPeer must name this replica "
                             "when aggregator.peers is set")
    except (ValueError, OSError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    new_logger(cfg.log.level, cfg.log.format)
    from kepler_tpu import fault, telemetry
    fault.install_from_config(cfg.fault)
    telemetry.install_from_config(cfg.telemetry)
    # multi-host DCN: join the cluster BEFORE any jax API initialises the
    # backend (no-op single-host). Config knobs take precedence over the
    # JAX_* env convention; a failed join logs its DISTINCT reason
    # (coordinator_unreachable vs init_error) and the fleet-window
    # health probe republishes it, so a half-joined mesh is diagnosable.
    from kepler_tpu.parallel import initialize_multihost

    mh = cfg.aggregator.multihost
    joined = initialize_multihost(
        coordinator_address=mh.coordinator or None,
        num_processes=(mh.num_processes
                       if mh.num_processes != -1 else None),
        process_id=mh.process_id if mh.process_id != -1 else None,
        init_timeout=mh.init_timeout or None)
    if mh.enabled and not joined:
        log.warning("multihost enabled but not joined (%s)%s — running "
                    "single-host", joined.reason,
                    f": {joined.detail}" if joined.detail else "")
    info = version.info()
    log.info("kepler-tpu aggregator %s (%s, %s)", info.version,
             info.python_version, info.platform)

    params = None
    if cfg.aggregator.params_path:
        from kepler_tpu.models.estimator import load_params
        params = load_params(cfg.aggregator.params_path)
        log.info("loaded %s params from %s", cfg.aggregator.model,
                 cfg.aggregator.params_path)

    from kepler_tpu.server.webconfig import make_api_server
    server = make_api_server([cfg.aggregator.listen_address],
                             cfg.web.config_file,
                             max_connections=cfg.web.max_connections)
    # fleet black box: one journal per replica, installed process-wide
    # (module emit sites) AND handed to the Aggregator (its /debug
    # surfaces + metric families ride the aggregator's registration)
    from kepler_tpu.fleet import journal as journal_mod
    jnl = journal_mod.install_from_config(
        cfg.telemetry,
        node=(cfg.aggregator.self_peer or cfg.aggregator.listen_address),
        max_drift_s=cfg.aggregator.hlc_max_drift)
    aggregator = Aggregator(
        server,
        interval=cfg.aggregator.interval,
        stale_after=cfg.aggregator.stale_after,
        model_mode=cfg.aggregator.model or None,
        model_params=params,
        node_bucket=cfg.tpu.node_bucket,
        workload_bucket=cfg.tpu.workload_bucket,
        backend=cfg.tpu.fleet_backend,
        accuracy_mode=cfg.aggregator.accuracy_mode,
        history_window=cfg.aggregator.history_window,
        training_dump_dir=cfg.aggregator.training_dump_dir,
        training_dump_max_files=cfg.aggregator.training_dump_max_files,
        skew_tolerance=cfg.aggregator.skew_tolerance,
        degraded_ttl=cfg.aggregator.degraded_ttl,
        dedup_window=cfg.aggregator.dedup_window,
        delivery_buckets=cfg.telemetry.delivery_buckets or None,
        pipeline_depth=cfg.aggregator.pipeline_depth,
        fused_window_k=cfg.aggregator.fused_window_k,
        bucket_shrink_after=cfg.aggregator.bucket_shrink_after,
        fallback_enabled=cfg.aggregator.fallback_enabled,
        repromote_after=cfg.aggregator.repromote_after,
        dispatch_timeout=cfg.aggregator.dispatch_timeout,
        mesh_shape=cfg.aggregator.mesh_shape,
        mesh_axes=cfg.aggregator.mesh_axes,
        multihost_enabled=cfg.aggregator.multihost.enabled,
        multihost_takeover=cfg.aggregator.multihost.takeover,
        membership_auto_apply=cfg.aggregator.membership.auto_apply,
        membership_autoscale=cfg.aggregator.membership.autoscale_enabled,
        membership_scale_up_load=cfg.aggregator.membership.scale_up_load,
        membership_scale_down_load=(
            cfg.aggregator.membership.scale_down_load),
        membership_up_windows=cfg.aggregator.membership.up_windows,
        membership_down_windows=cfg.aggregator.membership.down_windows,
        membership_min_replicas=cfg.aggregator.membership.min_replicas,
        membership_max_replicas=cfg.aggregator.membership.max_replicas,
        membership_standby_peers=cfg.aggregator.membership.standby_peers,
        membership_probe_timeout=cfg.aggregator.membership.probe_timeout,
        scoreboard_cap=cfg.aggregator.scoreboard_cap,
        anomaly_z=cfg.aggregator.anomaly_z,
        peers=cfg.aggregator.peers,
        self_peer=cfg.aggregator.self_peer,
        ring_epoch=cfg.aggregator.ring_epoch,
        ring_vnodes=cfg.aggregator.ring_vnodes,
        admission_enabled=cfg.aggregator.admission_enabled,
        admission_max_inflight=cfg.aggregator.admission_max_inflight,
        admission_latency_budget=cfg.aggregator.admission_latency_budget,
        admission_retry_after=cfg.aggregator.admission_retry_after,
        admission_retry_after_max=(
            cfg.aggregator.admission_retry_after_max),
        base_row_cache=cfg.aggregator.base_row_cache,
        journal=jnl,
        hlc_max_drift=cfg.aggregator.hlc_max_drift,
    )
    # self-telemetry traces (ingest/decode/merge, window cycles)
    server.register("/debug/traces", "Traces",
                    "recent cycle span traces (?format=json|chrome; "
                    "chrome loads in Perfetto)",
                    telemetry.make_traces_handler())
    services: list = [server, aggregator]

    if cfg.exporter.prometheus.enabled:
        from prometheus_client import CollectorRegistry

        from kepler_tpu.exporter.prometheus.exporter import (
            make_registry_handler,
        )
        registry = CollectorRegistry()
        registry.register(aggregator)
        from kepler_tpu.exporter.prometheus import HealthCollector
        registry.register(HealthCollector(server.health))
        registry.register(telemetry.collector())
        # ~2× the stock renderer at 1k-node fleets in BOTH negotiated
        # formats (byte-identical; fastexpo falls back wholesale on
        # anything beyond the simple kepler families)
        server.register("/metrics", "Metrics",
                        "Fleet-level Prometheus metrics",
                        make_registry_handler(registry))

    services.append(SignalHandler())
    try:
        init_services(services)
    except Exception as err:
        log.error("initialization failed: %s", err)
        return 1
    ctx = CancelContext()
    try:
        run_services(ctx, services,
                     restart=RestartPolicy.from_config(cfg.service))
    except Exception as err:
        log.error("run failed: %s", err)
        return 1
    log.info("Graceful shutdown completed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
