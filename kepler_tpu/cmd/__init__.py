"""CLI entry points (reference ``cmd/``)."""
