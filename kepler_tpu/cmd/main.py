"""CLI entry point.

Reference parity: ``cmd/kepler/main.go:27-65`` — parse flags+config, build
the service graph, sequential Init (rollback on failure), concurrent Run
(first exit cancels all), graceful shutdown on SIGINT/SIGTERM.

Run as ``python -m kepler_tpu.cmd.main [flags]`` or via the ``kepler-tpu``
console script.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Sequence

from kepler_tpu import fault, telemetry, version
from kepler_tpu.config import Config, parse_args_and_config
from kepler_tpu.device.fake import FakeCPUMeter
from kepler_tpu.device.rapl import RaplPowerMeter
from kepler_tpu.exporter.prometheus import (
    PrometheusExporter,
    create_collectors,
)
from kepler_tpu.exporter.stdout import StdoutExporter
from kepler_tpu.monitor.monitor import PowerMonitor
from kepler_tpu.monitor.watchdog import MonitorWatchdog
from kepler_tpu.resource import ResourceInformer, make_proc_reader
from kepler_tpu.server.debug import DebugService
from kepler_tpu.server.webconfig import make_api_server
from kepler_tpu.service.lifecycle import (
    CancelContext,
    RestartPolicy,
    SignalHandler,
    init_services,
    run_services,
)
from kepler_tpu.utils.logger import new_logger

log = logging.getLogger("kepler.main")


def _powercap_usable(sysfs: str) -> bool:
    powercap = os.path.join(sysfs, "class", "powercap")
    try:
        return any(e.startswith("intel-rapl") for e in os.listdir(powercap))
    except OSError:
        return False


def create_cpu_meter(cfg: Config):
    """reference createCPUMeter (main.go:227-241), extended with the MSR
    fallback the reference proposed (EP-002): powercap stays primary; MSR
    engages only when opted in AND powercap is unusable (or force, for
    testing)."""
    if cfg.dev.fake_cpu_meter.enabled:
        return FakeCPUMeter(zones=cfg.dev.fake_cpu_meter.zones)
    if cfg.msr.enabled:
        from kepler_tpu.device.msr import MsrPowerMeter

        if cfg.msr.force:
            return MsrPowerMeter(device_path=cfg.msr.device_path,
                                 zone_filter=cfg.rapl.zones)
        if (not _powercap_usable(cfg.host.sysfs)
                and MsrPowerMeter.available(cfg.msr.device_path)):
            log.warning("powercap unusable under %s; falling back to the "
                        "MSR meter", cfg.host.sysfs)
            return MsrPowerMeter(device_path=cfg.msr.device_path,
                                 zone_filter=cfg.rapl.zones)
    return RaplPowerMeter(sysfs_path=cfg.host.sysfs,
                          zone_filter=cfg.rapl.zones)


def create_services(cfg: Config) -> list:
    """reference createServices (main.go:124-225)."""
    if cfg.tpu.compilation_cache_dir:
        # persistent XLA cache: bucket-crossing / restart compiles become
        # disk hits (statelessness stays intact — it is only a cache)
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          cfg.tpu.compilation_cache_dir)
    meter = create_cpu_meter(cfg)

    pod_lookup = None
    if cfg.kube.enabled:
        from kepler_tpu.k8s.pod import PodInformer
        pod_lookup = PodInformer(
            node_name=cfg.kube.node_name, kubeconfig=cfg.kube.config)

    resources = ResourceInformer(reader=make_proc_reader(cfg.host.procfs),
                                 procfs_path=cfg.host.procfs,
                                 pod_lookup=pod_lookup)
    monitor = PowerMonitor(
        meter,
        resources,
        interval=cfg.monitor.interval,
        staleness=cfg.monitor.staleness,
        max_terminated=cfg.monitor.max_terminated,
        min_terminated_energy_uj=(
            cfg.monitor.min_terminated_energy_threshold * 1e6),
        workload_bucket=cfg.tpu.workload_bucket,
        state_path=cfg.monitor.state_path,
        state_max_age=cfg.monitor.state_max_age,
    )
    server = make_api_server(cfg.web.listen_addresses, cfg.web.config_file,
                             max_connections=cfg.web.max_connections)
    # self-telemetry: recent cycle traces (monitor refresh stages, scrape
    # renders, agent delivery legs) as JSON or Chrome trace-event format
    server.register("/debug/traces", "Traces",
                    "recent cycle span traces (?format=json|chrome; "
                    "chrome loads in Perfetto)",
                    telemetry.make_traces_handler())
    services: list = []
    if pod_lookup is not None:
        services.append(pod_lookup)
    services += [resources, monitor, server]
    if cfg.monitor.interval > 0:
        stall_journal = None
        if cfg.telemetry.journal.enabled:
            from kepler_tpu.fleet import journal
            stall_journal = journal.active()
        watchdog = MonitorWatchdog(
            monitor, interval=cfg.monitor.interval,
            stall_after=cfg.monitor.stall_after or None,
            journal=stall_journal)
        services.append(watchdog)
        # ONE monitor probe: the watchdog's (stall flag + age + stall
        # count) supersedes monitor.health, which reads the same flag
        server.health.register_probe("monitor-watchdog", watchdog.health)
    else:
        server.health.register_probe("monitor", monitor.health)
    # ready once the first snapshot exists (collector readiness gate)
    server.health.register_readiness(
        "monitor", lambda: {"ok": monitor.data_channel().is_set()})
    agent = None
    spool_error = ""
    if cfg.aggregator.endpoint:
        from kepler_tpu.fleet import FleetAgent, Spool
        from kepler_tpu.parallel.fleet import MODE_MODEL, MODE_RATIO
        spool = None
        if cfg.agent.spool.dir:
            # durable delivery: windows survive agent crashes/aggregator
            # outages on disk and replay with their original identity.
            # An unopenable spool (read-only disk after a crash, bad
            # permissions) degrades to the in-memory ring — losing the
            # durability upgrade must never cost the power metrics too.
            try:
                spool = Spool(
                    cfg.agent.spool.dir,
                    max_bytes=cfg.agent.spool.max_bytes,
                    max_records=cfg.agent.spool.max_records,
                    segment_bytes=cfg.agent.spool.segment_bytes,
                    fsync=cfg.agent.spool.fsync,
                    fsync_interval=cfg.agent.spool.fsync_interval,
                )
            except OSError as err:
                spool_error = str(err)
                log.error("report spool %s unusable (%s); continuing "
                          "WITHOUT durable delivery (in-memory ring only)",
                          cfg.agent.spool.dir, err)
        agent = FleetAgent(
            monitor,
            endpoint=cfg.aggregator.endpoint,
            node_name=cfg.kube.node_name,
            mode=(MODE_MODEL if cfg.aggregator.node_mode == "model"
                  else MODE_RATIO),
            tls_skip_verify=cfg.aggregator.tls_skip_verify,
            backoff_initial=cfg.aggregator.backoff_initial,
            backoff_max=cfg.aggregator.backoff_max,
            breaker_threshold=cfg.aggregator.breaker_threshold,
            breaker_cooldown=cfg.aggregator.breaker_cooldown,
            flush_timeout_s=cfg.aggregator.flush_timeout,
            spool=spool,
            peers=cfg.aggregator.peers,
            drain_batch_max=cfg.agent.drain.batch_max,
            drain_replay_rps=cfg.agent.drain.replay_rps,
            drain_retry_after_max=cfg.agent.drain.retry_after_max,
            wire_version=cfg.agent.wire.version,
            keyframe_every=cfg.agent.wire.keyframe_every,
            wire_degraded_ttl=cfg.agent.wire.degraded_ttl,
        )
        server.health.register_probe("fleet-agent", agent.health)
        if spool is not None:
            server.health.register_probe("fleet-spool", agent.spool_health)
        elif spool_error:
            # the operator ASKED for durability and is not getting it —
            # /healthz must say so, not stay silently green
            server.health.register_probe(
                "fleet-spool",
                lambda: {"ok": False, "enabled": False,
                         "error": f"configured spool unusable: "
                                  f"{spool_error}"})
    if cfg.exporter.prometheus.enabled:
        source = {"rapl": "rapl-powercap", "rapl-msr": "rapl-msr",
                  "fake-cpu-meter": "fake"}.get(meter.name(), meter.name())
        collectors = create_collectors(
            monitor,
            node_name=cfg.kube.node_name,
            metrics_level=cfg.exporter.prometheus.metrics_level,
            procfs=cfg.host.procfs,
            meter_source=source,
        )
        from kepler_tpu.exporter.prometheus import HealthCollector
        collectors.append(HealthCollector(server.health))
        # kepler_self_* families (stage histograms, cycle overruns)
        # scrape beside the power collectors; when telemetry is disabled
        # the recorder simply has no samples
        collectors.append(telemetry.collector())
        if cfg.telemetry.journal.enabled:
            # kepler_fleet_journal_* / HLC families (black box). The
            # import stays inside the gate: fleet pulls jax, and a
            # journal-less monitor must not pay that
            from kepler_tpu.fleet import journal
            collectors.append(journal.collector())
        if agent is not None:
            # breaker-state gauge always; kepler_fleet_spool_* rides
            # along when a spool is configured
            collectors.append(agent)
        services.append(PrometheusExporter(
            server, collectors,
            debug_collectors=cfg.exporter.prometheus.debug_collectors))
    if cfg.debug.pprof.enabled:
        services.append(DebugService(server))
    if cfg.exporter.stdout.enabled:
        services.append(StdoutExporter(monitor))
    if agent is not None:
        services.append(agent)
    if cfg.aggregator.enabled:
        log.warning("aggregator.enabled is set — the aggregator role runs "
                    "as its own binary: python -m kepler_tpu.cmd.aggregator")
    return services


def main(argv: Sequence[str] | None = None) -> int:
    try:
        cfg = parse_args_and_config(argv)
    except (ValueError, OSError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    # stdout exporter owns stdout; logs move to stderr (main.go:34-38)
    stream = sys.stderr if cfg.exporter.stdout.enabled else sys.stdout
    new_logger(cfg.log.level, cfg.log.format, stream=stream)
    info = version.info()
    log.info("kepler-tpu %s (%s, %s)", info.version, info.python_version,
             info.platform)

    try:
        fault.install_from_config(cfg.fault)
        telemetry.install_from_config(cfg.telemetry)
        if cfg.telemetry.journal.enabled:
            # black-box journal for the agent/monitor process (breaker,
            # spool rewind, watchdog stall events); lazy import — the
            # fleet package pulls jax
            from kepler_tpu.fleet import journal
            journal.install_from_config(
                cfg.telemetry, node=cfg.kube.node_name,
                max_drift_s=cfg.aggregator.hlc_max_drift)
        services = create_services(cfg)
    except Exception as err:
        log.error("failed to create services: %s", err)
        return 1
    signal_handler = SignalHandler()
    services.append(signal_handler)
    try:
        init_services(services)
    except Exception as err:
        log.error("initialization failed: %s", err)
        return 1
    ctx = CancelContext()
    try:
        run_services(ctx, services,
                     restart=RestartPolicy.from_config(cfg.service))
    except Exception as err:
        log.error("run failed: %s", err)
        return 1
    log.info("Graceful shutdown completed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
