"""Ring attention: context parallelism over the history time axis.

The reference has no sequences at all (SURVEY §5 — its signals are scalar
per-tick deltas). The time axis appears in this framework when the
estimator consumes per-workload feature *history* windows
(`kepler_tpu.models.temporal`): a fleet window is ``[B, T, F]`` where ``T``
can grow to hours of ticks. For long windows the KV working set no longer
fits one chip's HBM, so the sequence axis shards across devices and
attention runs as a **ring**: each device keeps its query block resident
and rotates K/V blocks around the mesh axis with ``ppermute`` (one
neighbour hop per step, riding ICI), accumulating flash-attention-style
online-softmax partials (`kepler_tpu.ops.attention`). No device ever
materialises the full ``[T, T]`` score matrix or the full K/V sequence,
and after ``n`` steps the telescoped merge equals exact softmax attention
— verified against the dense reference in ``tests/test_ring.py``.

Built on ``shard_map`` so the collective schedule is explicit; the
per-block compute inside is plain jnp, which XLA fuses and tiles onto the
MXU (bf16 matmuls, f32 accumulators).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.ops.attention import (
    _NEG_INF,
    block_attn,
    full_attention,
    merge_blocks,
    stats_to_out,
)
from kepler_tpu.parallel.compat import pcast_varying, shard_map

SEQ_AXIS = "seq"

__all__ = ["SEQ_AXIS", "full_attention", "make_ring_attention",
           "ring_attention_shardmap"]


def _ring_shard(q, k, v, t_valid, *, axis_name, causal, compute_dtype,
                backend="einsum"):
    """Per-device body: local q block resident, KV ring-rotates n times."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    q_pos = idx * t_loc + jnp.arange(t_loc)  # global positions of my queries

    # zeros-initialised carries must be marked device-varying over the ring
    # axis up front or the fori_loop carry types mismatch (shard_map vma rule)
    def vary(x):
        return pcast_varying(x, axis_name)
    o = vary(jnp.zeros((b, t_loc, h, d), jnp.float32))
    m = vary(jnp.full((b, h, t_loc), _NEG_INF, jnp.float32))
    l = vary(jnp.zeros((b, h, t_loc), jnp.float32))  # noqa: E741
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_partials(k, v, kv_val, src):
        if backend == "pallas":
            from kepler_tpu.ops.pallas_attention import flash_block_pallas

            # positions reach the kernel as scalar block starts; the
            # causal mask is rebuilt from iota inside VMEM — the [T, T]
            # mask never exists in HBM
            return flash_block_pallas(
                q, k, v, kv_val, idx * t_loc, src * t_loc, causal=causal,
                compute_dtype=compute_dtype)
        kv_pos = src * t_loc + jnp.arange(t_loc)
        mask = jnp.broadcast_to(kv_val[:, None, None, :],
                                (b, 1, t_loc, t_loc))
        if causal:
            mask = mask & (q_pos[:, None] >= kv_pos[None, :])
        return block_attn(q, k, v, mask, scale, compute_dtype)

    def step(s, carry):
        o, m, l, k, v, kv_val = carry  # noqa: E741
        src = (idx - s) % n  # shard this KV block originated from
        pv, m_blk, l_blk = block_partials(k, v, kv_val, src)
        o, m, l = merge_blocks(o, m, l, pv, m_blk, l_blk)  # noqa: E741
        # rotate KV (+validity) one hop; after n steps it is home again
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        kv_val = jax.lax.ppermute(kv_val, axis_name, perm)
        return o, m, l, k, v, kv_val

    o, m, l, _, _, _ = jax.lax.fori_loop(  # noqa: E741
        0, n, step, (o, m, l, k, v, t_valid))
    l_safe = jnp.maximum(l, 1e-30)
    return (o / stats_to_out(l_safe)).astype(q.dtype)


def ring_attention_shardmap(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    backend: str = "einsum",
):
    """Un-jitted shard-mapped ring kernel ``(q, k, v, t_valid) → out``.

    The composable form: call it inside a larger jitted program (the
    sequence-parallel temporal estimator does) or jit it directly via
    :func:`make_ring_attention`.
    """
    body = functools.partial(_ring_shard, axis_name=axis_name,
                             causal=causal, compute_dtype=compute_dtype,
                             backend=backend)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name),
                  P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        # pallas_call defeats the varying-axes checker (same caveat as
        # aggregator_core.shard_by_node)
        check_vma=backend != "pallas",
    )


def make_ring_attention(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    backend: str = "einsum",
):
    """→ jitted ``(q, k, v, t_valid) → out`` with T sharded over the mesh.

    Inputs are ``[B, T, H, D]`` (+ ``t_valid`` bool ``[B, T]``); T must
    divide by the ``axis_name`` mesh size. Output shards like q.
    ``backend="pallas"`` computes each block partial with the fused VMEM
    kernel (`ops.pallas_attention`); "einsum" lets XLA fuse the jnp path.
    """
    seq = NamedSharding(mesh, P(None, axis_name))
    shard = ring_attention_shardmap(mesh, axis_name=axis_name, causal=causal,
                                    compute_dtype=compute_dtype,
                                    backend=backend)
    return jax.jit(shard, in_shardings=(seq, seq, seq, seq),
                   out_shardings=seq)
