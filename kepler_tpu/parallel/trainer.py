"""Distributed estimator training: data parallel × tensor parallel.

The full training step (forward, masked-MSE loss, backward, adamw update)
jits over a 2-D ``node × model`` mesh:

- **DP** (``node`` axis): the flattened sample batch shards row-wise; the
  mean loss makes XLA reduce gradients with a psum over ``node`` — the
  gradient all-reduce of a hand-written DDP, derived by GSPMD instead.
- **TP** (``model`` axis): Megatron-style MLP sharding — ``w0 [F,H]``
  column-parallel ``P(None, 'model')``, ``w1 [H,H]`` row-parallel
  ``P('model', None)`` so the only forward collective is one psum on
  layer-1's output; ``w2``/biases replicate (Z is tiny).

Adam moments inherit the param shardings (optax state is a params-shaped
pytree), so optimizer memory also shards over ``model``.

This is the ``dryrun_multichip`` path: the driver runs it on N virtual CPU
devices to validate multi-chip compilation without hardware.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.models.mlp import MLPParams, predict_mlp
from kepler_tpu.models.train import TrainState, masked_mse
from kepler_tpu.parallel.mesh import MODEL_AXIS, NODE_AXIS


def mlp_param_shardings(mesh: Mesh) -> MLPParams:
    """Megatron-style TP layout for the MLP params."""
    ns = lambda *spec: NamedSharding(mesh, P(*spec))  # noqa: E731
    return MLPParams(
        w0=ns(None, MODEL_AXIS),  # column-parallel
        b0=ns(MODEL_AXIS),
        w1=ns(MODEL_AXIS, None),  # row-parallel (psum after)
        b1=ns(),
        w2=ns(),
        b2=ns(),
        w_skip=ns(),  # wide path: [F, Z] is tiny, replicate
    )


def _state_shardings(tree: Any, p_shard: MLPParams, mesh: Mesh):
    """Map a params-shaped (or opt-state) pytree to shardings.

    optax.adamw state embeds params-shaped subtrees (mu, nu) plus scalar
    counts; a leaf whose pytree path ends in a param name (and matches its
    rank) gets that param's sharding, everything else replicates.
    """
    rep = NamedSharding(mesh, P())

    def resolve(path, leaf):
        for entry in reversed(path):
            name = getattr(entry, "key", getattr(entry, "name", None))
            if isinstance(name, str) and name in p_shard:
                want = p_shard[name]
                if getattr(leaf, "ndim", 0) == len(want.spec):
                    return want
                return rep
        return rep

    return jax.tree_util.tree_map_with_path(resolve, tree)


def shard_train_state(state: TrainState, mesh: Mesh) -> TrainState:
    """device_put params + optimizer moments with the TP layout."""
    p_shard = mlp_param_shardings(mesh)

    def put(tree):
        shardings = _state_shardings(tree, p_shard, mesh)
        return jax.tree.map(jax.device_put, tree, shardings)

    return TrainState(
        params=put(state.params),
        opt_state=put(state.opt_state),
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
    )


def make_distributed_train_step(
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
):
    """jitted (state, features[B,W,F], valid[B,W], targets[B,W,Z]) → state, loss.

    The leading batch axis shards over ``node``; params/opt-state use the TP
    layout. GSPMD inserts the DP gradient psum and the TP activation psum.
    """
    data = NamedSharding(mesh, P(NODE_AXIS))

    def step(state: TrainState, features, workload_valid, targets):
        def loss_fn(params):
            pred = predict_mlp(params, features, workload_valid, clamp=False)
            return masked_mse(pred, targets, workload_valid)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(
        step,
        in_shardings=(None, data, data, data),  # state keeps its placement
        donate_argnums=(0,),
    )
