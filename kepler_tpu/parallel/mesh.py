"""Device-mesh construction.

The scaling axes of this framework (per SURVEY §2 checklist: the reference
has no DP/TP/PP — its "fleet" is a K8s DaemonSet; the TPU build's analog
axes are):

- ``node``  — data parallelism over the fleet's node axis: each device
  attributes a slice of the cluster's nodes (the moral equivalent of DP).
- ``model`` — tensor parallelism over the MLP estimator's hidden dim
  (column-/row-parallel weights, one psum on the output projection).

A 1-D mesh uses all devices on ``node``; a 2-D mesh splits them
``node × model``. Collectives ride ICI inside one pjit program — there is
no hand-written NCCL/MPI analog anywhere (XLA inserts them from sharding
annotations).
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, NamedTuple, Sequence

import jax
from jax.sharding import Mesh

NODE_AXIS = "node"
MODEL_AXIS = "model"

log = logging.getLogger("kepler.parallel.mesh")


class MultihostInit(NamedTuple):
    """Outcome of :func:`initialize_multihost` — truthy iff the process
    joined a cluster, with the DISTINCT failure reason preserved so a
    half-joined mesh is diagnosable from the return value, the log, and
    the ``fleet-window`` health probe (not a generic decline).

    ``reason`` is one of the bounded labels below; ``detail`` carries
    the underlying error text (bounded) when one exists.
    """

    joined: bool
    reason: str  # joined | unconfigured | coordinator_unreachable | init_error
    detail: str = ""
    num_processes: int = 1
    process_id: int = 0

    def __bool__(self) -> bool:  # backward compat: callers truth-test it
        return self.joined


#: the last initialize_multihost outcome in this process ("never called"
#: reads as an unconfigured single-host decline) — the health probe's view
_last_init: MultihostInit = MultihostInit(False, "unconfigured")


def multihost_status() -> MultihostInit:
    """The last :func:`initialize_multihost` outcome in this process."""
    return _last_init


# error-text markers that mean "the coordinator never answered" (gRPC
# deadline/connectivity vocabulary across the jax versions we support) —
# anything else is an init_error, a different operator problem entirely
_UNREACHABLE_MARKERS = ("deadline_exceeded", "deadline exceeded",
                        "unavailable", "timed out", "timeout",
                        "failed to connect", "connection refused")


def _classify_init_error(err: BaseException) -> str:
    text = f"{type(err).__name__}: {err}".lower()
    if any(m in text for m in _UNREACHABLE_MARKERS):
        return "coordinator_unreachable"
    return "init_error"


#: pre-probe bound when no init_timeout is configured — jax's own
#: RegisterTask deadline default
_DEFAULT_JOIN_DEADLINE_S = 300.0


def _wait_coordinator(addr: str, deadline_s: float) -> bool:
    """Poll a TCP connect to the coordinator until ``deadline_s``.

    jax's distributed client handles a connect deadline with a native
    ``LOG(FATAL)`` — the process ABORTS before any Python except clause
    can classify the failure (observed live on jax 0.4.37:
    ``Terminating process … DEADLINE_EXCEEDED … RegisterTask``). So for
    non-coordinator processes the unreachable case must be caught HERE,
    in Python, before ``jax.distributed.initialize`` is ever entered.
    Retries absorb the normal startup race where process 0 hasn't bound
    its port yet."""
    import socket
    import time

    host, _, port_s = addr.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        return True  # unparseable → let jax produce its own error
    host = host.strip("[]") or "127.0.0.1"
    deadline = time.monotonic() + max(1.0, deadline_s)
    while True:
        try:
            with socket.create_connection((host, port), timeout=2.0):
                return True
        except OSError:
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(0.5, max(0.05,
                                    deadline - time.monotonic())))


def make_mesh(
    mesh_shape: Sequence[int] = (),
    axes: Sequence[str] = (NODE_AXIS,),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh; empty shape = all devices on the first axis.

    ``mesh_shape`` may contain one ``-1`` (inferred). Axis count must match
    shape length (after defaulting).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if not mesh_shape:
        mesh_shape = [n] + [1] * (len(axes) - 1)
    shape = list(mesh_shape)
    if shape.count(-1) > 1:
        raise ValueError("at most one -1 in mesh_shape")
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        shape[shape.index(-1)] = n // known
    if math.prod(shape) != n:
        raise ValueError(
            f"mesh shape {shape} needs {math.prod(shape)} devices, "
            f"have {n}")
    if len(shape) != len(axes):
        raise ValueError(f"shape {shape} vs axes {axes} rank mismatch")
    import numpy as np

    return Mesh(np.asarray(devs).reshape(shape), tuple(axes))


def submesh_for_processes(
    mesh: Mesh,
    processes: Sequence[int],
    device_process: Any = None,
) -> Mesh:
    """A 1-D node mesh over the subset of ``mesh``'s devices owned by
    ``processes`` — the elastic-membership rebuild primitive: after a
    host death the survivors rebuild their multi-host window engine
    over exactly the surviving processes' devices (and a rejoin
    rebuilds over the full set again). Device order is preserved, so
    every process derives the identical shard order with no
    coordination — the same determinism contract as the ingest ring.

    ``device_process`` maps a device to its process index (defaults to
    ``device.process_index``; the virtual multi-host topology injects
    its own). Degenerate cases fail loudly: an empty retained set has
    no mesh to build.
    """
    keep = {int(p) for p in processes}
    if device_process is None:
        def device_process(d: Any) -> int:
            return int(getattr(d, "process_index", 0))
    devs = [d for d in mesh.devices.flat if int(device_process(d)) in keep]
    if not devs:
        raise ValueError(
            f"no devices of the mesh belong to processes "
            f"{sorted(keep)!r}")
    return make_mesh([len(devs)], (NODE_AXIS,), devices=devs)


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    init_timeout: float | None = None,
) -> MultihostInit:
    """Join a multi-host JAX cluster (DCN) so meshes span every host's
    chips — the scale-out leg beyond one aggregator host.

    The reference's fleet plane is per-node HTTP with no accelerator
    cluster at all (SURVEY §5 "distributed communication backend: absent");
    here, once one aggregator host saturates, N aggregator processes form
    one jax.distributed job: each host runs the SAME sharded programs and
    `jax.devices()` (hence `make_mesh()`) covers all hosts' chips, with
    XLA routing intra-host collectives over ICI and cross-host ones over
    DCN. Report ingest stays HTTP behind a load balancer; only the device
    mesh is cluster-wide.

    Arguments default from the standard env (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID — also set by TPU pod runtimes).
    → a truthy :class:`MultihostInit` if distributed init ran; a falsy
    one when unconfigured (single-host, the default everywhere in this
    repo's tests and benches) or when joining FAILED — with the failure
    reason kept distinct: ``coordinator_unreachable`` (the coordinator
    never answered within the deadline — the classic half-joined-mesh
    misconfiguration) vs ``init_error`` (anything else). Both are also
    logged at error level and surfaced by :func:`multihost_status`, which
    the aggregator's ``fleet-window`` health probe republishes.

    Call ONCE per process, before any other jax API touches the backend.
    """
    global _last_init
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        _last_init = MultihostInit(False, "unconfigured")
        return _last_init
    kwargs: dict[str, Any] = {"coordinator_address": addr}
    nproc = (num_processes if num_processes is not None
             else os.environ.get("JAX_NUM_PROCESSES"))
    pid = (process_id if process_id is not None
           else os.environ.get("JAX_PROCESS_ID"))
    if nproc is not None:
        kwargs["num_processes"] = int(nproc)
    if pid is not None:
        kwargs["process_id"] = int(pid)
    if init_timeout is not None and init_timeout > 0:
        kwargs["initialization_timeout"] = int(init_timeout)
    # non-coordinator processes: verify the coordinator ANSWERS before
    # entering jax — its native client aborts the whole process on a
    # connect deadline (no Python exception to classify), which would
    # turn the most common misconfiguration into an undiagnosable crash.
    # Process 0 hosts the coordinator itself, so it never probes; with
    # the process id UNKNOWN (jax auto-detection) we cannot tell the two
    # apart — probing would wrongly decline on the coordinator host, so
    # the probe is skipped and an unreachable coordinator still aborts
    # natively there. Leave the breadcrumb where it can be found.
    pid_i = int(pid) if pid is not None else None
    if pid_i is None:
        log.warning(
            "multi-host init with no explicit process id: the "
            "coordinator reachability pre-probe is skipped — if %s is "
            "unreachable, jax's native client will ABORT this process "
            "(set JAX_PROCESS_ID / aggregator.multihost.processId for "
            "a diagnosable coordinator_unreachable decline)", addr)
    if pid_i is not None and pid_i != 0:
        bound = (float(init_timeout) if init_timeout else
                 _DEFAULT_JOIN_DEADLINE_S)
        if not _wait_coordinator(addr, bound):
            detail = (f"no coordinator listening at {addr} within "
                      f"{bound:g}s")
            _last_init = MultihostInit(
                False, "coordinator_unreachable", detail=detail,
                num_processes=int(nproc) if nproc is not None else 1,
                process_id=pid_i)
            log.error("multi-host jax init FAILED "
                      "(coordinator_unreachable): %s", detail)
            return _last_init
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as err:
        # a failed join must not read as "unconfigured single-host": the
        # reason is preserved for the return/log/probe so the operator
        # sees a coordinator that never answered vs a real init bug
        reason = _classify_init_error(err)
        detail = f"{type(err).__name__}: {err}"[:240]
        _last_init = MultihostInit(
            False, reason, detail=detail,
            num_processes=int(nproc) if nproc is not None else 1,
            process_id=int(pid) if pid is not None else 0)
        log.error("multi-host jax init FAILED (%s) against %s: %s",
                  reason, addr, detail)
        return _last_init
    _last_init = MultihostInit(
        True, "joined",
        num_processes=jax.process_count(),
        process_id=jax.process_index())
    log.info("joined multi-host jax cluster: %s (process %s/%s, "
             "%d global devices)", addr, pid, nproc, len(jax.devices()))
    return _last_init
