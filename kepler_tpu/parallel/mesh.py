"""Device-mesh construction.

The scaling axes of this framework (per SURVEY §2 checklist: the reference
has no DP/TP/PP — its "fleet" is a K8s DaemonSet; the TPU build's analog
axes are):

- ``node``  — data parallelism over the fleet's node axis: each device
  attributes a slice of the cluster's nodes (the moral equivalent of DP).
- ``model`` — tensor parallelism over the MLP estimator's hidden dim
  (column-/row-parallel weights, one psum on the output projection).

A 1-D mesh uses all devices on ``node``; a 2-D mesh splits them
``node × model``. Collectives ride ICI inside one pjit program — there is
no hand-written NCCL/MPI analog anywhere (XLA inserts them from sharding
annotations).
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, Sequence

import jax
from jax.sharding import Mesh

NODE_AXIS = "node"
MODEL_AXIS = "model"

log = logging.getLogger("kepler.parallel.mesh")


def make_mesh(
    mesh_shape: Sequence[int] = (),
    axes: Sequence[str] = (NODE_AXIS,),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh; empty shape = all devices on the first axis.

    ``mesh_shape`` may contain one ``-1`` (inferred). Axis count must match
    shape length (after defaulting).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if not mesh_shape:
        mesh_shape = [n] + [1] * (len(axes) - 1)
    shape = list(mesh_shape)
    if shape.count(-1) > 1:
        raise ValueError("at most one -1 in mesh_shape")
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        shape[shape.index(-1)] = n // known
    if math.prod(shape) != n:
        raise ValueError(
            f"mesh shape {shape} needs {math.prod(shape)} devices, "
            f"have {n}")
    if len(shape) != len(axes):
        raise ValueError(f"shape {shape} vs axes {axes} rank mismatch")
    import numpy as np

    return Mesh(np.asarray(devs).reshape(shape), tuple(axes))


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join a multi-host JAX cluster (DCN) so meshes span every host's
    chips — the scale-out leg beyond one aggregator host.

    The reference's fleet plane is per-node HTTP with no accelerator
    cluster at all (SURVEY §5 "distributed communication backend: absent");
    here, once one aggregator host saturates, N aggregator processes form
    one jax.distributed job: each host runs the SAME sharded programs and
    `jax.devices()` (hence `make_mesh()`) covers all hosts' chips, with
    XLA routing intra-host collectives over ICI and cross-host ones over
    DCN. Report ingest stays HTTP behind a load balancer; only the device
    mesh is cluster-wide.

    Arguments default from the standard env (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID — also set by TPU pod runtimes).
    → True if distributed init ran; False when unconfigured (single-host,
    the default everywhere in this repo's tests and benches).

    Call ONCE per process, before any other jax API touches the backend.
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return False
    kwargs: dict[str, Any] = {"coordinator_address": addr}
    nproc = (num_processes if num_processes is not None
             else os.environ.get("JAX_NUM_PROCESSES"))
    pid = (process_id if process_id is not None
           else os.environ.get("JAX_PROCESS_ID"))
    if nproc is not None:
        kwargs["num_processes"] = int(nproc)
    if pid is not None:
        kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)
    log.info("joined multi-host jax cluster: %s (process %s/%s, "
             "%d global devices)", addr, pid, nproc, len(jax.devices()))
    return True
