"""jax API compatibility seams for the parallel layer.

The sharded programs are written against the current jax surface
(``jax.shard_map`` with its ``check_vma`` varying-axes checker and
``jax.lax.pcast`` for marking loop carries device-varying). Older
toolchains — including CPU-only CI hosts pinned to jax 0.4.x — ship the
same machinery as ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` replication checker and no ``pcast`` at all. This module
is the single place that bridges the two so every shard-mapped program
(ring/Ulysses attention, the GPipe pipeline, expert parallelism, the
packed fleet program, ``shard_by_node``) builds — and therefore the
device-tier analyzer (``kepler_tpu.analysis.device``) can trace them —
on either toolchain.

Semantics on the fallback path: ``pcast``-style varying marking does
not exist, so the replication checker cannot validate the ring/pipeline
carry pattern — ``shard_map`` therefore forces ``check_rep=False``
there. The checker is a tracing-time diagnostic only; program semantics
are unchanged (tests assert the sharded kernels still match their dense
references bit-for-bit on the fallback path).
"""

from __future__ import annotations

from typing import Any, Callable

import jax

try:  # current surface: jax.shard_map(..., check_vma=...)
    from jax import shard_map as _shard_map_new  # type: ignore[attr-defined]
except ImportError:
    _shard_map_new = None

_PCAST = getattr(jax.lax, "pcast", None)


def shard_map(f: Callable[..., Any], *, mesh: Any, in_specs: Any,
              out_specs: Any, check_vma: bool = True) -> Callable[..., Any]:
    """``jax.shard_map`` with a ``jax.experimental.shard_map`` fallback.

    ``check_vma`` maps onto the old API's ``check_rep``; on the fallback
    path it is forced off (see module docstring).
    """
    if _shard_map_new is not None:
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map_old

    return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def pcast_varying(x: Any, axis_name: str) -> Any:
    """Mark ``x`` device-varying over ``axis_name`` (loop-carry hygiene
    under the varying-axes checker); identity where ``pcast`` does not
    exist — the fallback ``shard_map`` runs with the checker off."""
    if _PCAST is None:
        return x
    return _PCAST(x, axis_name, to="varying")
