"""Expert parallelism: all_to_all dispatch for the MoE estimator.

Dense MoE (`kepler_tpu.models.moe.predict_moe`) runs every expert on every
row — fine for a handful of tiny experts on one chip, wasteful once the
fleet has many node types or the per-type models grow. This module shards
the expert axis over devices and moves **rows to their expert** instead:

    rows [B, F], experts sharded E/n per device
    → top-1 route (explicit node-type id, or learned gate)
    → one-hot dispatch [B_loc, E, C]  (capacity C, cumsum positions)
    → all_to_all: each device receives the rows routed to ITS experts
    → batched expert MLP on local experts only
    → all_to_all back, combine with gate weight

The two collectives are the classic MoE all_to_all pair (Switch/GShard
dispatch–combine, cf. PAPERS.md), riding ICI inside one shard_map; every
other op is a batched einsum. With explicit routing the EP result is
bit-comparable to dense routing — `tests/test_expert.py` asserts it.

Default capacity is lossless (C = per-device row count, covering the
worst case of every local row choosing one expert); pass
``capacity_factor`` < 1 for Switch-style bounded buffers where overflow
rows fall back to zero watts (callers then blend with ratio attribution,
the same degraded-zone philosophy as the reference's skip-on-error,
`internal/monitor/node.go:39-44`).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.models.moe import MoEParams, expert_forward, gate_logits
from kepler_tpu.parallel.compat import shard_map

EXPERT_AXIS = "expert"


def _ep_shard(params, x, expert_id, gate_prob, *, axis_name, capacity,
              compute_dtype):
    """Per-device body. x [B_loc, F]; params hold E_loc local experts."""
    n = jax.lax.psum(1, axis_name)
    e_loc = params["w0"].shape[0]
    e = e_loc * n  # global expert count
    b_loc = x.shape[0]
    c = capacity

    # positions within each expert's capacity buffer (over local rows)
    onehot = jax.nn.one_hot(expert_id, e, dtype=jnp.int32)  # [B_loc, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # [B_loc, E], -1 if unrouted
    keep = (pos >= 0) & (pos < c)
    dispatch = (jax.nn.one_hot(pos.clip(0), c, dtype=jnp.float32)
                * keep[..., None])  # [B_loc, E, C]

    # group rows per global expert, then exchange: axis 0 = destination dev
    ex_in = jnp.einsum("bec,bf->ecf", dispatch, x)  # [E, C, F]
    ex_in = ex_in.reshape(n, e_loc, c, -1)
    ex_in = jax.lax.all_to_all(ex_in, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)  # [n(src), E_loc, C, F]
    ex_in = ex_in.transpose(1, 0, 2, 3).reshape(e_loc, n * c, -1)

    ex_out = expert_forward(params, ex_in, compute_dtype)  # [E_loc, n*C, Z]

    z = ex_out.shape[-1]
    ex_out = ex_out.reshape(e_loc, n, c, z).transpose(1, 0, 2, 3)
    ex_out = jax.lax.all_to_all(ex_out, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)
    ex_out = ex_out.reshape(e, c, z)  # [E, C, Z], rows back home

    combine = dispatch * gate_prob[:, None, None]  # [B_loc, E, C]
    return jnp.einsum("bec,ecz->bz", combine, ex_out)  # [B_loc, Z]


def make_expert_parallel_moe(
    mesh: Mesh,
    *,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.0,
    rows_per_device: int | None = None,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """→ jitted ``(params, features[B,F], expert_id[B], gate_prob[B]) → [B,Z]``.

    ``B`` must divide by the ``axis_name`` mesh size; the global expert
    count must divide by it too (params shard on their leading E axis).
    ``expert_id`` is the per-row routing decision (node type, or
    ``top1_route``'s argmax); ``gate_prob`` its combine weight (1.0 for
    explicit routing). ``capacity_factor`` scales the lossless per-device
    buffer (1.0 = never drop).
    """
    n = mesh.shape[axis_name]
    rows = NamedSharding(mesh, P(axis_name))
    # expert weights shard on their leading E axis; the router's gate_w is
    # [F, E] (E is axis 1) and is only read OUTSIDE the shard_map anyway
    p_spec = dict(gate_w=P(None, axis_name), w0=P(axis_name),
                  b0=P(axis_name), w1=P(axis_name), b1=P(axis_name),
                  w_skip=P(axis_name))
    p_shard = {k: NamedSharding(mesh, s) for k, s in p_spec.items()}
    expert_keys = ("w0", "b0", "w1", "b1", "w_skip")

    def fn(params, features, expert_id, gate_prob):
        b_loc = features.shape[0] // n
        capacity = max(1, math.ceil(b_loc * capacity_factor))
        body = functools.partial(_ep_shard, axis_name=axis_name,
                                 capacity=capacity,
                                 compute_dtype=compute_dtype)
        experts = {k: params[k] for k in expert_keys}
        return shard_map(
            body,
            mesh=mesh,
            in_specs=({k: P(axis_name) for k in expert_keys},
                      P(axis_name), P(axis_name), P(axis_name)),
            out_specs=P(axis_name),
        )(experts, features, expert_id, gate_prob)

    _ = rows_per_device  # shapes are static under jit; kept for API clarity
    return jax.jit(fn, in_shardings=(p_shard, rows, rows, rows),
                   out_shardings=rows)


def top1_route(params: MoEParams, features: jax.Array):
    """Learned routing → (expert_id int32 [B], gate_prob f32 [B]).

    Switch-style: argmax expert, combine-weighted by its softmax prob.
    """
    logits = gate_logits(params, features)
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return idx, jnp.take_along_axis(probs, idx[..., None], axis=-1)[..., 0]
