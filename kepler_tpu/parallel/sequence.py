"""Sequence/context parallelism for the temporal estimator.

Long feature-history windows (`kepler_tpu.models.temporal`) shard their
time axis over the ``seq`` mesh axis: the pointwise trunk ops (in-proj,
LayerNorms, MLP, head) are per-timestep and shard trivially via GSPMD
sharding annotations, while attention — the only cross-timestep op —
runs as the shard-mapped ring kernel (`kepler_tpu.parallel.ring`), so no
device ever holds the full K/V sequence. The last-valid-timestep pooling
gathers one row per workload across shards, which XLA lowers to a tiny
collective.

`tests/test_ring.py` asserts this program matches single-device dense
attention on an 8-way virtual mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.models.temporal import TemporalParams, predict_temporal
from kepler_tpu.parallel.ring import SEQ_AXIS, ring_attention_shardmap


def make_temporal_program(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """→ jitted ``(params, feat_hist[W,T,F], workload_valid[W], t_valid[W,T])
    → watts [W,Z]`` with T sharded over ``axis_name``.

    T must divide by the mesh's ``axis_name`` size. Params replicate (the
    model is tiny; memory pressure lives in the sequence, not the weights).
    """
    hist = NamedSharding(mesh, P(None, axis_name))
    rep = NamedSharding(mesh, P())
    ring = ring_attention_shardmap(mesh, axis_name=axis_name, causal=True,
                                   compute_dtype=compute_dtype)

    def fn(params: TemporalParams, feat_hist, workload_valid, t_valid):
        return predict_temporal(params, feat_hist, workload_valid, t_valid,
                                clamp=clamp, compute_dtype=compute_dtype,
                                attention_fn=ring)

    return jax.jit(fn, in_shardings=(rep, hist, rep, hist),
                   out_shardings=rep)


def make_sequence_parallel_train_step(
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    axis_name: str = SEQ_AXIS,
    compute_dtype: jnp.dtype = jnp.float32,
    remat: bool = False,
):
    """Long-context TRAINING: gradients flow through ring attention.

    → jitted ``(state, feat_hist [W, T, F], workload_valid [W],
    t_valid [W, T], target_watts [W, Z]) → (state, loss)`` with T sharded
    over ``axis_name`` — the backward pass reverses the KV ring (ppermute's
    transpose is the opposite rotation; the blockwise fori_loop has a
    static trip count, so it lowers to a differentiable scan).

    ``remat=True`` wraps the forward in ``jax.checkpoint``: activations of
    the trunk recompute in the backward instead of living in HBM for the
    whole window — the standard FLOPs-for-memory trade once T is long.

    The input ``state`` is DONATED (its buffers are reused for the updated
    state, halving optimizer memory) — do not read it after the call;
    step repeatedly as ``state, loss = step(state, ...)``. The step body
    is `models.train.temporal_step_fn` — identical maths to the local
    :func:`make_temporal_train_step`, jitted here with seq shardings.
    """
    from kepler_tpu.models.train import temporal_step_fn

    hist = NamedSharding(mesh, P(None, axis_name))
    rep = NamedSharding(mesh, P())
    ring = ring_attention_shardmap(mesh, axis_name=axis_name, causal=True,
                                   compute_dtype=compute_dtype)
    step = temporal_step_fn(optimizer, compute_dtype, attention_fn=ring,
                            remat=remat)
    return jax.jit(step, in_shardings=(None, hist, rep, hist, rep),
                   donate_argnums=(0,))
