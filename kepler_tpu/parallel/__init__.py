"""Parallel/distributed layer: mesh, sharded fleet attribution, trainer."""

from kepler_tpu.parallel.aggregator_core import (
    FleetResult,
    fleet_attribution_program,
    make_fleet_program,
    run_fleet_attribution,
)
from kepler_tpu.parallel.fleet import (
    MODE_MODEL,
    MODE_RATIO,
    FleetBatch,
    NodeReport,
    assemble_fleet_batch,
)
from kepler_tpu.parallel.mesh import MODEL_AXIS, NODE_AXIS, make_mesh
from kepler_tpu.parallel.trainer import (
    make_distributed_train_step,
    mlp_param_shardings,
    shard_train_state,
)

__all__ = [
    "FleetBatch",
    "FleetResult",
    "MODE_MODEL",
    "MODE_RATIO",
    "MODEL_AXIS",
    "NODE_AXIS",
    "NodeReport",
    "assemble_fleet_batch",
    "fleet_attribution_program",
    "make_distributed_train_step",
    "make_fleet_program",
    "make_mesh",
    "mlp_param_shardings",
    "run_fleet_attribution",
    "shard_train_state",
]
