"""Parallel/distributed layer: mesh, sharded fleet attribution, trainer."""

from kepler_tpu.parallel.aggregator_core import (
    FleetResult,
    fleet_attribution_program,
    make_fleet_program,
    make_temporal_fleet_program,
    run_fleet_attribution,
    temporal_fleet_program,
)
from kepler_tpu.parallel.fleet import (
    MODE_MODEL,
    MODE_RATIO,
    FleetBatch,
    NodeReport,
    assemble_fleet_batch,
)
from kepler_tpu.parallel.expert import (
    EXPERT_AXIS,
    make_expert_parallel_moe,
    top1_route,
)
from kepler_tpu.parallel.mesh import (
    MODEL_AXIS,
    NODE_AXIS,
    MultihostInit,
    initialize_multihost,
    make_mesh,
    multihost_status,
    submesh_for_processes,
)
from kepler_tpu.parallel.pipeline import (
    STAGE_AXIS,
    make_pipeline,
    make_pipelined_deep,
)
from kepler_tpu.parallel.ulysses import (
    make_ulysses_attention,
    make_ulysses_temporal_program,
    ulysses_attention_shardmap,
)
from kepler_tpu.parallel.ring import (
    SEQ_AXIS,
    full_attention,
    make_ring_attention,
)
from kepler_tpu.parallel.sequence import (
    make_sequence_parallel_train_step,
    make_temporal_program,
)
from kepler_tpu.parallel.trainer import (
    make_distributed_train_step,
    mlp_param_shardings,
    shard_train_state,
)

__all__ = [
    "EXPERT_AXIS",
    "SEQ_AXIS",
    "STAGE_AXIS",
    "full_attention",
    "make_expert_parallel_moe",
    "make_pipeline",
    "make_pipelined_deep",
    "make_temporal_fleet_program",
    "temporal_fleet_program",
    "make_ring_attention",
    "make_ulysses_attention",
    "make_ulysses_temporal_program",
    "ulysses_attention_shardmap",
    "make_sequence_parallel_train_step",
    "make_temporal_program",
    "top1_route",
    "FleetBatch",
    "FleetResult",
    "MODE_MODEL",
    "MODE_RATIO",
    "MODEL_AXIS",
    "NODE_AXIS",
    "NodeReport",
    "assemble_fleet_batch",
    "fleet_attribution_program",
    "make_distributed_train_step",
    "make_fleet_program",
    "initialize_multihost",
    "make_mesh",
    "submesh_for_processes",
    "MultihostInit",
    "multihost_status",
    "mlp_param_shardings",
    "run_fleet_attribution",
    "shard_train_state",
]
