"""Fleet batch assembly: ragged per-node reports → one padded tensor.

SURVEY §7 hard part (a): pods-per-node varies wildly; shapes must come from
a small bucket set or every fleet composition change recompiles. Nodes pad
to ``node_bucket`` multiples, workloads to ``workload_bucket`` multiples;
masks make padding contribute exact zeros (the batched analog of the
reference's skip-on-error, SURVEY §5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import numpy as np

from kepler_tpu.ops.attribution import pad_to_bucket

# estimator-mode codes carried in the fleet tensor (models/estimator.py)
MODE_RATIO = 0
MODE_MODEL = 1


@dataclass
class NodeReport:
    """One node's feature rows for one window (the gRPC wire payload)."""

    node_name: str
    zone_deltas_uj: np.ndarray  # f32/f64 [Z]
    zone_valid: np.ndarray  # bool [Z]
    usage_ratio: float
    cpu_deltas: np.ndarray  # f32 [w] (ragged)
    workload_ids: list[str]
    node_cpu_delta: float
    dt_s: float
    mode: int = MODE_RATIO  # MODE_RATIO on RAPL nodes, MODE_MODEL otherwise
    workload_kinds: np.ndarray | None = None  # int8 [w], optional
    meta: Mapping[str, str] = field(default_factory=dict)


@dataclass
class FleetBatch:
    """Dense padded arrays, shapes [N, ...] with N/W bucketed."""

    node_names: list[str]  # first n_nodes entries real, rest ""
    n_nodes: int  # real node count
    workload_counts: list[int]  # real workload count per node row
    workload_ids: list[list[str]]
    zone_deltas_uj: np.ndarray  # f32 [N, Z]
    zone_valid: np.ndarray  # bool [N, Z]
    usage_ratio: np.ndarray  # f32 [N]
    cpu_deltas: np.ndarray  # f32 [N, W]
    workload_valid: np.ndarray  # bool [N, W]
    node_cpu_delta: np.ndarray  # f32 [N]
    dt_s: np.ndarray  # f32 [N]
    mode: np.ndarray  # int32 [N]

    @property
    def shape(self) -> tuple[int, int, int]:
        n, w = self.cpu_deltas.shape
        return n, w, self.zone_deltas_uj.shape[1]


def assemble_fleet_batch(
    reports: Sequence[NodeReport],
    n_zones: int,
    node_bucket: int = 8,
    workload_bucket: int = 256,
    zone_deltas_mat: np.ndarray | None = None,
    zone_valid_mat: np.ndarray | None = None,
) -> FleetBatch:
    """Pad/mask ragged node reports into one rectangular batch.

    Missing nodes simply aren't rows; a node that reported unreadable zones
    keeps its row with those zones masked. Shapes are
    ``[pad(N), pad(max_w)]`` so the jit cache sees O(buckets²) shapes, not
    O(fleet compositions).

    ``zone_deltas_mat`` / ``zone_valid_mat``: optional pre-aligned
    ``[n_real, n_zones]`` matrices (the aggregator's grouped zone-align
    produces them directly); when given, the per-report zone arrays are
    not touched.
    """
    n_real = len(reports)
    n = pad_to_bucket(max(n_real, 1), node_bucket)
    max_w = max((len(r.cpu_deltas) for r in reports), default=1)
    w = pad_to_bucket(max_w, workload_bucket)

    cpu = np.zeros((n, w), np.float32)
    valid = np.zeros((n, w), bool)
    if n_real:
        zone_deltas = np.zeros((n, n_zones), np.float32)
        zone_valid = np.zeros((n, n_zones), bool)
        if zone_deltas_mat is not None:
            if zone_deltas_mat.shape != (n_real, n_zones):
                raise ValueError(
                    f"zone matrix shape {zone_deltas_mat.shape}, expected "
                    f"({n_real}, {n_zones})")
            zone_deltas[:n_real] = zone_deltas_mat
            zone_valid[:n_real] = zone_valid_mat
        else:
            for r in reports:
                zd = np.asarray(r.zone_deltas_uj)
                if zd.shape != (n_zones,):
                    raise ValueError(
                        f"node {r.node_name}: {zd.shape} zones, expected "
                        f"({n_zones},)")
                zv = np.asarray(r.zone_valid)
                if zv.shape != (n_zones,):
                    raise ValueError(
                        f"node {r.node_name}: zone_valid shape {zv.shape}, "
                        f"expected ({n_zones},)")
            zone_deltas[:n_real] = np.stack(
                [np.asarray(r.zone_deltas_uj, np.float32)
                 for r in reports])
            zone_valid[:n_real] = np.stack(
                [np.asarray(r.zone_valid, bool) for r in reports])
        usage = np.zeros(n, np.float32)
        usage[:n_real] = np.fromiter((r.usage_ratio for r in reports),
                                     np.float64, n_real)
        node_delta = np.zeros(n, np.float32)
        node_delta[:n_real] = np.fromiter(
            (r.node_cpu_delta for r in reports), np.float64, n_real)
        dt = np.zeros(n, np.float32)
        dt[:n_real] = np.fromiter((r.dt_s for r in reports), np.float64,
                                  n_real)
        mode = np.zeros(n, np.int32)
        mode[:n_real] = np.fromiter((r.mode for r in reports), np.int64,
                                    n_real)
        # ragged cpu rows → one flat concat + a vectorized 2-D scatter
        # (the per-row python assignments used to dominate 1k-node windows)
        lengths = np.fromiter((len(r.cpu_deltas) for r in reports),
                              np.int64, n_real)
        total = int(lengths.sum())
        if total:
            flat = np.concatenate(
                [np.asarray(r.cpu_deltas, np.float32) for r in reports])
            rows = np.repeat(np.arange(n_real), lengths)
            starts = np.concatenate(([0], np.cumsum(lengths)[:-1]))
            cols = np.arange(total) - np.repeat(starts, lengths)
            cpu[rows, cols] = flat
            valid[rows, cols] = True
        counts = lengths.tolist()
        names = [r.node_name for r in reports]
        # id lists are referenced, not copied — callers treat reports as
        # immutable once handed over (the wire decoder builds fresh lists)
        ids = [r.workload_ids for r in reports]
    else:
        zone_deltas = np.zeros((n, n_zones), np.float32)
        zone_valid = np.zeros((n, n_zones), bool)
        usage = np.zeros(n, np.float32)
        node_delta = np.zeros(n, np.float32)
        dt = np.zeros(n, np.float32)
        mode = np.zeros(n, np.int32)
        names, counts, ids = [], [], []

    names += [""] * (n - n_real)
    counts += [0] * (n - n_real)
    ids += [[] for _ in range(n - n_real)]

    return FleetBatch(
        node_names=names, n_nodes=n_real, workload_counts=counts,
        workload_ids=ids, zone_deltas_uj=zone_deltas, zone_valid=zone_valid,
        usage_ratio=usage, cpu_deltas=cpu, workload_valid=valid,
        node_cpu_delta=node_delta, dt_s=dt, mode=mode,
    )
