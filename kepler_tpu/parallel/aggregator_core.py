"""The sharded cluster-attribution program.

BASELINE.json north star: gather per-node feature rows, evaluate
ratio-attribution AND learned estimators as one batched computation over
``[nodes × pods × features]`` on TPU, scatter watts back per node.

Sharding: the node axis spreads across the mesh's ``node`` axis (each device
attributes its slice of the fleet — pure data parallelism, zero collectives
in the forward program since every reduction is within one node's row).
Model params are replicated (tiny) or tensor-sharded over ``model``
(see ``kepler_tpu.parallel.trainer``). XLA GSPMD propagates shardings from
the input annotations; there are no hand-placed collectives here.

Mixed fleets (config 5): both paths evaluate for every node (the model is a
pair of matmuls — cheaper than a branch on TPU, and `lax.cond` over a
batched axis would serialize anyway); `jnp.where` on the per-node mode code
selects the result. RAPL nodes get ratio watts, non-RAPL nodes get model
watts scaled onto their (unknown) zone axis.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.models.estimator import predictor
from kepler_tpu.models.features import build_features
from kepler_tpu.ops.attribution import AttributionResult, attribute_fleet
from kepler_tpu.parallel.fleet import MODE_MODEL, FleetBatch
from kepler_tpu.parallel.mesh import NODE_AXIS


class FleetResult(NamedTuple):
    node_energy_uj: jax.Array  # [N, Z]
    node_active_uj: jax.Array  # [N, Z]
    node_idle_uj: jax.Array  # [N, Z]
    node_power_uw: jax.Array  # [N, Z]
    node_active_power_uw: jax.Array  # [N, Z]
    node_idle_power_uw: jax.Array  # [N, Z]
    workload_energy_uj: jax.Array  # [N, W, Z]
    workload_power_uw: jax.Array  # [N, W, Z]


def _ratio_only_result(ratio: AttributionResult) -> FleetResult:
    return FleetResult(
        node_energy_uj=ratio.node.energy_uj,
        node_active_uj=ratio.node.active_uj,
        node_idle_uj=ratio.node.idle_uj,
        node_power_uw=ratio.node.power_uw,
        node_active_power_uw=ratio.node.active_power_uw,
        node_idle_power_uw=ratio.node.idle_power_uw,
        workload_energy_uj=ratio.workloads.energy_uj,
        workload_power_uw=ratio.workloads.power_uw,
    )


def mix_model_watts(
    ratio: AttributionResult,
    model_watts: jax.Array,  # f32 [N, W, Z] estimator output (watts)
    mode: jax.Array,  # int32 [N]
    dt_s: jax.Array,  # f32 [N]
) -> FleetResult:
    """Per-node select: RAPL nodes keep ratio watts, MODE_MODEL nodes take
    the estimator's. Shared by the single-tick and temporal fleet programs."""
    model_power_uw = model_watts * 1e6  # watts → µW
    model_energy_uj = model_power_uw * dt_s[:, None, None]  # µW·s = µJ
    is_model = (mode == MODE_MODEL)[:, None, None]
    wl_power = jnp.where(is_model, model_power_uw, ratio.workloads.power_uw)
    wl_energy = jnp.where(is_model, model_energy_uj,
                          ratio.workloads.energy_uj)
    # model-mode nodes have no RAPL; their node totals are the sum of
    # model-estimated workload power (active == total, idle unknown → 0)
    est_node_power = jnp.sum(model_power_uw, axis=1)  # [N, Z]
    est_node_energy = jnp.sum(model_energy_uj, axis=1)
    is_model_nz = (mode == MODE_MODEL)[:, None]
    return FleetResult(
        node_energy_uj=jnp.where(is_model_nz, est_node_energy,
                                 ratio.node.energy_uj),
        node_active_uj=jnp.where(is_model_nz, est_node_energy,
                                 ratio.node.active_uj),
        node_idle_uj=jnp.where(is_model_nz, 0.0, ratio.node.idle_uj),
        node_power_uw=jnp.where(is_model_nz, est_node_power,
                                ratio.node.power_uw),
        node_active_power_uw=jnp.where(is_model_nz, est_node_power,
                                       ratio.node.active_power_uw),
        node_idle_power_uw=jnp.where(is_model_nz, 0.0,
                                     ratio.node.idle_power_uw),
        workload_energy_uj=wl_energy,
        workload_power_uw=wl_power,
    )


def fleet_attribution_program(
    model_params: Any,
    zone_deltas_uj: jax.Array,  # f32 [N, Z]
    zone_valid: jax.Array,  # bool [N, Z]
    usage_ratio: jax.Array,  # f32 [N]
    cpu_deltas: jax.Array,  # f32 [N, W]
    workload_valid: jax.Array,  # bool [N, W]
    node_cpu_delta: jax.Array,  # f32 [N]
    dt_s: jax.Array,  # f32 [N]
    mode: jax.Array,  # int32 [N] MODE_RATIO / MODE_MODEL
    *,
    predict_fn,
    attribute_fn=attribute_fleet,
) -> FleetResult:
    """The pure program; wrap with jit+shardings via ``make_fleet_program``."""
    ratio = attribute_fn(
        zone_deltas_uj, zone_valid, usage_ratio, cpu_deltas,
        workload_valid, node_cpu_delta, dt_s,
    )
    if predict_fn is None:
        return _ratio_only_result(ratio)
    feats = build_features(cpu_deltas, workload_valid, node_cpu_delta,
                           usage_ratio, dt_s)
    model_watts = predict_fn(model_params, feats, workload_valid)
    return mix_model_watts(ratio, model_watts, mode, dt_s)


def temporal_fleet_program(
    model_params: Any,
    zone_deltas_uj: jax.Array,  # f32 [N, Z]
    zone_valid: jax.Array,  # bool [N, Z]
    usage_ratio: jax.Array,  # f32 [N]
    cpu_deltas: jax.Array,  # f32 [N, W]
    workload_valid: jax.Array,  # bool [N, W]
    node_cpu_delta: jax.Array,  # f32 [N]
    dt_s: jax.Array,  # f32 [N]
    mode: jax.Array,  # int32 [N]
    feat_hist: jax.Array,  # f32 [N, W, T, F] per-workload history windows
    t_valid: jax.Array,  # bool [N, W, T]
    *,
    attribute_fn=attribute_fleet,
    accuracy_mode: bool = False,
) -> FleetResult:
    """Mixed fleet with the TEMPORAL estimator: the aggregator accretes each
    workload's feature history (`kepler_tpu.monitor.history`) and the model
    predicts from the whole window instead of the last tick."""
    from kepler_tpu.models.temporal import predict_temporal

    ratio = attribute_fn(
        zone_deltas_uj, zone_valid, usage_ratio, cpu_deltas,
        workload_valid, node_cpu_delta, dt_s,
    )
    pfn = (accuracy_mode_predictor(predict_temporal, "temporal")
           if accuracy_mode else predict_temporal)
    watts = pfn(model_params, feat_hist, workload_valid, t_valid=t_valid)
    return mix_model_watts(ratio, watts, mode, dt_s)


def resolve_attribute_fn(mesh: Mesh, backend: str):
    """→ the fleet-attribution contraction for ``backend``.

    "einsum" lets XLA fuse it; "pallas" binds the Mosaic kernel with
    interpret mode engaged automatically off-TPU. Shared by the sharded
    and packed-transfer program builders.
    """
    if backend == "pallas":
        from kepler_tpu.ops.pallas_attribution import attribute_fleet_pallas
        interpret = mesh.devices.flat[0].platform != "tpu"
        return functools.partial(attribute_fleet_pallas, interpret=interpret)
    if backend == "einsum":
        return attribute_fleet
    raise ValueError(f"unknown attribution backend {backend!r}; "
                     "valid: einsum, pallas")


def shard_by_node(fn, mesh: Mesh, in_specs):
    """shard_map ``fn`` over the node axis (pallas-backend program builders).

    pallas_call has no SPMD partitioning rule, so the kernel must run
    per-shard; the fleet forward has no cross-node math, so this changes
    layout, not semantics. check_vma=False because pallas_call defeats the
    varying-axes checker.
    """
    from kepler_tpu.parallel.compat import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=P(NODE_AXIS), check_vma=False)


def accuracy_mode_predictor(predict_fn, model_mode: str):
    """Wrap a registry predictor for ACCURACY-mode serving: f32 compute
    dtype (bf16 trunks carry ~1e-3 relative noise — twice the whole 0.5%
    budget) and matmul precision HIGHEST for the estimator's ops (TPU
    "f32" matmuls otherwise run one bf16 MXU pass). Estimator shapes are
    tiny, so the 3-pass cost is invisible; the bulk ratio-attribution
    contraction stays OUTSIDE the wrapper at default precision — this is
    the configuration `benchmarks/accuracy.py` validates to p99 ≤ 0.5%.
    """
    kw = {} if model_mode == "linear" else {"compute_dtype": jnp.float32}

    def wrapped(params, feats, workload_valid, **extra):
        with jax.default_matmul_precision("highest"):
            return predict_fn(params, feats, workload_valid, **kw, **extra)

    return wrapped


def make_fleet_program(mesh: Mesh, model_mode: str | None = None,
                       backend: str = "einsum",
                       accuracy_mode: bool = False):
    """jit the fleet program with node-axis shardings over ``mesh``.

    ``model_mode``: None = ratio only; "linear"/"mlp" compiles that
    predictor into the program for mixed fleets.

    ``accuracy_mode``: serve the estimator at f32/highest precision (see
    :func:`accuracy_mode_predictor`); default bf16 is the throughput mode.

    ``backend``: "einsum" lets XLA fuse the attribution contraction;
    "pallas" runs it as the hand-written Mosaic kernel
    (``ops.pallas_attribution``), wrapped in ``shard_map`` over the node
    axis so each device executes the kernel on its local shard (the
    forward has no cross-node math, so this changes layout, not
    semantics; interpret mode engages automatically off-TPU).
    """
    predict_fn = predictor(model_mode) if model_mode else None
    if predict_fn is not None and accuracy_mode:
        predict_fn = accuracy_mode_predictor(predict_fn, model_mode)
    by_node_2d = NamedSharding(mesh, P(NODE_AXIS, None))
    by_node_1d = NamedSharding(mesh, P(NODE_AXIS))
    replicated = NamedSharding(mesh, P())

    attribute_fn = resolve_attribute_fn(mesh, backend)
    fn = functools.partial(fleet_attribution_program,
                           predict_fn=predict_fn,
                           attribute_fn=attribute_fn)
    if backend == "pallas":
        data_specs = (P(NODE_AXIS, None), P(NODE_AXIS, None), P(NODE_AXIS),
                      P(NODE_AXIS, None), P(NODE_AXIS, None), P(NODE_AXIS),
                      P(NODE_AXIS), P(NODE_AXIS))
        fn = shard_by_node(fn, mesh, in_specs=(P(),) + data_specs)
    return jax.jit(
        fn,
        in_shardings=(
            replicated,  # model params (tiny; tensor-sharded in trainer)
            by_node_2d,  # zone_deltas
            by_node_2d,  # zone_valid
            by_node_1d,  # usage_ratio
            by_node_2d,  # cpu_deltas
            by_node_2d,  # workload_valid
            by_node_1d,  # node_cpu_delta
            by_node_1d,  # dt
            by_node_1d,  # mode
        ),
        out_shardings=NamedSharding(mesh, P(NODE_AXIS)),
    )


def make_temporal_fleet_program(mesh: Mesh, backend: str = "einsum",
                                accuracy_mode: bool = False):
    """jit the TEMPORAL fleet program (extra ``feat_hist``/``t_valid``
    inputs, node-axis sharded). Params replicate — the model is tiny; for
    very long windows serve through ``parallel.sequence`` instead."""
    by_node = NamedSharding(mesh, P(NODE_AXIS))
    replicated = NamedSharding(mesh, P())
    fn = functools.partial(temporal_fleet_program,
                           attribute_fn=resolve_attribute_fn(mesh, backend),
                           accuracy_mode=accuracy_mode)
    if backend == "pallas":
        data_specs = (P(NODE_AXIS, None), P(NODE_AXIS, None), P(NODE_AXIS),
                      P(NODE_AXIS, None), P(NODE_AXIS, None), P(NODE_AXIS),
                      P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
                      P(NODE_AXIS))
        fn = shard_by_node(fn, mesh, in_specs=(P(),) + data_specs)
    return jax.jit(
        fn,
        in_shardings=(replicated,) + (by_node,) * 10,
        out_shardings=by_node,
    )


def run_fleet_attribution(
    program,
    batch: FleetBatch,
    model_params: Any = None,
    feat_hist=None,  # [N, W, T, F] — temporal programs only
    t_valid=None,  # [N, W, T]
) -> FleetResult:
    """Host entry: device_put the padded batch and run one sharded step."""
    args = [
        model_params if model_params is not None else jnp.zeros(()),
        jnp.asarray(batch.zone_deltas_uj),
        jnp.asarray(batch.zone_valid),
        jnp.asarray(batch.usage_ratio),
        jnp.asarray(batch.cpu_deltas),
        jnp.asarray(batch.workload_valid),
        jnp.asarray(batch.node_cpu_delta),
        jnp.asarray(batch.dt_s),
        jnp.asarray(batch.mode),
    ]
    if feat_hist is not None:
        args += [jnp.asarray(feat_hist), jnp.asarray(t_valid)]
    return program(*args)
