"""Packed-transfer fleet attribution: one H2D, one dispatch, one D2H.

Motivation: on network-attached TPU (and over the dev tunnel this repo
benches through) every host↔device transfer pays a large fixed latency, so
a step that moves 9 input arrays and 2 outputs spends its p99 in round
trips, not compute. This module packs the whole fleet window into ONE f32
input array and the whole scatter-back payload into ONE f16 output array:

  input  [N, W + 2Z + 4]  — cpu | zone | zone_valid | ratio, denom, dt, mode
  output [N, W + 2, Z]    — per-workload watts, with node ACTIVE watts and
                            node TOTAL watts as the two extra rows (f16:
                            watts stay well inside half range and carry
                            ~0.05% error, inside the 0.5%-of-RAPL budget;
                            µW or µJ would overflow)

The unpack/slice lives inside the jitted program, so XLA fuses it with the
attribution math and the device sees exactly one executable.

Sparse model evaluation (``model_bucket``): mixed fleets evaluate BOTH
paths for every node in the dense program ("cheaper than a branch on
TPU"), but the estimator is the whole device leg at fleet shapes — an MLP
forward over [N·W] rows whose output is discarded for every MODE_RATIO
node. The sparse variant takes an extra ``model_rows`` index vector
(padded with N — gather clamps, scatter drops) and runs the estimator
only on the gathered MODE_MODEL rows: bit-identical outputs at half the
FLOPs on a 50/50 fleet. The row-index gather has no shard_map story, so
the sparse variant is einsum-backend only; pallas keeps the dense
program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.parallel.aggregator_core import (
    FleetResult,
    fleet_attribution_program,
    mix_model_watts,
    resolve_attribute_fn,
    shard_by_node,
)
from kepler_tpu.parallel.fleet import MODE_MODEL, FleetBatch, NodeReport
from kepler_tpu.parallel.mesh import NODE_AXIS
from kepler_tpu.models.estimator import predictor

# packed output layout: the two synthetic rows appended after the W
# workload rows (kept as named offsets so unpackers and the window
# engine agree by construction)
ROW_NODE_ACTIVE = -2
ROW_NODE_TOTAL = -1


# keplint: layout-definition
@dataclass(frozen=True)
class PackedLayout:
    """THE packed input-row layout — the single source of truth.

    One f32 row is ``cpu[W] | zone[Z] | zone_valid[Z] | ratio, denom,
    dt, mode``. Every producer and consumer of packed rows — the jitted
    device programs here, the ``fleet.window`` staging engines, and the
    pure-NumPy rung-3 mirror (:func:`numpy_fleet_window`) — derives its
    offsets from this class, so the jax program and its host fallback
    cannot drift apart silently. Raw layout-offset arithmetic anywhere
    outside this class is a keplint finding (KTL114 ``packed-layout``);
    this is the only ``layout-definition``-marked scope.
    """

    n_workloads: int
    n_zones: int

    @property
    def width(self) -> int:
        """Total packed row width."""
        return self.n_workloads + 2 * self.n_zones + 4

    @property
    def cpu(self) -> slice:
        """Per-workload cpu-delta columns (NaN = invalid slot)."""
        return slice(0, self.n_workloads)

    @property
    def zone(self) -> slice:
        """Per-zone energy-delta columns (µJ)."""
        return slice(self.n_workloads, self.n_workloads + self.n_zones)

    @property
    def zone_valid(self) -> slice:
        """Per-zone validity columns (0.0/1.0)."""
        return slice(self.n_workloads + self.n_zones,
                     self.n_workloads + 2 * self.n_zones)

    @property
    def col_ratio(self) -> int:
        return self.n_workloads + 2 * self.n_zones + 0

    @property
    def col_denom(self) -> int:
        return self.n_workloads + 2 * self.n_zones + 1

    @property
    def col_dt(self) -> int:
        return self.n_workloads + 2 * self.n_zones + 2

    @property
    def col_mode(self) -> int:
        return self.n_workloads + 2 * self.n_zones + 3

    def empty_row(self) -> np.ndarray:
        """One packed row holding no node: zeros, cpu columns NaN (no
        valid workload slots) — what cleared resident rows scatter."""
        row = np.zeros(self.width, np.float32)
        row[self.cpu] = np.nan
        return row


def packed_width(n_workloads: int, n_zones: int) -> int:
    """Row width of the packed INPUT layout."""
    return PackedLayout(n_workloads, n_zones).width


def pack_fleet_inputs(batch: FleetBatch,
                      out: np.ndarray | None = None) -> np.ndarray:
    """FleetBatch → one f32 [N, W + 2Z + 4] host array (one H2D).

    ``out``: optional preallocated destination (the window engine's
    reusable staging buffer); a fresh array is returned when absent or
    mis-shaped.
    """
    n, w, z = batch.shape
    lay = PackedLayout(w, z)
    if out is None or out.shape != (n, lay.width):
        out = np.empty((n, lay.width), np.float32)
    # invalid workload slots ride as NaN in the cpu column — no separate
    # mask plane needed in the packed layout
    out[:, lay.cpu] = np.where(batch.workload_valid, batch.cpu_deltas,
                               np.nan)
    out[:, lay.zone] = batch.zone_deltas_uj
    out[:, lay.zone_valid] = batch.zone_valid
    out[:, lay.col_ratio] = batch.usage_ratio
    out[:, lay.col_denom] = batch.node_cpu_delta
    out[:, lay.col_dt] = batch.dt_s
    out[:, lay.col_mode] = batch.mode
    return out


def pack_reports_into(out: np.ndarray, reports: Sequence[NodeReport],
                      zone_deltas_mat: np.ndarray,
                      zone_valid_mat: np.ndarray,
                      n_workloads: int) -> None:
    """Pack ragged reports straight into ``out[:len(reports)]`` (packed
    row layout) without materializing an intermediate FleetBatch — the
    delta-H2D staging path packs every window, so the extra cpu/valid
    planes and the NaN-merge pass the two-step route pays are real
    milliseconds at fleet scale. Rows beyond each report's workload
    count stay NaN (invalid)."""
    n = len(reports)
    lay = PackedLayout(n_workloads, zone_deltas_mat.shape[1])
    out[:n, lay.cpu] = np.nan
    lengths = np.fromiter((len(r.cpu_deltas) for r in reports),
                          np.int64, n)
    total = int(lengths.sum())
    if total:
        flat = np.concatenate(
            [np.asarray(r.cpu_deltas, np.float32) for r in reports])
        rows = np.repeat(np.arange(n), lengths)
        starts = np.concatenate(([0], np.cumsum(lengths)[:-1]))
        cols = np.arange(total) - np.repeat(starts, lengths)
        out[rows, cols] = flat
    out[:n, lay.zone] = zone_deltas_mat
    out[:n, lay.zone_valid] = zone_valid_mat
    out[:n, lay.col_ratio] = np.fromiter(
        (r.usage_ratio for r in reports), np.float64, n)
    out[:n, lay.col_denom] = np.fromiter(
        (r.node_cpu_delta for r in reports), np.float64, n)
    out[:n, lay.col_dt] = np.fromiter(
        (r.dt_s for r in reports), np.float64, n)
    out[:n, lay.col_mode] = np.fromiter(
        (r.mode for r in reports), np.int64, n)


def _unpack_fields(packed: jax.Array, w: int, z: int) -> tuple[
        jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array,
        jax.Array, jax.Array]:
    lay = PackedLayout(w, z)
    cpu_nan = packed[:, lay.cpu]
    workload_valid = ~jnp.isnan(cpu_nan)
    cpu = jnp.where(workload_valid, cpu_nan, 0.0)
    zone = packed[:, lay.zone]
    zone_valid = packed[:, lay.zone_valid] > 0.5
    ratio = packed[:, lay.col_ratio]
    denom = packed[:, lay.col_denom]
    dt = packed[:, lay.col_dt]
    mode = packed[:, lay.col_mode].astype(jnp.int32)
    return cpu, workload_valid, zone, zone_valid, ratio, denom, dt, mode


def _pack_watts_f16(res: FleetResult) -> jax.Array:
    """FleetResult → one f16 [N, W+2, Z] output (one D2H), in watts."""
    watts = res.workload_power_uw * 1e-6  # µW → W for f16 range
    active = res.node_active_power_uw[:, None, :] * 1e-6
    total = res.node_power_uw[:, None, :] * 1e-6
    return jnp.concatenate([watts, active, total],
                           axis=1).astype(jnp.float16)


def _window_step_fns(mesh: Mesh, n_workloads: int, n_zones: int,
                     model_mode: str | None, backend: str,
                     model_bucket: int | None) -> tuple[
                         Callable, Callable | None]:
    """The shared UNJITTED packed window-step bodies → (dense, sparse).

    ``sparse`` is None unless ``model_bucket`` is set with a model mode
    (einsum backend required — the row-index gather has no shard story).
    Both the per-window packed builder (:func:`make_packed_fleet_program`)
    and the fused K-window scan builder (:func:`make_fused_window_program`)
    compose these same closures, so the two programs cannot drift."""
    predict_fn = predictor(model_mode) if model_mode else None
    if predict_fn is not None and model_mode != "linear" \
            and mesh.devices.flat[0].platform != "tpu":
        # bf16 trunks are an MXU throughput feature; off-TPU, bf16 is
        # emulated — measurably SLOWER than f32 and noisier. Serve f32
        # compute on CPU/GPU hosts (output dtype unchanged: the f16
        # packed wire format is the quantizer either way).
        base_fn = predict_fn

        def predict_fn(params: Any, feats: jax.Array, valid: jax.Array,
                       _fn: Callable = base_fn) -> jax.Array:
            return _fn(params, feats, valid, compute_dtype=jnp.float32)

    w, z = n_workloads, n_zones
    attribute_fn = resolve_attribute_fn(mesh, backend)
    sparse = model_bucket is not None and predict_fn is not None
    if sparse and backend != "einsum":
        raise ValueError(
            "sparse model evaluation (model_bucket) requires the einsum "
            f"backend; got {backend!r}")

    def unpack_and_attribute(model_params: Any,
                             packed: jax.Array) -> jax.Array:
        fields = _unpack_fields(packed, w, z)
        cpu, workload_valid, zone, zone_valid, ratio, denom, dt, mode = fields
        res = fleet_attribution_program(
            model_params, zone, zone_valid, ratio, cpu, workload_valid,
            denom, dt, mode, predict_fn=predict_fn,
            attribute_fn=attribute_fn)
        return _pack_watts_f16(res)

    def unpack_and_attribute_sparse(model_params: Any, packed: jax.Array,
                                    model_rows: jax.Array) -> jax.Array:
        from kepler_tpu.models.features import build_features

        fields = _unpack_fields(packed, w, z)
        cpu, workload_valid, zone, zone_valid, ratio, denom, dt, mode = fields
        ratio_res = attribute_fn(zone, zone_valid, ratio, cpu,
                                 workload_valid, denom, dt)
        sub_valid = workload_valid[model_rows]
        feats = build_features(cpu[model_rows], sub_valid,
                               denom[model_rows], ratio[model_rows],
                               dt[model_rows])
        sub_watts = predict_fn(model_params, feats, sub_valid)
        # padding entries (index N) drop on the scatter; MODE_RATIO rows
        # keep zeros here, which mix_model_watts' where() never selects
        model_watts = jnp.zeros(cpu.shape + (z,), jnp.float32).at[
            model_rows].set(sub_watts)
        return _pack_watts_f16(mix_model_watts(ratio_res, model_watts,
                                               mode, dt))

    return unpack_and_attribute, (unpack_and_attribute_sparse
                                  if sparse else None)


def make_packed_fleet_program(mesh: Mesh, n_workloads: int, n_zones: int,
                              model_mode: str | None = None,
                              backend: str = "einsum",
                              model_bucket: int | None = None,
                              local_model_rows: bool = False) -> Callable:
    """→ jitted ``packed_in [N, W+2Z+4] → packed_watts_f16 [N, W+2, Z]``.

    W and Z are static (they define the packing layout); N stays dynamic
    per compilation, sharded over the mesh's node axis.

    ``model_bucket``: when given (and ``model_mode`` is set), the program
    takes a third ``model_rows`` int32 [model_bucket] argument and
    evaluates the estimator ONLY on those rows (sparse mixed-fleet
    evaluation; see module docstring). Entries ≥ N are padding: the
    gather clamps them to a real row whose scatter-back is then dropped.

    ``local_model_rows``: SHARDED sparse evaluation for multi-device
    meshes. The replicated-``model_rows`` gather above has no shard
    story — GSPMD would all-gather the whole packed batch to satisfy
    arbitrary global indices. With ``local_model_rows`` the program runs
    under ``shard_map`` over the node axis: ``model_rows`` is int32
    [n_shards × model_bucket] sharded over ``node``, each shard's
    segment holding SHARD-LOCAL row indices (pad = the shard's local row
    count, gather-clamped / scatter-dropped per shard). The estimator
    gather, forward, and scatter-back all stay shard-local; the only
    cross-shard step left in a window is the caller's result fetch.
    """
    unpack_and_attribute, unpack_and_attribute_sparse = _window_step_fns(
        mesh, n_workloads, n_zones, model_mode, backend, model_bucket)
    sparse = unpack_and_attribute_sparse is not None
    if sparse and local_model_rows:
        from kepler_tpu.parallel.compat import shard_map

        # per-shard body: every array is the shard's LOCAL block, so the
        # pad/clamp/drop index space is the local row count and no
        # collective is ever emitted — XLA runs K independent partitions
        local = shard_map(
            unpack_and_attribute_sparse, mesh=mesh,
            in_specs=(P(), P(NODE_AXIS, None), P(NODE_AXIS)),
            out_specs=P(NODE_AXIS, None, None))
        return jax.jit(
            local,
            in_shardings=(NamedSharding(mesh, P()),
                          NamedSharding(mesh, P(NODE_AXIS, None)),
                          NamedSharding(mesh, P(NODE_AXIS))),
            out_shardings=NamedSharding(mesh, P(NODE_AXIS)),
        )
    if sparse:
        return jax.jit(
            unpack_and_attribute_sparse,
            in_shardings=(NamedSharding(mesh, P()),
                          NamedSharding(mesh, P(NODE_AXIS, None)),
                          NamedSharding(mesh, P())),
            out_shardings=NamedSharding(mesh, P(NODE_AXIS)),
        )
    fn = unpack_and_attribute
    if backend == "pallas":
        fn = shard_by_node(fn, mesh, in_specs=(P(), P(NODE_AXIS, None)))
    return jax.jit(
        fn,
        in_shardings=(NamedSharding(mesh, P()),
                      NamedSharding(mesh, P(NODE_AXIS, None))),
        out_shardings=NamedSharding(mesh, P(NODE_AXIS)),
    )


def make_fused_window_program(mesh: Mesh, n_workloads: int, n_zones: int,
                              model_mode: str | None = None,
                              backend: str = "einsum",
                              model_bucket: int | None = None) -> Callable:
    """→ jitted DEVICE-RESIDENT window loop: one dispatch per K windows.

    ``fused(params, resident, delta_rows, delta_idx[, model_rows])``:

      resident    f32 [N, width]      — DONATED packed resident block
      delta_rows  f32 [K, DB, width]  — per-interval staged delta rows
      delta_idx   i32 [K, DB]         — target rows (pad = N → dropped)
      model_rows  i32 [K, MB]         — sparse variant only (pad = N)

      → (resident' f32 [N, width], outs f16 [K, N, W+2, Z])

    One ``lax.scan`` applies each interval's delta rows to the resident
    block and runs the shared packed window body on the result — the
    host dispatches ONCE per K windows and the publish fetch
    materializes all K packed outputs in one transfer, amortizing the
    per-window host↔device sync floor K×. K and DB ride on the argument
    shapes (static per compilation, bucketed by the window engine).

    The resident block is donated (argnum 1): the scan carry aliases the
    input buffer, so the device never holds two fleet-sized residents
    and the caller must rebind its handle to the returned one.

    With ``backend="pallas"`` on a single-device mesh and no model, each
    scan step runs the fused mega-kernel
    (``ops.pallas_attribution.fused_window_step``): scatter + unpack +
    attribution in ONE kernel body. Everywhere else the step composes
    the drop-mode scatter with the shared window body and XLA fuses the
    pair per step (still one executable for the whole K-window batch).
    """
    dense_fn, sparse_fn = _window_step_fns(
        mesh, n_workloads, n_zones, model_mode, backend, model_bucket)
    repl = NamedSharding(mesh, P())
    by_node = NamedSharding(mesh, P(NODE_AXIS, None))
    out_shardings = (by_node, NamedSharding(mesh, P(None, NODE_AXIS)))

    if sparse_fn is not None:
        def fused_scan_sparse(model_params: Any, resident: jax.Array,
                              delta_rows: jax.Array, delta_idx: jax.Array,
                              model_rows: jax.Array) -> tuple[
                                  jax.Array, jax.Array]:
            def step(res, xs):
                rows, idx, mrows = xs
                res = res.at[idx].set(rows, mode="drop")
                return res, sparse_fn(model_params, res, mrows)

            return jax.lax.scan(step, resident,
                                (delta_rows, delta_idx, model_rows))

        return jax.jit(
            fused_scan_sparse,
            donate_argnums=(1,),
            in_shardings=(repl, by_node, repl, repl, repl),
            out_shardings=out_shardings,
        )

    lay = PackedLayout(n_workloads, n_zones)
    use_kernel = (backend == "pallas" and model_mode is None
                  and len(list(mesh.devices.flat)) == 1)
    if use_kernel:
        from kepler_tpu.ops.pallas_attribution import fused_window_step
        interpret = mesh.devices.flat[0].platform != "tpu"
    body_fn = dense_fn
    if backend == "pallas" and not use_kernel:
        # pallas_call has no SPMD rule: the per-step body runs per-shard
        # (the scatter stays outside — its indices are global row ids)
        body_fn = shard_by_node(dense_fn, mesh,
                                in_specs=(P(), P(NODE_AXIS, None)))

    def fused_scan(model_params: Any, resident: jax.Array,
                   delta_rows: jax.Array,
                   delta_idx: jax.Array) -> tuple[jax.Array, jax.Array]:
        def step(res, xs):
            rows, idx = xs
            if use_kernel:
                return fused_window_step(res, rows, idx, lay,
                                         interpret=interpret)
            res = res.at[idx].set(rows, mode="drop")
            return res, body_fn(model_params, res)

        return jax.lax.scan(step, resident, (delta_rows, delta_idx))

    # keep_unused: ratio mode (and the mega-kernel path) never reads
    # model_params, but pruning it would renumber the flat arguments and
    # detach the donate_argnums=(1,) contract from the resident block
    # (KTL121 checks declared vs realized donation by flat position)
    return jax.jit(
        fused_scan,
        donate_argnums=(1,),
        keep_unused=True,
        in_shardings=(repl, by_node, repl, repl),
        out_shardings=out_shardings,
    )


def _numpy_gelu(x: np.ndarray) -> np.ndarray:
    """jax.nn.gelu's default (tanh-approximate) formulation in NumPy."""
    c = np.float32(np.sqrt(2.0 / np.pi))
    return np.float32(0.5) * x * (
        np.float32(1.0) + np.tanh(c * (x + np.float32(0.044715) * x ** 3)))


def _numpy_features(cpu: np.ndarray, valid: np.ndarray, denom: np.ndarray,
                    ratio: np.ndarray, dt: np.ndarray) -> np.ndarray:
    """NumPy mirror of models.features.build_features → f32 [N, W, F]."""
    deltas = np.where(valid, cpu, 0.0).astype(np.float32)
    d = denom[:, None]
    share = np.where(d > 0.0, deltas / np.maximum(d, 1e-30), 0.0)
    dtc = dt[:, None]
    rate = np.where(dtc > 0.0, deltas / np.maximum(dtc, 1e-30), 0.0)
    w_shape = deltas.shape
    node_log = np.log1p(np.maximum(denom, 0.0))
    feats = np.stack([
        deltas,
        share,
        np.broadcast_to(ratio[:, None], w_shape),
        np.broadcast_to(dt[:, None], w_shape),
        rate,
        np.ones_like(deltas),
        np.broadcast_to(node_log[:, None], w_shape),
    ], axis=-1).astype(np.float32)
    return np.where(valid[..., None], feats, 0.0)


def _numpy_model_watts(model_mode: str, params: Any, feats: np.ndarray,
                       valid: np.ndarray) -> np.ndarray | None:
    """NumPy forward for the estimators the host rung can serve (linear,
    mlp — the shipped default). → watts f32 [N, W, Z], or None when the
    mode has no NumPy mirror (moe/deep; temporal never takes the packed
    path at all)."""
    if params is None:
        return None
    try:
        p = {k: np.asarray(v, np.float32) for k, v in dict(params).items()}
    except Exception:
        return None
    if model_mode == "linear":
        if "weight" not in p or "bias" not in p:
            return None
        watts = feats @ p["weight"] + p["bias"]
    elif model_mode == "mlp":
        if any(k not in p for k in ("w0", "b0", "w1", "b1", "w2", "b2",
                                    "w_skip")):
            return None
        h = _numpy_gelu(feats @ p["w0"] + p["b0"])
        h = _numpy_gelu(h @ p["w1"] + p["b1"])
        watts = h @ p["w2"] + feats @ p["w_skip"] + p["b2"]
    else:
        return None
    watts = np.maximum(watts.astype(np.float32), 0.0)
    return np.where(valid[..., None], watts, 0.0)


def numpy_fleet_window(packed: np.ndarray, n_workloads: int, n_zones: int,
                       params: Any = None,
                       model_mode: str | None = None) -> np.ndarray:
    """Pure-NumPy mirror of the packed fleet program — the aggregator's
    host-fallback rung (docs/developer/resilience.md "Device-plane
    faults"): same packed input layout in, same ``[N, W+2, Z]`` watts
    layout out (f32, not f16 — there is no wire-format quantizer to
    satisfy on host), touching no jax API at all so it keeps publishing
    with the device plane completely dead.

    Ratio-node attribution is exact (the same masked outer product the
    device program runs). Model rows are served for the NumPy-mirrored
    estimators (linear, mlp); modes without a host mirror (moe, deep)
    publish zero watts for their model rows — absence, not fabrication,
    and the ladder's health probe names the degraded rung.
    """
    w, z = n_workloads, n_zones
    lay = PackedLayout(w, z)
    cpu_nan = packed[:, lay.cpu]
    valid = ~np.isnan(cpu_nan)
    cpu = np.where(valid, cpu_nan, 0.0).astype(np.float32)
    zone = packed[:, lay.zone]
    zone_valid = packed[:, lay.zone_valid] > 0.5
    ratio = packed[:, lay.col_ratio]
    denom = packed[:, lay.col_denom]
    dt = packed[:, lay.col_dt]
    mode = packed[:, lay.col_mode].astype(np.int32)

    # node split (ops.attribution._node_split, NumPy)
    deltas = np.where(zone_valid, zone, 0.0).astype(np.float32)
    r = np.clip(ratio, 0.0, 1.0)[:, None]
    active = deltas * r
    dtc = dt[:, None]
    safe_dt = np.where(dtc > 0.0, dtc, 1.0)
    total_power_uw = np.where(dtc > 0.0, deltas / safe_dt, 0.0)
    active_power_uw = np.where(dtc > 0.0, active / safe_dt, 0.0)
    # workload ratios + the [W] ⊗ [Z] outer product, batched
    d = denom[:, None]
    ratios = np.where(d > 0.0,
                      cpu / np.maximum(d, 1e-30), 0.0).astype(np.float32)
    wl_power_uw = np.einsum("nw,nz->nwz", ratios, active_power_uw)

    node_active_w = active_power_uw * 1e-6  # µW → W (packed wire unit)
    node_total_w = total_power_uw * 1e-6
    wl_watts = wl_power_uw * 1e-6

    model_rows = np.flatnonzero(mode == MODE_MODEL)
    if model_rows.size and model_mode:
        feats = _numpy_features(cpu[model_rows], valid[model_rows],
                                denom[model_rows], ratio[model_rows],
                                dt[model_rows])
        watts = _numpy_model_watts(model_mode, params, feats,
                                   valid[model_rows])
        if watts is None:
            watts = np.zeros((model_rows.size, w, z), np.float32)
        wl_watts[model_rows] = watts
        est_node = watts.sum(axis=1)
        node_active_w[model_rows] = est_node
        node_total_w[model_rows] = est_node
    return np.concatenate(
        [wl_watts, node_active_w[:, None, :], node_total_w[:, None, :]],
        axis=1).astype(np.float32)


def unpack_fleet_watts(packed_watts: np.ndarray) -> tuple[np.ndarray,
                                                          np.ndarray]:
    """One D2H array → (workload_watts [N, W, Z], node_active_watts [N, Z])."""
    return packed_watts[:, :ROW_NODE_ACTIVE, :], \
        packed_watts[:, ROW_NODE_ACTIVE, :]


def unpack_fleet_window(packed_watts: np.ndarray) -> tuple[
        np.ndarray, np.ndarray, np.ndarray]:
    """One D2H array → (workload_watts [N, W, Z], node_active_watts [N, Z],
    node_total_watts [N, Z]) — the aggregator's scatter-back triple."""
    return (packed_watts[:, :ROW_NODE_ACTIVE, :],
            packed_watts[:, ROW_NODE_ACTIVE, :],
            packed_watts[:, ROW_NODE_TOTAL, :])
