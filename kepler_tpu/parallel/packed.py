"""Packed-transfer fleet attribution: one H2D, one dispatch, one D2H.

Motivation: on network-attached TPU (and over the dev tunnel this repo
benches through) every host↔device transfer pays a large fixed latency, so
a step that moves 9 input arrays and 2 outputs spends its p99 in round
trips, not compute. This module packs the whole fleet window into ONE f32
input array and the whole scatter-back payload into ONE f16 output array:

  input  [N, W + 2Z + 4]  — cpu | zone | zone_valid | ratio, denom, dt, mode
  output [N, W + 1, Z]    — per-workload watts, with node active watts as
                            the extra row (f16: watts stay well inside
                            half range and carry ~0.05% error, inside the
                            0.5%-of-RAPL budget; µW or µJ would overflow)

The unpack/slice lives inside the jitted program, so XLA fuses it with the
attribution math and the device sees exactly one executable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.parallel.aggregator_core import (
    fleet_attribution_program,
    resolve_attribute_fn,
    shard_by_node,
)
from kepler_tpu.parallel.fleet import FleetBatch
from kepler_tpu.parallel.mesh import NODE_AXIS
from kepler_tpu.models.estimator import predictor


def pack_fleet_inputs(batch: FleetBatch) -> np.ndarray:
    """FleetBatch → one f32 [N, W + 2Z + 4] host array (one H2D)."""
    n, w, z = batch.shape
    out = np.empty((n, w + 2 * z + 4), np.float32)
    # invalid workload slots ride as NaN in the cpu column — no separate
    # mask plane needed in the packed layout
    out[:, :w] = np.where(batch.workload_valid, batch.cpu_deltas, np.nan)
    out[:, w: w + z] = batch.zone_deltas_uj
    out[:, w + z: w + 2 * z] = batch.zone_valid
    out[:, w + 2 * z + 0] = batch.usage_ratio
    out[:, w + 2 * z + 1] = batch.node_cpu_delta
    out[:, w + 2 * z + 2] = batch.dt_s
    out[:, w + 2 * z + 3] = batch.mode
    return out


def make_packed_fleet_program(mesh: Mesh, n_workloads: int, n_zones: int,
                              model_mode: str | None = None,
                              backend: str = "einsum"):
    """→ jitted ``packed_in [N, W+2Z+4] → packed_watts_f16 [N, W+1, Z]``.

    W and Z are static (they define the packing layout); N stays dynamic
    per compilation, sharded over the mesh's node axis.
    """
    predict_fn = predictor(model_mode) if model_mode else None
    w, z = n_workloads, n_zones
    attribute_fn = resolve_attribute_fn(mesh, backend)

    def unpack_and_attribute(model_params, packed):
        cpu_nan = packed[:, :w]
        workload_valid = ~jnp.isnan(cpu_nan)
        cpu = jnp.where(workload_valid, cpu_nan, 0.0)
        zone = packed[:, w: w + z]
        zone_valid = packed[:, w + z: w + 2 * z] > 0.5
        ratio = packed[:, w + 2 * z + 0]
        denom = packed[:, w + 2 * z + 1]
        dt = packed[:, w + 2 * z + 2]
        mode = packed[:, w + 2 * z + 3].astype(jnp.int32)
        res = fleet_attribution_program(
            model_params, zone, zone_valid, ratio, cpu, workload_valid,
            denom, dt, mode, predict_fn=predict_fn,
            attribute_fn=attribute_fn)
        watts = res.workload_power_uw * 1e-6  # µW → W for f16 range
        node_watts = res.node_active_power_uw[:, None, :] * 1e-6
        return jnp.concatenate([watts, node_watts],
                               axis=1).astype(jnp.float16)

    fn = unpack_and_attribute
    if backend == "pallas":
        fn = shard_by_node(fn, mesh, in_specs=(P(), P(NODE_AXIS, None)))
    return jax.jit(
        fn,
        in_shardings=(NamedSharding(mesh, P()),
                      NamedSharding(mesh, P(NODE_AXIS, None))),
        out_shardings=NamedSharding(mesh, P(NODE_AXIS)),
    )


def unpack_fleet_watts(packed_watts: np.ndarray) -> tuple[np.ndarray,
                                                          np.ndarray]:
    """One D2H array → (workload_watts [N, W, Z], node_active_watts [N, Z])."""
    return packed_watts[:, :-1, :], packed_watts[:, -1, :]
