"""Pipeline parallelism: GPipe-style microbatch streaming over ``stage``.

The deep estimator (`kepler_tpu.models.deep`) is a stack of S identical
residual blocks; here the stack's leading axis shards over the ``stage``
mesh axis (one block — or S/n consecutive blocks — per device) and the
batch splits into M microbatches that stream through: each tick every
device applies its stage to the activation it holds, then ``ppermute``s
the result one hop down the ring. After ``M + S − 1`` ticks every
microbatch has crossed every stage — the classic GPipe schedule with its
S−1-tick bubble, expressed as a ``fori_loop`` inside one ``shard_map``
(the same shape as the scaling-book's shard_map pipeline recipe).

Inference-only by design: the training path already covers DP×TP
(`kepler_tpu.parallel.trainer`), and serving is where the fleet batch is
big enough for microbatching to pay.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.parallel.compat import pcast_varying, shard_map

STAGE_AXIS = "stage"


def _pp_shard(stage_params, x_mb, *, axis_name, stage_fn):
    """Per-device body. stage_params: local stage(s), leading axis S/n.
    x_mb [M, mB, D] microbatches (replicated; only stage 0 reads them)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def apply_local(params, x):
        # a device may own several consecutive blocks of the stack
        def body(x, block):
            return stage_fn(block, x), None

        out, _ = jax.lax.scan(body, x, params)
        return out

    def tick(t, carry):
        state, out = carry
        # stage 0 ingests microbatch t (garbage past M — masked at write)
        feed = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        x_in = jnp.where(idx == 0, feed, state)
        y = apply_local(stage_params, x_in)
        # last stage emits microbatch t-(n-1) once the bubble has drained
        oi = jnp.clip(t - (n - 1), 0, m - 1)
        valid = t >= (n - 1)
        prev = jax.lax.dynamic_index_in_dim(out, oi, 0, keepdims=False)
        out = jax.lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, y, prev), oi, 0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return state, out

    # zeros-initialised carries must be marked device-varying over the stage
    # axis up front or the fori_loop carry types mismatch (shard_map vma rule)
    state = pcast_varying(jnp.zeros_like(x_mb[0]), axis_name)
    out = pcast_varying(jnp.zeros_like(x_mb), axis_name)
    _, out = jax.lax.fori_loop(0, m + n - 1, tick, (state, out))
    # every stage wrote a buffer; only the last stage's is the answer —
    # zero the rest and psum so the result replicates
    out = out * (idx == n - 1)
    return jax.lax.psum(out, axis_name)


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable,  # (block_params_no_stage_axis, x [mB, D]) → [mB, D]
    *,
    axis_name: str = STAGE_AXIS,
    n_microbatches: int = 4,
):
    """→ jitted ``(stacked_stage_params, x [B, D]) → [B, D]``.

    ``stacked_stage_params``: pytree whose leaves have a leading stage axis
    S (divisible by the mesh's ``axis_name`` size). ``B`` must divide by
    ``n_microbatches``. Output equals applying the S stages sequentially.
    """
    stages = NamedSharding(mesh, P(axis_name))
    rep = NamedSharding(mesh, P())
    body = functools.partial(_pp_shard, axis_name=axis_name,
                             stage_fn=stage_fn)

    def fn(stage_params, x):
        b = x.shape[0]
        if b % n_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {n_microbatches} microbatches")
        x_mb = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])
        out = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=P(),
        )(stage_params, x_mb)
        return out.reshape(b, *x.shape[1:])

    return jax.jit(fn, in_shardings=(stages, rep), out_shardings=rep)


def make_pipelined_deep(
    mesh: Mesh,
    *,
    axis_name: str = STAGE_AXIS,
    n_microbatches: int = 4,
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """→ jitted ``(DeepParams, features [B, F], workload_valid [B]) → [B, Z]``.

    Embed and head run replicated outside the pipeline (one tiny matmul
    each); the S-block stack streams through the stage ring.
    """
    from kepler_tpu.models.deep import block_fn, embed, head

    pipeline = make_pipeline(
        mesh,
        functools.partial(block_fn, compute_dtype=compute_dtype),
        axis_name=axis_name, n_microbatches=n_microbatches)
    stages = NamedSharding(mesh, P(axis_name))
    rep = NamedSharding(mesh, P())
    shardings = dict(in_proj=rep, in_bias=rep, w_head=rep, b_head=rep,
                     w_skip=rep,
                     blocks=jax.tree.map(lambda _: stages,
                                         dict(ln_scale=0, ln_bias=0, w0=0,
                                              b0=0, w1=0, b1=0)))

    def fn(params, features, workload_valid):
        x = embed(params, features, compute_dtype)
        x = pipeline(params["blocks"], x)
        return head(params, x, workload_valid, clamp, features=features)

    return jax.jit(fn, in_shardings=(shardings, rep, rep),
                   out_shardings=rep)
