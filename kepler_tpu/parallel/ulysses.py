"""Ulysses-style (all-to-all) sequence parallelism for attention.

The second classic context-parallel scheme beside the ring
(`kepler_tpu.parallel.ring`): instead of rotating K/V blocks around the
mesh, one ``all_to_all`` re-partitions the sharded SEQUENCE axis into a
sharded HEAD axis — each device then runs ordinary dense attention over
the FULL sequence for its subset of heads, and a second ``all_to_all``
restores sequence sharding (DeepSpeed-Ulysses; see PAPERS.md).

Trade-offs vs the ring, as a selection guide:

- Ulysses moves ``O(T·D)`` activations twice per layer through two
  all_to_alls and then attends densely — ONE exchange, latency-bound;
  the ring moves K/V ``P−1`` times in ``P`` overlap-able steps —
  bandwidth-spread, and never materializes full-T anything per device.
- Ulysses parallelism degree is capped by the head count (H must divide
  by the mesh axis; the temporal model has 4 heads); the ring scales to
  any T-divisor.
- Per-device attention memory: Ulysses holds full T for H/P heads
  (``O(T²·H/P)`` scores unless fused); the ring holds one T/P block
  pair at a time.

Both plug into the SAME ``attention_fn`` seam of the temporal trunk and
are verified equivalent to dense single-device attention (and to each
other) in ``tests/test_ulysses.py`` / ``tests/test_ring.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kepler_tpu.ops.attention import full_attention
from kepler_tpu.parallel.compat import shard_map
from kepler_tpu.parallel.ring import SEQ_AXIS


def _ulysses_shard(q, k, v, t_valid, *, axis_name: str, causal: bool,
                   compute_dtype) -> jax.Array:
    """Per-shard body: [B, T/P, H, Dh] in/out, full-T attention inside."""
    # time-gather / head-scatter: [B, T/P, H, Dh] → [B, T, H/P, Dh]
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    tv = lax.all_gather(t_valid, axis_name, axis=1, tiled=True)  # [B, T]
    out = full_attention(qg, kg, vg, causal=causal, t_valid=tv,
                         compute_dtype=compute_dtype)
    # head-gather / time-scatter back: [B, T, H/P, Dh] → [B, T/P, H, Dh]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_shardmap(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """Un-jitted shard-mapped Ulysses kernel ``(q, k, v, t_valid) → out``.

    The composable form (same contract as
    :func:`~kepler_tpu.parallel.ring.ring_attention_shardmap`): inputs
    ``[B, T, H, Dh]`` with T sharded over ``axis_name``; H must divide
    by the mesh's ``axis_name`` size.
    """
    n = mesh.shape[axis_name]
    body = functools.partial(_ulysses_shard, axis_name=axis_name,
                             causal=causal, compute_dtype=compute_dtype)

    def checked(q, k, v, t_valid):
        if q.shape[2] % n:
            raise ValueError(
                f"Ulysses needs heads ({q.shape[2]}) divisible by the "
                f"'{axis_name}' mesh size ({n}); use the ring for more "
                "parallelism than heads")
        return shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, axis_name), P(None, axis_name),
                      P(None, axis_name), P(None, axis_name)),
            out_specs=P(None, axis_name),
        )(q, k, v, t_valid)

    return checked


def make_ulysses_attention(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """→ jitted ``(q, k, v, t_valid) → out`` with T sharded over the mesh
    and heads re-partitioned internally via all_to_all."""
    seq = NamedSharding(mesh, P(None, axis_name))
    shard = ulysses_attention_shardmap(mesh, axis_name=axis_name,
                                       causal=causal,
                                       compute_dtype=compute_dtype)
    return jax.jit(shard, in_shardings=(seq, seq, seq, seq),
                   out_shardings=seq)


def make_ulysses_temporal_program(
    mesh: Mesh,
    *,
    axis_name: str = SEQ_AXIS,
    clamp: bool = True,
    compute_dtype: jnp.dtype = jnp.bfloat16,
):
    """Temporal estimator served with Ulysses context parallelism —
    the all-to-all twin of ``sequence.make_temporal_program``."""
    from kepler_tpu.models.temporal import predict_temporal

    hist = NamedSharding(mesh, P(None, axis_name))
    rep = NamedSharding(mesh, P())
    attn = ulysses_attention_shardmap(mesh, axis_name=axis_name,
                                      causal=True,
                                      compute_dtype=compute_dtype)

    def fn(params, feat_hist, workload_valid, t_valid):
        return predict_temporal(params, feat_hist, workload_valid, t_valid,
                                clamp=clamp, compute_dtype=compute_dtype,
                                attention_fn=attn)

    return jax.jit(fn, in_shardings=(rep, hist, rep, hist),
                   out_shardings=rep)
